"""PipelineModule partitioning/tied-weight tests — reference
tests/unit/test_pipe_module.py pattern."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec)
from tests.unit.simple_model import make_stack_specs


def _build(n_layers=8, tied=False, **kw):
    specs, loss_fn, input_fn = make_stack_specs(8, n_layers, tied_head=tied)
    module = PipelineModule(specs, loss_fn=loss_fn, input_fn=input_fn, **kw)
    batch = {"x": np.ones((4, 8), np.float32),
             "y": np.zeros((4,), np.int32)}
    params = module.init(jax.random.PRNGKey(0), batch)
    return module, params, batch


def test_init_params_keys():
    module, params, _ = _build(n_layers=3)
    # 3 stack layers + head, no tied
    assert sorted(params.keys()) == [f"layer_{i:02d}" for i in range(4)]


def test_tied_params_shared():
    module, params, _ = _build(n_layers=3, tied=True)
    assert "tied_emb" in params
    # 3 middle + head own params; the two tied layers share one entry
    assert len(params) == 5
    counts = module._param_counts
    # second tied occurrence contributes 0 (owner carries the weight)
    assert counts[0] > 0 and counts[4] == 0


def test_partition_uniform():
    module, _, _ = _build(n_layers=6, partition_method="uniform")
    parts = module.partition_layers(num_stages=2)
    assert parts[0] == 0 and parts[-1] == 7  # 6 stack + head
    assert len(parts) == 3


def test_partition_parameters_balanced():
    module, _, _ = _build(n_layers=7, partition_method="parameters")
    parts = module.partition_layers(num_stages=4)
    assert parts[0] == 0 and parts[-1] == 8
    assert all(parts[i] < parts[i + 1] for i in range(4))


def test_partition_type_regex():
    module, _, _ = _build(n_layers=6, partition_method="type:DenseTanh")
    parts = module.partition_layers(num_stages=3)
    # only DenseTanh layers carry weight; boundaries still cover all layers
    assert parts[0] == 0 and parts[-1] == 7


def test_partition_unknown_method():
    module, _, _ = _build(partition_method="nonsense")
    with pytest.raises(KeyError):
        module.partition_layers(num_stages=2)


def test_stage_param_keys_disjoint_cover():
    module, params, _ = _build(n_layers=6)
    module.num_stages = 3
    all_keys = []
    for s in range(3):
        all_keys += module.stage_param_keys(s)
    assert sorted(all_keys) == sorted(params.keys())


def test_tied_groups():
    module, params, _ = _build(n_layers=6, tied=True,
                               partition_method="uniform")
    groups = module.tied_groups(num_stages=4)
    # first and last tied layer land on different stages
    assert "emb" in groups and len(groups["emb"]) == 2


def test_forward_full_matches_stagewise():
    module, params, batch = _build(n_layers=6, partition_method="uniform")
    module.num_stages = 3
    rng = jax.random.PRNGKey(1)
    full = module.forward_full(params, batch, rng, train=False)
    x = module.input_fn(batch)
    for s in range(3):
        x = module.forward_stage(params, x, s, rng, train=False)
    np.testing.assert_allclose(np.asarray(full), np.asarray(x), rtol=1e-6)


def test_loss_runs():
    module, params, batch = _build(n_layers=2)
    loss, metrics = module.loss(params, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_activation_checkpoint_interval_same_output():
    module, params, batch = _build(n_layers=6)
    module.activation_checkpoint_interval = 2
    rng = jax.random.PRNGKey(1)
    ckpt = module.forward_full(params, batch, rng, train=True)
    module.activation_checkpoint_interval = 0
    plain = module.forward_full(params, batch, rng, train=True)
    np.testing.assert_allclose(np.asarray(ckpt), np.asarray(plain), rtol=1e-6)


def test_remat_grads_match():
    """Grad equality with/without activation checkpointing (the reference
    test_activation_checkpointing round-trip property)."""
    module, params, batch = _build(n_layers=4)
    rng = jax.random.PRNGKey(1)

    def loss_of(params, interval):
        module.activation_checkpoint_interval = interval
        out = module.forward_full(params, batch, rng, train=True)
        return module.loss_fn(out, batch)[0]

    g_plain = jax.grad(lambda p: loss_of(p, 0))(params)
    g_ckpt = jax.grad(lambda p: loss_of(p, 2))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_plain),
                    jax.tree_util.tree_leaves(g_ckpt)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-7)


def test_layerspec_forward_fn():
    """LayerSpec.forward_fn: custom apply WITHOUT weight tying (the
    TiedLayerSpec contract, now on plain layers too — e.g. an untied LM
    head)."""
    import flax.linen as nn

    class Lin(nn.Module):
        feats: int = 8

        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(self.feats, name="lin")(x)

    calls = []

    def doubled(module, params, x):
        calls.append(type(module).__name__)
        return module.apply({"params": params}, x) * 2.0

    specs = [LayerSpec(Lin), LayerSpec(Lin, forward_fn=doubled)]
    module = PipelineModule(specs, loss_fn=lambda o, b: (o.sum(), {}))
    batch = {"x": np.ones((2, 8), np.float32)}
    params = module.init(jax.random.PRNGKey(0), batch)
    assert sorted(params) == ["layer_00", "layer_01"]  # NOT tied
    base = module._layers[1].obj.apply(
        {"params": params["layer_01"]},
        module._layers[0].obj.apply({"params": params["layer_00"]},
                                    batch["x"]))
    out = module.forward_full(params, batch, jax.random.PRNGKey(1),
                              train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base) * 2.0,
                               rtol=1e-6)
    assert "Lin" in calls


def test_validate_chunking_and_tied_introspection():
    module, _, _ = _build(n_layers=7)           # 8 layers, untied
    assert module.validate_chunking(2, 2) is None
    why = module.validate_chunking(2, 3)
    assert "divisible" in why and "8" in why
    assert not module.has_tied_layers()
    tied_mod, _, _ = _build(n_layers=3, tied=True)
    assert tied_mod.has_tied_layers()


def test_gpt2_untied_head_matches_tied_shapes():
    """The untied GPT-2 head owns its own wte with the tied head's shape
    (zb-h1 uses this variant)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module

    cfg = GPT2Config(vocab_size=64, n_positions=16, n_embd=8, n_layer=2,
                     n_head=2, dtype=jnp.float32)
    tied = gpt2_pipeline_module(cfg)
    untied = gpt2_pipeline_module(cfg, untied_head=True)
    assert tied.has_tied_layers() and not untied.has_tied_layers()
    batch = {"input_ids": np.zeros((2, 16), np.int64),
             "labels": np.zeros((2, 16), np.int64)}
    pt = tied.init(jax.random.PRNGKey(0), batch)
    pu = untied.init(jax.random.PRNGKey(0), batch)
    head_key = f"layer_{len(untied._layers) - 1:02d}"
    assert pu[head_key]["wte"].shape == pt["tied_embed"]["wte"].shape


def test_layerspec_repr():
    spec = LayerSpec(dict)
    assert "dict" in repr(spec)


def test_same_shaped_layers_init_differently():
    """Regression: with seed_layers=False (the default) every layer used to
    fold in 0, so all same-shaped layers initialized with identical weights
    (symmetric init degrades training and dropout cannot break it)."""
    module, params, _ = _build(n_layers=3)
    l0 = jax.tree_util.tree_leaves(params["layer_00"])
    l1 = jax.tree_util.tree_leaves(params["layer_01"])
    assert any(a.shape == b.shape and not np.allclose(a, b)
               for a, b in zip(l0, l1)), \
        "same-shaped pipeline layers must not share init weights"


def test_seed_layers_reproducible_independent_of_rng():
    """seed_layers=True pins each layer's init to base_seed+index: the same
    weights come out regardless of the engine rng (reference module.py:85)."""
    _, p_a, _ = _build(n_layers=3, seed_layers=True, base_seed=7)
    specs, loss_fn, input_fn = make_stack_specs(8, 3)
    module_b = PipelineModule(specs, loss_fn=loss_fn, input_fn=input_fn,
                              seed_layers=True, base_seed=7)
    batch = {"x": np.ones((4, 8), np.float32), "y": np.zeros((4,), np.int32)}
    p_b = module_b.init(jax.random.PRNGKey(999), batch)  # different rng
    for a, b in zip(jax.tree_util.tree_leaves(p_a),
                    jax.tree_util.tree_leaves(p_b)):
        np.testing.assert_allclose(a, b)
    # and distinct layers still differ
    l0 = jax.tree_util.tree_leaves(p_a["layer_00"])
    l1 = jax.tree_util.tree_leaves(p_a["layer_01"])
    assert any(a.shape == b.shape and not np.allclose(a, b)
               for a, b in zip(l0, l1))
