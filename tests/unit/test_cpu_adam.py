"""CPU Adam (ZeRO-Offload) tests — reference tests/unit/test_cpu_adam.py
pattern: the native kernel vs an independent Adam implementation, plus the
engine's offload flow end-to-end."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam


def torch_style_adam(p, g, m, v, step, lr, beta1, beta2, eps, wd, adamw):
    """Independent reference (torch.optim.Adam/AdamW semantics), float64."""
    p, g, m, v = (x.astype(np.float64) for x in (p, g, m, v))
    if not adamw and wd > 0:
        g = g + wd * p
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    mh = m / (1 - beta1 ** step)
    vh = v / (1 - beta2 ** step)
    upd = mh / (np.sqrt(vh) + eps)
    if adamw and wd > 0:
        upd = upd + wd * p
    return p - lr * upd, m, v


@pytest.mark.parametrize("n", [7, 64, 1000, 4099])  # odd sizes hit SIMD tails
@pytest.mark.parametrize("adamw,wd", [(True, 0.01), (False, 0.01),
                                      (True, 0.0)])
def test_cpu_adam_matches_reference(n, adamw, wd):
    rng = np.random.default_rng(0)
    opt = DeepSpeedCPUAdam(lr=0.01, weight_decay=wd, adamw_mode=adamw)
    p = rng.standard_normal(n).astype(np.float32)
    params = {"w": p.copy()}
    state = opt.init_state(params)
    leaves = [np.ascontiguousarray(p.copy())]

    p_ref = p.copy()
    m_ref = np.zeros(n)
    v_ref = np.zeros(n)
    for step in range(1, 6):
        g = rng.standard_normal(n).astype(np.float32)
        opt.step(leaves, [g], state)
        p_ref, m_ref, v_ref = torch_style_adam(
            p_ref, g, m_ref, v_ref, step, 0.01, 0.9, 0.999, 1e-8, wd, adamw)
        np.testing.assert_allclose(leaves[0], p_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(state["m"][0], m_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(state["v"][0], v_ref, rtol=1e-5, atol=1e-6)


def test_cpu_adam_grad_scale_fused_unscale():
    rng = np.random.default_rng(1)
    n = 256
    p0 = rng.standard_normal(n).astype(np.float32)
    g = rng.standard_normal(n).astype(np.float32)

    opt1 = DeepSpeedCPUAdam(lr=0.01)
    s1 = opt1.init_state({"w": p0})
    l1 = [np.ascontiguousarray(p0.copy())]
    opt1.step(l1, [g * 128.0], s1, grad_scale=128.0)

    opt2 = DeepSpeedCPUAdam(lr=0.01)
    s2 = opt2.init_state({"w": p0})
    l2 = [np.ascontiguousarray(p0.copy())]
    opt2.step(l2, [g], s2)
    np.testing.assert_allclose(l1[0], l2[0], rtol=1e-5, atol=1e-6)


def test_native_matches_numpy_fallback():
    opt_native = DeepSpeedCPUAdam(lr=0.02, weight_decay=0.01)
    if not opt_native.using_native:
        pytest.skip("no native toolchain")
    opt_np = DeepSpeedCPUAdam(lr=0.02, weight_decay=0.01)
    opt_np._lib = None
    rng = np.random.default_rng(2)
    p0 = rng.standard_normal(513).astype(np.float32)
    l1 = [np.ascontiguousarray(p0.copy())]
    l2 = [np.ascontiguousarray(p0.copy())]
    s1 = opt_native.init_state({"w": p0})
    s2 = opt_np.init_state({"w": p0})
    for _ in range(4):
        g = rng.standard_normal(513).astype(np.float32)
        opt_native.step(l1, [g], s1)
        opt_np.step(l2, [g], s2)
    np.testing.assert_allclose(l1[0], l2[0], rtol=1e-5, atol=1e-6)


def test_bf16_cast_round_to_nearest_even():
    opt = DeepSpeedCPUAdam()
    x = np.asarray([1.0, 1.0 + 2 ** -8, -3.14159, 65504.0, 1e-40],
                   np.float32)
    out = opt.cast_to([x], "bfloat16")[0]
    import ml_dtypes

    exp = x.astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(out.view(np.uint16), exp.view(np.uint16))


def test_engine_offload_e2e():
    """cpu_offload config: fp32 master+moments on host, loss decreases,
    results match the non-offload engine."""
    import deepspeed_tpu
    from tests.unit.simple_model import SimpleModel, random_dataloader

    def run(offload):
        model = SimpleModel(hidden_dim=16)
        cfg = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
            "zero_optimization": {"stage": 2, "cpu_offload": offload},
            "steps_per_print": 100,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                   config_params=cfg)
        data = random_dataloader(16, 64, 8, seed=0)
        losses = []
        for _ in range(8):
            batch = next(data)
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        return engine, losses

    _, base = run(offload=False)
    engine, off = run(offload=True)
    assert engine._offload
    assert np.isfinite(off).all() and off[-1] < off[0]
    np.testing.assert_allclose(base, off, rtol=2e-3, atol=1e-4)


def test_engine_offload_checkpoint_roundtrip(tmp_path):
    import deepspeed_tpu
    from tests.unit.simple_model import SimpleModel, random_dataloader

    def make():
        model = SimpleModel(hidden_dim=16)
        cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
               "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
               "zero_optimization": {"stage": 2, "cpu_offload": True},
               "steps_per_print": 100}
        engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                   config_params=cfg)
        return engine

    engine = make()
    data = random_dataloader(16, 64, 8, seed=0)
    for _ in range(3):
        batch = next(data)
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(str(tmp_path), tag="o1")

    engine2 = make()
    batch = next(data)
    loss = engine2(batch)
    engine2.backward(loss)
    engine2.step()
    engine2.load_checkpoint(str(tmp_path), tag="o1")
    for a, b in zip(engine._host_master_flat, engine2._host_master_flat):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(engine._host_opt["m"], engine2._host_opt["m"]):
        np.testing.assert_array_equal(a, b)
    assert engine2._host_opt["step"] == engine._host_opt["step"]

    # both continue identically
    batch = next(data)
    l1 = float(jax.device_get(engine(batch)))
    engine.backward(l1)
    l2 = float(jax.device_get(engine2(batch)))
    engine2.backward(l2)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_engine_offload_fp16_overflow_skips():
    import deepspeed_tpu
    from tests.unit.simple_model import SimpleModel

    model = SimpleModel(hidden_dim=16)
    cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
           "fp16": {"enabled": True, "initial_scale_power": 4},
           "zero_optimization": {"stage": 2, "cpu_offload": True},
           "steps_per_print": 100}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config_params=cfg)
    rng = np.random.default_rng(0)
    batch = {"x": rng.standard_normal((8, 16)).astype(np.float32),
             "y": rng.integers(0, 4, (8,)).astype(np.int32)}
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    scale0 = float(jax.device_get(engine.state.scaler.loss_scale))
    # poison batches to force overflow; default hysteresis (delayed_shift=2)
    # halves the scale only on the SECOND consecutive overflow
    bad = {"x": np.full((8, 16), np.inf, np.float32),
           "y": np.zeros((8,), np.int32)}
    loss = engine(bad)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 1
    assert float(jax.device_get(engine.state.scaler.loss_scale)) == scale0
    loss = engine(bad)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 2
    scale1 = float(jax.device_get(engine.state.scaler.loss_scale))
    assert scale1 <= scale0 / 2


def test_engine_offload_gas_accumulation_matches():
    """gas=4: per-micro gradients stream to host asynchronously and
    accumulate there (no device accumulator at all — state.accum is empty);
    the trajectory must match the on-device engine."""
    import deepspeed_tpu
    from tests.unit.simple_model import SimpleModel, random_dataloader

    def run(offload):
        model = SimpleModel(hidden_dim=16)
        cfg = {
            "train_batch_size": 64,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
            "zero_optimization": {"stage": 2, "cpu_offload": offload},
            "steps_per_print": 100,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                   config_params=cfg)
        data = random_dataloader(16, 256, 16, seed=0)
        losses = [float(jax.device_get(engine.train_batch(data_iter=data)))
                  for _ in range(4)]
        return engine, losses

    _, base = run(offload=False)
    engine, off = run(offload=True)
    assert engine.state.accum == ()  # the freed device accumulator
    assert np.isfinite(off).all() and off[-1] < off[0]
    np.testing.assert_allclose(base, off, rtol=2e-3, atol=1e-4)
