"""graftlint framework + rule-catalog tests.

Three layers:
1. framework — registry, suppression comments, baseline add/expire
   semantics, fingerprint stability, reporters, CLI exit codes;
2. rules — every AST rule class has known-bad fixture snippets it fires
   on and known-good (fixed) twins it stays quiet on (the acceptance
   criterion for each rule class);
3. repo — the full rule set over the real tree is exercised by
   tests/unit/test_lint_guards.py (tier-1), not here, so this file stays
   jax-free and fast.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from tools.graftlint import core  # noqa: E402
from tools.graftlint.core import (REGISTRY, load_baseline, run_paths,  # noqa: E402
                                  run_source, save_baseline)

EXPECTED_RULES = {"bare-except", "donated-state", "host-sync",
                  "rank-branch-collective", "disarmed-discipline",
                  "raw-ckpt-write"}


def lint(src, path="deepspeed_tpu/x.py", rules=None):
    picked = None if rules is None else [REGISTRY[r] for r in rules]
    return run_source(src, path, rules=picked)


def rule_names(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# framework
# ---------------------------------------------------------------------------

def test_registry_catalog():
    assert EXPECTED_RULES <= set(REGISTRY)
    for name, rule in REGISTRY.items():
        assert rule.name == name and rule.description


def test_syntax_error_surfaces_as_finding():
    got = lint("def f(:\n")
    assert len(got) == 1 and got[0].rule == "syntax"


def test_findings_sorted_and_formatted():
    src = ("try:\n    x()\nexcept:\n    raise ValueError()\n"
           "try:\n    y()\nexcept Exception:\n    pass\n")
    got = lint(src)
    assert [f.line for f in got] == sorted(f.line for f in got)
    assert got[0].format().startswith("deepspeed_tpu/x.py:3: [bare-except]")


def test_suppression_same_line_prev_line_and_wrong_rule():
    base = "try:\n    x()\nexcept:{}\n    raise ValueError()\n"
    assert rule_names(lint(base.format(""))) == ["bare-except"]
    assert lint(base.format("  # graftlint: disable=bare-except")) == []
    # suppression on the PRECEDING line (wrapped statements)
    src = ("try:\n    x()\n# graftlint: disable=bare-except\nexcept:\n"
           "    raise ValueError()\n")
    assert lint(src) == []
    # a different rule's token does not suppress
    assert rule_names(lint(base.format(
        "  # graftlint: disable=host-sync"))) == ["bare-except"]
    # disable=all suppresses any rule
    assert lint(base.format("  # graftlint: disable=all")) == []


def test_rule_scoping_by_path():
    src = ("class E:\n"
           "    def _arm_x(self):\n"
           "        self._x_armed = True\n")
    assert rule_names(lint(src, "deepspeed_tpu/runtime/foo.py")) \
        == ["disarmed-discipline"]
    # the discipline is an engine-source contract, not a test-file one
    assert lint(src, "tests/unit/test_foo.py") == []


def _write(tmp, rel, text):
    p = os.path.join(tmp, rel)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "w", encoding="utf-8") as f:
        f.write(text)
    return p


BAD_FILE = "def f():\n    try:\n        g()\n    except:\n        raise V()\n"
GOOD_FILE = "def f():\n    g()\n"


def test_baseline_add_then_expire(tmp_path):
    tmp = str(tmp_path)
    baseline = os.path.join(tmp, "baseline.json")
    _write(tmp, "pkg/mod.py", BAD_FILE)

    r1 = run_paths(roots=("pkg",), baseline_path=baseline, repo_root=tmp)
    assert len(r1.new) == 1 and not r1.baselined and not r1.stale
    assert r1.exit_code == 1

    save_baseline(r1, path=baseline, notes={
        fp: "intentional fixture" for fp in r1.fingerprints})
    r2 = run_paths(roots=("pkg",), baseline_path=baseline, repo_root=tmp)
    assert not r2.new and len(r2.baselined) == 1 and not r2.stale
    assert r2.exit_code == 0
    entry = load_baseline(baseline)["entries"][0]
    assert entry["note"] == "intentional fixture"
    assert entry["rule"] == "bare-except"

    # fix the violation: the entry goes stale, lint still passes, and a
    # baseline update prunes it
    _write(tmp, "pkg/mod.py", GOOD_FILE)
    r3 = run_paths(roots=("pkg",), baseline_path=baseline, repo_root=tmp)
    assert not r3.new and not r3.baselined and len(r3.stale) == 1
    assert r3.exit_code == 0
    save_baseline(r3, path=baseline)
    assert load_baseline(baseline)["entries"] == []


def test_baseline_fingerprint_survives_line_moves(tmp_path):
    tmp = str(tmp_path)
    baseline = os.path.join(tmp, "baseline.json")
    _write(tmp, "pkg/mod.py", BAD_FILE)
    r1 = run_paths(roots=("pkg",), baseline_path=baseline, repo_root=tmp)
    save_baseline(r1, path=baseline)
    # shift the violation down two lines: same text -> same fingerprint
    _write(tmp, "pkg/mod.py", "\n\n" + BAD_FILE)
    r2 = run_paths(roots=("pkg",), baseline_path=baseline, repo_root=tmp)
    assert not r2.new and len(r2.baselined) == 1 and not r2.stale


def test_scoped_baseline_update_preserves_out_of_scope(tmp_path):
    """A scoped run (subset of roots or rules) must neither report
    out-of-coverage baseline entries as stale nor delete them on a
    baseline update — the baseline is a whole-repo artifact."""
    tmp = str(tmp_path)
    baseline = os.path.join(tmp, "b.json")
    _write(tmp, "a/f.py", BAD_FILE)
    _write(tmp, "b/g.py", BAD_FILE)
    r_full = run_paths(roots=("a", "b"), baseline_path=baseline,
                       repo_root=tmp)
    save_baseline(r_full, path=baseline,
                  notes={fp: "keep" for fp in r_full.fingerprints})
    assert len(load_baseline(baseline)["entries"]) == 2

    # root-scoped: b/ is out of coverage — not stale, survives the update
    r_a = run_paths(roots=("a",), baseline_path=baseline, repo_root=tmp)
    assert not r_a.new and not r_a.stale
    save_baseline(r_a, path=baseline)
    entries = load_baseline(baseline)["entries"]
    assert {e["path"] for e in entries} == {"a/f.py", "b/g.py"}
    assert all(e["note"] == "keep" for e in entries)

    # rule-scoped: bare-except entries are out of coverage for host-sync
    r_rule = run_paths(roots=("a", "b"), rules=[REGISTRY["host-sync"]],
                       baseline_path=baseline, repo_root=tmp)
    assert not r_rule.stale
    save_baseline(r_rule, path=baseline)
    assert len(load_baseline(baseline)["entries"]) == 2


def test_run_paths_skips_pycache(tmp_path):
    tmp = str(tmp_path)
    _write(tmp, "pkg/__pycache__/junk.py", BAD_FILE)
    _write(tmp, "pkg/ok.py", GOOD_FILE)
    r = run_paths(roots=("pkg",), repo_root=tmp, use_baseline=False)
    assert not r.new


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _cli(*args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.graftlint", *args],
                          cwd=cwd, capture_output=True, text=True,
                          timeout=120)


def test_cli_clean_dir_exits_zero():
    proc = _cli("tools/graftlint", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["summary"]["new"] == 0
    assert set(EXPECTED_RULES) <= set(payload["rules"])


def test_cli_new_finding_exits_nonzero(tmp_path):
    bad = _write(str(tmp_path), "bad.py", BAD_FILE)
    proc = _cli(bad, "--no-baseline")
    assert proc.returncode == 1
    assert "[bare-except]" in proc.stdout


def test_cli_json_shape_on_findings(tmp_path):
    bad = _write(str(tmp_path), "bad.py", BAD_FILE)
    proc = _cli(bad, "--no-baseline", "--json")
    payload = json.loads(proc.stdout)
    assert payload["summary"]["new"] == 1
    f = payload["new"][0]
    assert f["rule"] == "bare-except" and f["line"] == 4 and f["message"]


def test_cli_baseline_update_roundtrip(tmp_path):
    tmp = str(tmp_path)
    bad = _write(tmp, "bad.py", BAD_FILE)
    baseline = os.path.join(tmp, "b.json")
    assert _cli(bad, "--baseline", baseline).returncode == 1
    assert _cli(bad, "--baseline", baseline,
                "--baseline-update").returncode == 0
    assert _cli(bad, "--baseline", baseline).returncode == 0
    assert _cli(bad, "--baseline", baseline,
                "--strict-stale").returncode == 0
    _write(tmp, "bad.py", GOOD_FILE)
    assert _cli(bad, "--baseline", baseline).returncode == 0
    assert _cli(bad, "--baseline", baseline,
                "--strict-stale").returncode == 1


def test_cli_strict_stale_composes_with_baseline_update(tmp_path):
    """ISSUE 19 satellite bugfix: --strict-stale --baseline-update must
    BOTH prune the stale entries AND exit 1 in the same run — before,
    --baseline-update returned 0 unconditionally, so a CI job asking to
    prune-and-flag saw the prune but never the flag (exit code and
    prune disagreed)."""
    tmp = str(tmp_path)
    bad = _write(tmp, "bad.py", BAD_FILE)
    baseline = os.path.join(tmp, "b.json")
    assert _cli(bad, "--baseline", baseline,
                "--baseline-update").returncode == 0
    assert len(load_baseline(baseline)["entries"]) == 1

    _write(tmp, "bad.py", GOOD_FILE)   # the finding is fixed -> stale
    # plain --strict-stale: flags the drift, does NOT prune
    assert _cli(bad, "--baseline", baseline,
                "--strict-stale").returncode == 1
    assert len(load_baseline(baseline)["entries"]) == 1
    # composed: prunes AND still exits 1 — one CI invocation sees both
    proc = _cli(bad, "--baseline", baseline,
                "--strict-stale", "--baseline-update")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "1 stale pruned" in proc.stdout
    assert load_baseline(baseline)["entries"] == []
    # pruned baseline, nothing stale left: the same invocation is clean
    assert _cli(bad, "--baseline", baseline,
                "--strict-stale", "--baseline-update").returncode == 0


def test_nonexistent_root_raises_not_empty_scan(tmp_path):
    """A missing root must error, not silently scan nothing — an empty
    scan feeding --baseline-update would wipe the baseline."""
    with pytest.raises(FileNotFoundError, match="no_such_dir"):
        run_paths(roots=("no_such_dir",), repo_root=str(tmp_path),
                  use_baseline=False)
    proc = _cli("no_such_dir_anywhere")
    assert proc.returncode == 2 and "not found" in proc.stderr


def test_cli_relative_roots_resolve_from_user_cwd(tmp_path):
    """`python -m tools.graftlint mydir` from any cwd lints that dir."""
    tmp = str(tmp_path)
    _write(tmp, "mydir/f.py", BAD_FILE)
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "mydir", "--no-baseline"],
        cwd=tmp, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[bare-except]" in proc.stdout


def test_cli_rule_subset_and_unknown():
    assert _cli("--list-rules").returncode == 0
    proc = _cli("tools/graftlint", "--rules", "bare-except")
    assert proc.returncode == 0
    assert _cli("--rules", "no-such-rule").returncode == 2


def test_legacy_shim_still_works():
    """Satellite: tools/check_no_bare_except.py survives as a shim — same
    CLI, same check_source API (exercised by test_lint_guards.py)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_no_bare_except.py"),
         "tools/graftlint"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# rule: donated-state
# ---------------------------------------------------------------------------

DONATION_BAD = """
def t(engine, np, b):
    p0 = engine.state.params["w1"]
    engine.train_batch(batch=b)
    return np.sum(p0)
"""

DONATION_GOOD_MATERIALIZED = """
def t(engine, np, b, jax):
    p0 = jax.device_get(engine.state.params["w1"])
    engine.train_batch(batch=b)
    return np.sum(p0)
"""

DONATION_GOOD_REREAD = """
def t(engine, np, b):
    engine.train_batch(batch=b)
    return np.sum(engine.state.params["w1"])
"""

DONATION_GOOD_REBOUND = """
def t(engine, np, b):
    p0 = engine.state.params["w1"]
    engine.train_batch(batch=b)
    p0 = engine.state.params["w1"]
    return np.sum(p0)
"""

DONATION_BAD_STAGE = """
def t(engine, b):
    acc = engine.stage_states[0].accum
    engine.train_batch(batch=b)
    return acc
"""


def test_donated_state_fires_on_held_leaf():
    got = lint(DONATION_BAD, "tests/unit/t.py", rules=["donated-state"])
    assert rule_names(got) == ["donated-state"] and got[0].line == 5
    assert "donated" in got[0].message


def test_donated_state_quiet_on_fixes():
    for src in (DONATION_GOOD_MATERIALIZED, DONATION_GOOD_REREAD,
                DONATION_GOOD_REBOUND):
        assert lint(src, "tests/unit/t.py", rules=["donated-state"]) == [], src


def test_donated_state_tracks_stage_states():
    got = lint(DONATION_BAD_STAGE, "deepspeed_tpu/runtime/x.py",
               rules=["donated-state"])
    assert rule_names(got) == ["donated-state"]


def test_donated_state_use_before_step_is_fine():
    src = ("def t(engine, np, b):\n"
           "    p0 = engine.state.params\n"
           "    s = np.sum(p0)\n"
           "    engine.step()\n"
           "    return s\n")
    assert lint(src, "tests/unit/t.py", rules=["donated-state"]) == []


# ---------------------------------------------------------------------------
# rule: host-sync
# ---------------------------------------------------------------------------

HS_TRACED_BAD = """
import jax
import numpy as np
def micro(state, batch):
    return float(np.asarray(state.accum))
fn = jax.jit(micro)
"""

HS_TRACED_GOOD = """
import jax
import jax.numpy as jnp
def micro(state, batch):
    return jnp.asarray(state.accum)
fn = jax.jit(micro)
"""

HS_FACTORY_BAD = """
def _make_micro_fn(self):
    def micro(state, batch):
        return jax.device_get(state.accum)
    return micro
"""

HS_HOT_LOOP_BAD = """
class E:
    def train_batch(self, micros):
        for m in micros:
            loss = self._jit(m)
            total += float(jax.device_get(loss))
        return total
"""

HS_HOT_LOOP_GOOD = """
class E:
    def train_batch(self, micros):
        losses = []
        for m in micros:
            losses.append(self._jit(m))
        return float(np.sum(jax.device_get(losses)))
"""


def test_host_sync_fires_in_traced_fn():
    got = lint(HS_TRACED_BAD, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]
    assert "traced" in got[0].message


def test_host_sync_quiet_on_jnp_in_traced_fn():
    assert lint(HS_TRACED_GOOD, rules=["host-sync"]) == []


def test_host_sync_fires_in_make_factory_defs():
    got = lint(HS_FACTORY_BAD, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]


HS_PLAN_BUILDER_BAD = """
class E:
    def train_batch(self, batch):
        plan = build_gather_plan(self._names, self._shapes, self._dims, 8)
        return self._jit(batch, plan)
"""

HS_PLAN_BUILDER_GOOD = """
class E:
    def _arm_stage3(self, stage, dp):
        self._s3_plan = build_gather_plan(self._names, self._shapes,
                                          self._dims, dp)
        if not self._s3_plan.blocks:
            log_dist("stage-3 DISARMED - nothing partitionable")

    def train_batch(self, batch):
        return self._jit(batch, self._s3_plan)
"""


def test_host_sync_flags_plan_builder_in_hot_fn():
    """ISSUE 8 satellite: the stage-3 gather-plan builder (O(param-leaves)
    host work) is flagged ANYWHERE inside a hot step-driving function —
    not just in loops — and quiet when built once at arming time."""
    path = "deepspeed_tpu/runtime/engine.py"
    got = lint(HS_PLAN_BUILDER_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]
    assert "arming time" in got[0].message
    assert lint(HS_PLAN_BUILDER_GOOD, path, rules=["host-sync"]) == []
    # the bar applies to the engine files' hot fns only: a cold caller
    # (or a non-engine file) builds plans freely
    assert lint(HS_PLAN_BUILDER_BAD, "tools/somefile.py",
                rules=["host-sync"]) == []


@pytest.mark.parametrize("path", ["deepspeed_tpu/runtime/engine.py",
                                  "deepspeed_tpu/runtime/pipe/engine.py",
                                  "bench.py", "tools/pipe_bench.py",
                                  "tools/serve_bench.py"])
def test_host_sync_fires_in_hot_loop(path):
    got = lint(HS_HOT_LOOP_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], path
    assert "per-iteration loop" in got[0].message


HS_SERVING_BAD = """
class InferenceEngine:
    def step(self):
        for slot, req in self.scheduler.running.items():
            tok = int(jax.device_get(self._nxt[slot]))
            req.generated.append(tok)
"""

HS_SERVING_GOOD = """
class InferenceEngine:
    def step(self):
        out = self._decode(self.params, self._tables)
        toks = np.asarray(jax.device_get(out))
        for slot, req in self.scheduler.running.items():
            req.generated.append(int(toks[slot]))
"""


@pytest.mark.parametrize("path", ["deepspeed_tpu/serving/engine.py",
                                  "deepspeed_tpu/serving/scheduler.py"])
def test_host_sync_serving_per_token_fetch_is_an_error(path):
    """PR-5 satellite: the serving hot paths are held to the training
    engines' bar — a per-slot/per-token device_get in the step loop
    fires; ONE batched fetch after dispatch is the blessed idiom."""
    got = lint(HS_SERVING_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], path
    assert lint(HS_SERVING_GOOD, path, rules=["host-sync"]) == []


HS_RELIABILITY_BAD = """
class InferenceEngine:
    def _enforce_deadlines(self, events):
        now = self.clock()
        for req in list(self.scheduler.requests.values()):
            if float(jax.device_get(req.deadline_arr)) < now:
                self._abort(req, "expired", events)
"""

HS_RELIABILITY_GOOD = """
class InferenceEngine:
    def _enforce_deadlines(self, events):
        now = self.clock()
        for req in list(self.scheduler.requests.values()):
            if req.deadline is not None and now > req.deadline:
                self._abort(req, "expired", events)

    def recover(self, journal_path):
        entries = RequestJournal.replay(journal_path)
        return [self.submit(e["prompt"], e["max_new"]) for e in entries]

    def drain(self):
        while self.scheduler.in_flight():
            self.step()
        return self.results
"""

HS_RECOVER_BAD = """
class InferenceEngine:
    def recover(self, journal_path):
        rids = []
        for e in RequestJournal.replay(journal_path):
            rids.append(self.submit(e["prompt"], e["max_new"]))
            jax.device_get(self.pool.tensors.k)
        return rids
"""

HS_DRAIN_BAD = """
class InferenceEngine:
    def drain(self):
        while self.scheduler.in_flight():
            self.step()
            self.pool.tensors.k.block_until_ready()
        return self.results
"""


@pytest.mark.parametrize("src,label", [
    (HS_RELIABILITY_BAD, "_enforce_deadlines"),
    (HS_RECOVER_BAD, "recover"),
    (HS_DRAIN_BAD, "drain"),
])
@pytest.mark.parametrize("path", ["deepspeed_tpu/serving/engine.py",
                                  "deepspeed_tpu/serving/reliability.py"])
def test_host_sync_covers_serving_reliability_hot_fns(src, label, path):
    """ISSUE 9 satellite: the reliability layer's step-boundary fns
    (deadline sweep, journal replay/recovery, drain loop) are held to
    the hot-path bar — a per-request/per-step device sync fires."""
    got = lint(src, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], (label, path)


HS_FLEET_BAD = """
class FleetRouter:
    def step(self):
        for rep in self.replicas:
            rep.engine.step()
            jax.device_get(rep.engine.pool.tensors.k)
"""

HS_FLEET_MIGRATE_BAD = """
class FleetRouter:
    def _migrate(self, rep, events):
        for e in RequestJournal.replay(rep.journal_path):
            target = self._place(len(e["prompt"]), exclude=rep)
            target.engine.submit(e["prompt"], e["max_new"])
            target.engine.pool.tensors.k.block_until_ready()
"""

HS_FLEET_GOOD = """
class FleetRouter:
    def step(self):
        events = {"failures": []}
        for rep in self.replicas:
            self._step_replica(rep, events)
        return events

    def _handoff_tick(self, rep, events):
        req = min(rep.engine.scheduler.running.values(),
                  key=lambda r: r.submit_seq)
        entry = rep.engine.export_request(req.rid)
        target = self._place(0, decode_target=True, exclude=rep)
        target.engine.import_request(entry)

    def _migrate(self, rep, events):
        for e in RequestJournal.replay(rep.journal_path):
            target = self._place(len(e["prompt"]), exclude=rep)
            target.engine.submit(e["prompt"], e["max_new"])
"""


@pytest.mark.parametrize("src,label", [
    (HS_FLEET_BAD, "step"),
    (HS_FLEET_MIGRATE_BAD, "_migrate"),
])
def test_host_sync_covers_fleet_router_hot_fns(src, label):
    """ISSUE 11 satellite: the fleet router's step loop and migration
    path are hot — a device sync per replica/request there serializes
    the whole fleet against the host."""
    got = lint(src, "deepspeed_tpu/serving/fleet.py", rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], label


def test_host_sync_quiet_on_fleet_straight_line_handoff():
    # per-replica stepping through a helper, a straight-line handoff
    # (the ONE blessed device touch) and a sync-free migration loop:
    # no findings
    assert lint(HS_FLEET_GOOD, "deepspeed_tpu/serving/fleet.py",
                rules=["host-sync"]) == []


HS_SUPERVISOR_TICK_BAD = """
class TrainingSupervisor:
    def _heartbeat_tick(self, w):
        stale, dead = [], []
        for h in self.hosts:
            h.tick(w)
            lag = float(jax.device_get(self.engine.state.step)) - h.last_beat
            if lag > self.config.heartbeat_timeout_steps:
                dead.append(h.rank)
        return stale, dead
"""

HS_SUPERVISOR_ROLLBACK_BAD = """
class TrainingSupervisor:
    def _rollback(self, reason):
        for _attempt in range(self.config.max_recovery_attempts):
            _path, client = self.engine.load_checkpoint(
                self.save_dir, tag=self.last_committed_tag, elastic=True)
            for leaf in jax.tree_util.tree_leaves(self.engine.state.params):
                leaf.block_until_ready()
"""

HS_SUPERVISOR_GOOD = """
class TrainingSupervisor:
    def tick(self):
        self.wall_step += 1
        stale, dead = self._heartbeat_tick(self.wall_step)
        if dead and self._verdict(dead, self.wall_step):
            self._elastic_restart(dead)
            return
        self.supervised_step()

    def _heartbeat_tick(self, w):
        stale, dead = [], []
        for h in self.hosts:
            h.tick(w)
            lag = w - h.last_beat
            if lag > self.config.heartbeat_timeout_steps:
                dead.append(h.rank)
            elif lag > 0:
                stale.append(h.rank)
        return stale, dead

    def _rollback(self, reason):
        for _attempt in range(self.config.max_recovery_attempts):
            _path, client = self.engine.load_checkpoint(
                self.save_dir, tag=self.last_committed_tag, elastic=True)
            self._reseat_data(client)
"""


@pytest.mark.parametrize("src,label", [
    (HS_SUPERVISOR_TICK_BAD, "_heartbeat_tick"),
    (HS_SUPERVISOR_ROLLBACK_BAD, "_rollback"),
])
def test_host_sync_covers_supervisor_hot_fns(src, label):
    """ISSUE 12 satellite: the training supervisor's detection tick and
    recovery paths are hot — a device sync per simulated host (or per
    state leaf mid-rollback) would serialize every wall step, failure
    or not, against the host."""
    got = lint(src, "deepspeed_tpu/runtime/resilience/supervisor.py",
               rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], label


def test_host_sync_quiet_on_supervisor_host_only_loop():
    # the real shape: pure host heartbeat bookkeeping and recovery
    # retries that touch the device only through the engine's own
    # load/init entry points — no findings
    assert lint(HS_SUPERVISOR_GOOD,
                "deepspeed_tpu/runtime/resilience/supervisor.py",
                rules=["host-sync"]) == []


HS_INTEGRITY_VOTE_BAD = """
class IntegrityMonitor:
    def state_vote(self, engine):
        digests = []
        for leaf in jax.tree_util.tree_leaves(engine.state.params):
            digests.append(int(jax.device_get(fold(leaf))))
        return digests
"""

HS_INTEGRITY_OBSERVE_BAD = """
class IntegrityMonitor:
    def observe_step(self, step, metrics):
        zs = {}
        for name, value in metrics.items():
            zs[name] = self.stats[name].z(float(jax.device_get(value)))
        return zs
"""

HS_INTEGRITY_GOOD = """
def state_vote(engine):
    with jax.set_mesh(engine.mesh):
        table = engine._integrity._vote_jit(tuple(leaves))
    rows = np.asarray(jax.device_get(table), dtype=np.int64)
    return classify_digests(rows)


class IntegrityMonitor:
    def observe_step(self, step, loss=None, grad_norm=None,
                     update_ratio=None, overflow=False):
        samples = {"loss": loss, "grad_norm": grad_norm,
                   "update_ratio": update_ratio}
        zs = {}
        for n, v in samples.items():
            if v is not None:
                zs[n] = self.stats[n].z(v)
        return any(z > self.config.z_threshold for z in zs.values())
"""


@pytest.mark.parametrize("src,label", [
    (HS_INTEGRITY_VOTE_BAD, "per-leaf digest fetch"),
    (HS_INTEGRITY_OBSERVE_BAD, "per-sentinel device fetch"),
])
def test_host_sync_covers_integrity_hot_fns(src, label):
    """ISSUE 13 satellite: the integrity monitor's per-step observe and
    the vote entry points are hot — the sentinel values must RIDE the
    engine's one batched fetch, and a vote may fetch its digest table
    exactly once (straight-line); a per-leaf/per-sentinel device_get
    loop serializes the state against the host every step."""
    got = lint(src, "deepspeed_tpu/runtime/resilience/integrity.py",
               rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], label


def test_host_sync_quiet_on_integrity_batched_fetch():
    # the real shape: ONE straight-line device_get of the gathered
    # digest table per vote, pure host float math in observe_step
    assert lint(HS_INTEGRITY_GOOD,
                "deepspeed_tpu/runtime/resilience/integrity.py",
                rules=["host-sync"]) == []


def test_host_sync_quiet_on_host_only_reliability_fns():
    # the real implementations are pure host accounting: clock reads,
    # dict walks, journal appends — no findings
    assert lint(HS_RELIABILITY_GOOD, "deepspeed_tpu/serving/engine.py",
                rules=["host-sync"]) == []
    assert lint(HS_RELIABILITY_GOOD,
                "deepspeed_tpu/serving/reliability.py",
                rules=["host-sync"]) == []


def test_host_sync_quiet_on_batched_fetch_after_loop():
    assert lint(HS_HOT_LOOP_GOOD, "deepspeed_tpu/runtime/engine.py",
                rules=["host-sync"]) == []


def test_host_sync_hot_loop_scoped_to_hot_files():
    # the same loop in an arbitrary module is host-side code, not a
    # schedule hot path — only the traced-fn context applies there
    assert lint(HS_HOT_LOOP_BAD, "deepspeed_tpu/utils/foo.py",
                rules=["host-sync"]) == []


def test_host_sync_comprehension_counts_as_loop():
    src = ("class E:\n"
           "    def eval_batch(self, losses, np, jax):\n"
           "        return float(np.mean([float(jax.device_get(l)) "
           "for l in losses]))\n")
    got = lint(src, "deepspeed_tpu/runtime/pipe/engine.py",
               rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]


# ---------------------------------------------------------------------------
# rule: rank-branch-collective
# ---------------------------------------------------------------------------

SPMD_BAD = """
def body(x, jax):
    if jax.lax.axis_index("data") == 0:
        x = jax.lax.psum(x, "data")
    return x
"""

SPMD_BAD_ELSE = """
def body(x, jax):
    if jax.lax.axis_index("data") == 0:
        pass
    else:
        x = jax.lax.all_gather(x, "data")
    return x
"""

SPMD_BAD_HOST = """
def save(jax, mu, payload):
    if jax.process_index() == 0:
        return mu.process_allgather(payload)
"""

SPMD_GOOD = """
def body(x, jax, jnp):
    y = jax.lax.psum(x, "data")
    return jnp.where(jax.lax.axis_index("data") == 0, y, x)
"""

SPMD_GOOD_UNIFORM_GUARD = """
def save(jax, mu, payload):
    if jax.process_count() > 1:
        return mu.process_allgather(payload)
    return payload
"""


def test_rank_branch_collective_fires():
    got = lint(SPMD_BAD, rules=["rank-branch-collective"])
    assert rule_names(got) == ["rank-branch-collective"]
    assert "psum" in got[0].message and "deadlock" in got[0].message


def test_rank_branch_collective_fires_in_else_arm():
    got = lint(SPMD_BAD_ELSE, rules=["rank-branch-collective"])
    assert rule_names(got) == ["rank-branch-collective"]


def test_rank_branch_host_barrier_fires():
    got = lint(SPMD_BAD_HOST, rules=["rank-branch-collective"])
    assert rule_names(got) == ["rank-branch-collective"]


def test_rank_branch_collective_quiet_on_fixes():
    assert lint(SPMD_GOOD, rules=["rank-branch-collective"]) == []
    # process_count is uniform across ranks: not a divergence hazard
    assert lint(SPMD_GOOD_UNIFORM_GUARD,
                rules=["rank-branch-collective"]) == []


SPMD_BAD_QUANT_WIRE = """
def exchange(grads, jax, cc, mesh):
    if jax.lax.axis_index("data") == 0:
        grads = cc.quantized_all_reduce(grads, "data", bits=1)
    g = cc.quantized_all_gather(grads, mesh)
    if jax.process_index() == 0:
        g = cc.quantized_reduce_scatter(g, "data")
    return g
"""

SPMD_GOOD_QUANT_WIRE = """
def exchange(grads, jax, cc, mesh):
    grads = cc.quantized_all_reduce(grads, "data", bits=1)
    return cc.quantized_all_gather(grads, mesh)
"""

SPMD_BAD_TRANSPORT_BARRIER = """
def monitor(self, jax, wall_step):
    if jax.process_index() == 0:
        self.transport.heartbeat_tick(wall_step)
        return self.transport.vote_dead((), wall_step)
    return ()
"""

SPMD_GOOD_TRANSPORT_BARRIER = """
def monitor(self, jax, wall_step):
    self.transport.heartbeat_tick(wall_step)
    dead = self.transport.vote_dead((), wall_step)
    if jax.process_index() == 0:
        log_dead(dead)
    return dead
"""

SPMD_GOOD_SUBMIT_NOT_A_BARRIER = """
def admit(self, jax, prompt):
    if jax.process_index() == 0:
        return self.engine.submit(prompt, max_new_tokens=8)
"""


def test_rank_branch_quantized_collectives_fire():
    """ISSUE 19 satellite: the PR-18 quantized wire collectives are
    rank-gated deadlocks like their dense counterparts — all three
    custom ops under a rank branch fire; unconditional use is quiet."""
    got = lint(SPMD_BAD_QUANT_WIRE, rules=["rank-branch-collective"])
    assert rule_names(got) == ["rank-branch-collective"] * 2
    assert "quantized_all_reduce" in got[0].message
    assert "quantized_reduce_scatter" in got[1].message
    assert lint(SPMD_GOOD_QUANT_WIRE,
                rules=["rank-branch-collective"]) == []


def test_rank_branch_transport_barriers_fire():
    """ISSUE 19 satellite: transport-level quorum barriers
    (heartbeat_tick / vote_dead) wedge exactly like device collectives
    when only rank 0 posts them; running the round on every peer and
    rank-gating the LOGGING is the quiet twin.  serving's submit() is
    an unrelated name and must never fire."""
    got = lint(SPMD_BAD_TRANSPORT_BARRIER,
               rules=["rank-branch-collective"])
    assert rule_names(got) == ["rank-branch-collective"] * 2
    assert "heartbeat_tick" in got[0].message
    assert "vote_dead" in got[1].message
    assert lint(SPMD_GOOD_TRANSPORT_BARRIER,
                rules=["rank-branch-collective"]) == []
    assert lint(SPMD_GOOD_SUBMIT_NOT_A_BARRIER,
                rules=["rank-branch-collective"]) == []


# ---------------------------------------------------------------------------
# rule: disarmed-discipline
# ---------------------------------------------------------------------------

DISARM_BAD = """
class E:
    def _arm_thing(self):
        self._thing_armed = False
        if self.config.thing and self.dp > 1:
            self._thing_armed = True
"""

DISARM_GOOD = DISARM_BAD + """
        elif self.config.thing:
            log_dist("thing DISARMED — requires dp > 1",
                     ranks=[0], level=logging.WARNING)
"""

DISARM_BAD_ATTR_ONLY = """
class E:
    def configure(self):
        self._wire_armed = self.dp > 1
"""


def test_disarmed_discipline_fires_without_warning_path():
    got = lint(DISARM_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"] and got[0].line == 3
    assert "DISARMED" in got[0].message


def test_disarmed_discipline_quiet_with_warning():
    assert lint(DISARM_GOOD, rules=["disarmed-discipline"]) == []


def test_disarmed_discipline_catches_armed_attr_outside_arm_fns():
    got = lint(DISARM_BAD_ATTR_ONLY, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]


DISARM_S3_BAD = """
class E:
    def _arm_stage3(self, stage, dp, params_template):
        self._s3_sched_armed = stage == 3 and dp > 1
"""

DISARM_S3_GOOD = DISARM_S3_BAD + """
        if stage == 3 and not self._s3_sched_armed:
            log_dist("ZeRO stage-3: scheduled gathers DISARMED - dp is 1",
                     ranks=[0], level=logging.WARNING)
"""


def test_disarmed_discipline_covers_arm_stage3_path():
    """ISSUE 8 satellite: the new _arm_stage3_* arming path is held to
    the same discipline — fire without a DISARMED branch, quiet with."""
    got = lint(DISARM_S3_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_stage3" in got[0].message
    assert lint(DISARM_S3_GOOD, rules=["disarmed-discipline"]) == []


DISARM_SHED_BAD = """
class Reliability:
    def _arm_shedding(self):
        self.shedding_armed = self.config.slo_ttft_s is not None \\
            and self.engine.scheduler.policy == "continuous"
"""

DISARM_SHED_GOOD = DISARM_SHED_BAD + """
        if self.config.slo_ttft_s is not None and not self.shedding_armed:
            logger.warning("SLO shedding DISARMED - policy '%s' gates "
                           "admission on batch membership",
                           self.engine.scheduler.policy)
"""


def test_disarmed_discipline_covers_arm_shedding_path():
    """ISSUE 9 satellite: the serving overload guard's arming fn is
    held to the armed-or-warns discipline — an _arm_shedding that can
    silently leave the gate off fires; warning DISARMED quiets it."""
    got = lint(DISARM_SHED_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_shedding" in got[0].message
    assert lint(DISARM_SHED_GOOD, rules=["disarmed-discipline"]) == []


DISARM_DISPATCH_BAD = """
class FleetRouter:
    def _arm_dispatch(self):
        self.dispatch_armed = self.config.dispatch == "slo" and all(
            r.engine.scheduler.policy == "continuous"
            for r in self.replicas)
"""

DISARM_DISPATCH_GOOD = DISARM_DISPATCH_BAD + """
        if self.config.dispatch == "slo" and not self.dispatch_armed:
            logger.warning("SLO-aware dispatch DISARMED - a replica "
                           "policy the TTFT model cannot describe; "
                           "falling back to round-robin")
"""


def test_disarmed_discipline_covers_arm_dispatch_path():
    """ISSUE 11 satellite: the fleet router's placement arming fn is
    held to the armed-or-warns discipline — a silent round-robin
    fallback fires; warning DISARMED quiets it."""
    got = lint(DISARM_DISPATCH_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_dispatch" in got[0].message
    assert lint(DISARM_DISPATCH_GOOD, rules=["disarmed-discipline"]) == []


DISARM_SUPERVISOR_BAD = """
class DeepSpeedEngine:
    def _arm_supervisor(self, supervisor):
        if not supervisor.save_dir or not self._resilience.atomic_checkpoints:
            self._supervisor = None
            return False
        self._supervisor = supervisor
        return True
"""

DISARM_SUPERVISOR_GOOD = """
class DeepSpeedEngine:
    def _arm_supervisor(self, supervisor):
        if not supervisor.save_dir or not self._resilience.atomic_checkpoints:
            self._supervisor = None
            log_dist("self-healing supervision DISARMED - no committed-"
                     "tag directory / atomic commits off; steps run "
                     "unsupervised", ranks=[0], level=logging.WARNING)
            return False
        self._supervisor = supervisor
        return True
"""


def test_disarmed_discipline_covers_arm_supervisor_path():
    """ISSUE 12 satellite: the engine's supervision arming fn is held to
    the armed-or-warns discipline — silently refusing to supervise (no
    retry/rollback/elastic restart, run dies on the first fault) fires;
    warning DISARMED naming the blockers quiets it."""
    got = lint(DISARM_SUPERVISOR_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_supervisor" in got[0].message
    assert lint(DISARM_SUPERVISOR_GOOD, rules=["disarmed-discipline"]) == []


DISARM_INTEGRITY_BAD = """
class DeepSpeedEngine:
    def _arm_integrity(self):
        self._integrity = None
        if not self._resilience.integrity_enabled:
            return
        if self._offload or self._onebit_wire():
            return
        self._integrity = IntegrityMonitor(cfg, self.dp_world_size)
"""

DISARM_INTEGRITY_GOOD = """
class DeepSpeedEngine:
    def _arm_integrity(self):
        self._integrity = None
        if not self._resilience.integrity_enabled:
            return
        if self._offload or self._onebit_wire():
            log_dist("numerical-integrity defense DISARMED - "
                     "cpu_offload / 1-bit wire leave no device-resident "
                     "replicated state to vote over", ranks=[0],
                     level=logging.WARNING)
            return
        self._integrity = IntegrityMonitor(cfg, self.dp_world_size)
"""


def test_disarmed_discipline_covers_arm_integrity_path():
    """ISSUE 13 satellite: the integrity arming fn is held to the
    armed-or-warns discipline — silently skipping the defense (silent
    corruption then sails past every detector) fires; warning DISARMED
    naming the blockers quiets it."""
    got = lint(DISARM_INTEGRITY_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_integrity" in got[0].message
    assert lint(DISARM_INTEGRITY_GOOD, rules=["disarmed-discipline"]) == []


DISARM_AUTOSCALE_BAD = """
class FleetRouter:
    def _arm_autoscale(self, spec):
        self.autoscale_armed = False
        self._autoscale = None
        if spec is None:
            return
        if self._role_split or spec.min_replicas < 1:
            return
        self._autoscale = spec
        self.autoscale_armed = True
"""

DISARM_AUTOSCALE_GOOD = DISARM_AUTOSCALE_BAD.replace(
    "            return\n        self._autoscale = spec",
    '            logger.warning(\n'
    '                "fleet autoscaler: DISARMED - role-split fleet / "\n'
    '                "invalid replica bounds; the replica set stays "\n'
    '                "fixed")\n'
    "            return\n        self._autoscale = spec")


def test_disarmed_discipline_covers_arm_autoscale_path():
    """ISSUE 16 satellite: the router's autoscale arming fn is held to
    the armed-or-warns discipline — a fleet that silently never scales
    (the user asked for elasticity, provisioning stays frozen) fires;
    warning DISARMED naming the blockers quiets it."""
    got = lint(DISARM_AUTOSCALE_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_autoscale" in got[0].message
    assert lint(DISARM_AUTOSCALE_GOOD, rules=["disarmed-discipline"]) == []


DISARM_TRANSPORT_BAD = """
class FleetRouter:
    def _arm_transport(self, transport):
        self._transport = None
        self.transport_armed = False
        if transport is None:
            return
        if transport.world != len(self.replicas) + 1:
            return
        self._transport = transport.start()
        self.transport_armed = True
"""

DISARM_TRANSPORT_GOOD = DISARM_TRANSPORT_BAD.replace(
    "            return\n        self._transport = transport.start()",
    '            logger.warning(\n'
    '                "fleet transport: DISARMED - world does not map "\n'
    '                "onto the replica set; replica liveness stays "\n'
    '                "in-process")\n'
    "            return\n        self._transport = transport.start()")


def test_disarmed_discipline_covers_arm_transport_path():
    """ISSUE 16 satellite: the transport-seam arming fn is held to the
    armed-or-warns discipline — silently falling back to in-process
    liveness (peer death then goes undetected at the process level)
    fires; warning DISARMED naming the blockers quiets it."""
    got = lint(DISARM_TRANSPORT_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_transport" in got[0].message
    assert lint(DISARM_TRANSPORT_GOOD, rules=["disarmed-discipline"]) == []


# ---------------------------------------------------------------------------
# rule: raw-ckpt-write
# ---------------------------------------------------------------------------

RUNTIME_PATH = "deepspeed_tpu/runtime/somefile.py"

CKPT_BAD_OPEN = """
def write_side_metadata(path, meta):
    with open(path, "w") as f:
        json.dump(meta, f)
"""

CKPT_BAD_SAVEZ = """
def stash_state(path, arrays):
    np.savez(path, **arrays)
"""

CKPT_BAD_HASHED_OUTSIDE_COMMIT = """
def sneaky(path, arrays):
    savez_hashed(path, **arrays)
"""

CKPT_BAD_RENAME = """
def my_own_atomic_commit(tmp, final):
    os.replace(tmp, final)
"""

CKPT_GOOD_COMMIT_WRITER = """
def _write_snapshot_files(path, snap):
    fname = os.path.join(path, "model_states.npz")
    np.savez(fname, **snap["arrays"])
    chaos.file_written(fname)
    mpath = os.path.join(path, "metadata.pkl")
    with open(mpath, "wb") as f:
        pickle.dump(snap["meta"], f)
    chaos.file_written(mpath)
"""

CKPT_GOOD_READS_AND_LOOKALIKES = """
def harmless(path, d, s):
    with open(path) as f:
        data = f.read()
    with open(path, "rb") as f:
        more = f.read()
    d2 = d.copy()            # dict.copy, not shutil.copy
    s2 = s.replace("a", "b")  # str.replace, not os.replace
    arr = np.load(path)
    return data, more, d2, s2, arr
"""


def test_raw_ckpt_write_fires_on_each_writer_kind():
    for src, kind in ((CKPT_BAD_OPEN, "open"),
                      (CKPT_BAD_SAVEZ, "np.savez"),
                      (CKPT_BAD_HASHED_OUTSIDE_COMMIT, "savez_hashed"),
                      (CKPT_BAD_RENAME, "os.replace")):
        got = lint(src, path=RUNTIME_PATH, rules=["raw-ckpt-write"])
        assert got and got[0].rule == "raw-ckpt-write", kind
        assert "atomic commit path" in got[0].message
    # the bad open fixture flags both the open and the json.dump
    got = lint(CKPT_BAD_OPEN, path=RUNTIME_PATH, rules=["raw-ckpt-write"])
    assert len(got) == 2


def test_raw_ckpt_write_quiet_in_chaos_hooked_commit_writer():
    """The payload-writer discipline: writes that feed chaos.file_written
    are commit-path writes (kill-mid-write tests cover them)."""
    assert lint(CKPT_GOOD_COMMIT_WRITER, path=RUNTIME_PATH,
                rules=["raw-ckpt-write"]) == []


def test_raw_ckpt_write_quiet_on_reads_and_lookalikes():
    assert lint(CKPT_GOOD_READS_AND_LOOKALIKES, path=RUNTIME_PATH,
                rules=["raw-ckpt-write"]) == []


def test_raw_ckpt_write_scoped_to_runtime_and_exempts_atomic():
    # same bad source outside deepspeed_tpu/runtime/: out of scope
    assert lint(CKPT_BAD_OPEN, path="deepspeed_tpu/serving/x.py",
                rules=["raw-ckpt-write"]) == []
    # and atomic.py IS the commit path
    assert lint(CKPT_BAD_RENAME,
                path="deepspeed_tpu/runtime/resilience/atomic.py",
                rules=["raw-ckpt-write"]) == []


def test_raw_ckpt_write_suppressible_inline():
    src = ('def legacy(path, arrays):\n'
           '    np.savez(path, **arrays)'
           '  # graftlint: disable=raw-ckpt-write\n')
    assert lint(src, path=RUNTIME_PATH, rules=["raw-ckpt-write"]) == []


def test_raw_ckpt_write_repo_runtime_is_clean():
    """The acceptance bar: the rule runs over the real runtime tree with
    an EMPTY baseline — nothing writes around the atomic discipline."""
    from tools.graftlint.core import run_paths

    result = run_paths(["deepspeed_tpu/runtime"],
                       rules=[REGISTRY["raw-ckpt-write"]],
                       use_baseline=False)
    assert result.new == [], [f.format() for f in result.new]


# ---------------------------------------------------------------------------
# rule: bare-except (folded from check_no_bare_except)
# ---------------------------------------------------------------------------

def test_bare_except_rule_matches_legacy_checker():
    src = "try:\n    x()\nexcept Exception:\n    pass\n"
    got = lint(src, rules=["bare-except"])
    assert rule_names(got) == ["bare-except"]
    # the legacy opt-out marker keeps working through the rule
    src_ok = ("try:\n    x()\n"
              "except Exception:  # lint: allow-broad-except\n    pass\n")
    assert lint(src_ok, rules=["bare-except"]) == []


# ---------------------------------------------------------------------------
# telemetry coverage (ISSUE 10): hot-file host-sync + _arm_telemetry
# discipline
# ---------------------------------------------------------------------------

# span emit that pays a device round-trip per recorded event — the
# exact failure mode the telemetry host-sync bar exists to catch
TELEMETRY_HS_BAD = """
def record_spans(tracer, lane, arrays, jax):
    for a in arrays:
        tracer.complete("fetch", lane, float(jax.device_get(a)))
"""

# fixed twin: one batched fetch after the loop, spans from host floats
TELEMETRY_HS_GOOD = """
def record_spans(tracer, lane, arrays, jax):
    ts = jax.device_get(arrays)
    for t in ts:
        tracer.complete("fetch", lane, float(t))
"""


@pytest.mark.parametrize("path", ["deepspeed_tpu/telemetry/trace.py",
                                  "deepspeed_tpu/telemetry/metrics.py",
                                  "deepspeed_tpu/telemetry/mfu.py"])
def test_host_sync_fires_in_telemetry_loop(path):
    got = lint(TELEMETRY_HS_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]
    assert "per-iteration loop" in got[0].message


def test_host_sync_quiet_on_batched_telemetry_emit():
    assert lint(TELEMETRY_HS_GOOD, "deepspeed_tpu/telemetry/trace.py",
                rules=["host-sync"]) == []


def test_host_sync_telemetry_scope_is_telemetry_files_only():
    # the same loop in a non-hot module is plain host code
    assert lint(TELEMETRY_HS_BAD, "deepspeed_tpu/utils/foo.py",
                rules=["host-sync"]) == []


ARM_TELEMETRY_BAD = """
class E:
    def _arm_telemetry(self):
        self._telemetry = None
        if self.config.telemetry_enabled:
            self._telemetry = build_session(self.config)
"""

ARM_TELEMETRY_GOOD = ARM_TELEMETRY_BAD + """
        elif self.config.metrics_jsonl:
            log_dist("telemetry: DISARMED — metrics_jsonl set but "
                     "telemetry.enabled=false", ranks=[0],
                     level=logging.WARNING)
"""


def test_disarmed_discipline_covers_arm_telemetry():
    got = lint(ARM_TELEMETRY_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert lint(ARM_TELEMETRY_GOOD, rules=["disarmed-discipline"]) == []


# ---------------------------------------------------------------------------
# memory accounting (ISSUE 15): cold report builders + arming discipline
# ---------------------------------------------------------------------------

HS_MEMORY_READ_BAD = """
class E:
    def train_batch(self, batch):
        loss = self._jit(batch)
        watermark = self.memory_report()
        return loss, watermark
"""

HS_MEASURED_READ_BAD = """
class E:
    def step(self):
        self._take_step()
        self._last_mem = self._memacct.measured_memory()
"""

HS_MEMORY_READ_GOOD = """
class E:
    def memory_report(self):
        return build_report(self._analytic_memory_components(),
                            self._memacct.measured_memory(),
                            device_memory_report())

    def train_batch(self, batch):
        return self._jit(batch)
"""


def test_host_sync_flags_measured_memory_read_in_hot_fn():
    """ISSUE 15 satellite: a measured-memory read (memory_report /
    measured_memory — lazy compiles + whole-tree walks) inside a hot
    step fn is a finding; the same builders called from a cold report
    fn are quiet."""
    path = "deepspeed_tpu/runtime/engine.py"
    got = lint(HS_MEMORY_READ_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]
    assert "arming time" in got[0].message
    got = lint(HS_MEASURED_READ_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]
    assert lint(HS_MEMORY_READ_GOOD, path, rules=["host-sync"]) == []
    # the bar applies to engine/bench hot fns only
    assert lint(HS_MEMORY_READ_BAD, "tools/somefile.py",
                rules=["host-sync"]) == []


def test_host_sync_flags_memory_read_in_bench_timed_region():
    # bench files hold EVERY fn to the bar — the one blessed read in
    # bench.py carries an inline suppression
    got = lint(HS_MEMORY_READ_BAD, "bench.py", rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]


ARM_MEMORY_BAD = """
class E:
    def _arm_memory_accounting(self):
        self._memacct = None
        if self.config.telemetry_enabled and self.config.memory:
            self._memacct = MemoryAccounting(shared=self._telemetry.mfu)
"""

ARM_MEMORY_GOOD = ARM_MEMORY_BAD + """
        elif self.config.telemetry_enabled:
            log_dist("memory accounting: DISARMED — telemetry.memory="
                     "false; memory_report() stays analytic-only",
                     ranks=[0], level=logging.WARNING)
"""


def test_disarmed_discipline_covers_arm_memory_accounting():
    """ISSUE 15 satellite: the memory-accounting arming fn is held to
    the armed-or-warns discipline — a silent analytic-only fallback
    fires; warning DISARMED quiets it."""
    got = lint(ARM_MEMORY_BAD, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_memory_accounting" in got[0].message
    assert lint(ARM_MEMORY_GOOD, rules=["disarmed-discipline"]) == []


# ---------------------------------------------------------------------------
# rule: host-sync — prefix cache + speculative decode (ISSUE 17)
# ---------------------------------------------------------------------------

HS_RADIX_WALK_BAD = """
class PagedKVPool:
    def prefix_attach(self, rid, shard, tokens):
        blocks = []
        for node in self.prefix_lookup(shard, tokens)[0]:
            node.refs += 1
            jax.device_get(self.tensors.k[:, node.block])
            blocks.append(node.block)
        return blocks
"""

HS_COW_SPLIT_BAD = """
class PagedKVPool:
    def _cow_copy(self, shard, src, dst):
        arrs = _cow_copy_rows(self.tensors.arrays, src, dst)
        for a in arrs:
            a.block_until_ready()
        self.tensors = PoolTensors(*arrs)
"""

HS_RECLAIM_BAD = """
class PagedKVPool:
    def _reclaim_block(self, shard):
        while self._lru:
            node = self._lru.pop()
            if float(jax.device_get(node.score)) > 0:
                continue
            return node.block
"""

HS_DRAFT_BAD = """
class InferenceEngine:
    def _spec_decode_tick(self, events):
        for slot, req in self.scheduler.running.items():
            drafts = self._draft_tokens(req, self.spec_k)
            tok = int(jax.device_get(self._nxt[slot]))
            req.generated.append(tok)
"""

HS_PREFIX_SPEC_GOOD = """
class PagedKVPool:
    def prefix_attach(self, rid, shard, tokens):
        full, cow, cow_len = self.prefix_lookup(shard, tokens)
        blocks = []
        for node in full:
            node.refs += 1
            blocks.append(node.block)
        if cow is not None and cow_len > 0:
            self._cow_copy(shard, cow.block, blocks[-1])
        return blocks

    def _cow_copy(self, shard, src, dst):
        self.tensors = PoolTensors(
            *_cow_copy_rows(self.tensors.arrays, src, dst))


class InferenceEngine:
    def _spec_decode_tick(self, events):
        out = self._spec(self.params, self._tables)
        outs, fins = jax.device_get((out[-2], out[-1]))
        for slot, req in self.scheduler.running.items():
            req.generated.append(int(outs[slot, 0]))
"""


@pytest.mark.parametrize("src,label", [
    (HS_RADIX_WALK_BAD, "prefix_attach"),
    (HS_COW_SPLIT_BAD, "_cow_copy"),
    (HS_RECLAIM_BAD, "_reclaim_block"),
])
def test_host_sync_covers_radix_cow_refcount_fns(src, label):
    """ISSUE 17 satellite: the radix walk, COW split and LRU reclaim run
    at admission over every request — a device sync per tree node (or a
    block on the COW copy) fires; the single jitted copy dispatch and
    host-only refcount bookkeeping stay quiet."""
    path = "deepspeed_tpu/serving/kv_cache.py"
    got = lint(src, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], (label, path)
    # scoped: the same walk in a test file is not a hot path
    assert lint(src, "tests/unit/t.py", rules=["host-sync"]) == []


def test_host_sync_covers_draft_verify_tick():
    """The draft-verify tick is held to the decode bar: a per-lane fetch
    fires; drafting + ONE batched fetch after the dispatch is quiet."""
    path = "deepspeed_tpu/serving/engine.py"
    got = lint(HS_DRAFT_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]
    assert lint(HS_PREFIX_SPEC_GOOD, path, rules=["host-sync"]) == []
    assert lint(HS_PREFIX_SPEC_GOOD,
                "deepspeed_tpu/serving/kv_cache.py",
                rules=["host-sync"]) == []


# ---------------------------------------------------------------------------
# rule: disarmed-discipline — cache/spec arming pairs (ISSUE 17)
# ---------------------------------------------------------------------------

DISARM_PREFIX_CACHE_BAD = """
class InferenceEngine:
    def _arm_prefix_cache(self, requested, quantize_kv):
        if not requested:
            return False
        if quantize_kv and not self.pool.quantized:
            return False
        return True
"""

DISARM_PREFIX_CACHE_GOOD = """
class InferenceEngine:
    def _arm_prefix_cache(self, requested, quantize_kv):
        if not requested:
            return False
        if quantize_kv and not self.pool.quantized:
            logger.warning("prefix cache: DISARMED - int8 KV was "
                           "requested but the pool disarmed it "
                           "(off-profitability)")
            return False
        if self.scheduler.draining:
            logger.warning("prefix cache: DISARMED - draining engine "
                           "admits nothing, the tree would pin blocks")
            return False
        return True
"""

DISARM_SPEC_BAD = """
class InferenceEngine:
    def _arm_speculative(self, spec):
        if not spec or self.temperature != 0.0:
            return 0
        return int(spec)
"""

DISARM_SPEC_GOOD = """
class InferenceEngine:
    def _arm_speculative(self, spec):
        if not spec:
            return 0
        if self.temperature != 0.0:
            logger.warning("speculative decoding: DISARMED - sampling "
                           "!= greedy: the acceptance rule is only "
                           "defined at temperature=0")
            return 0
        return int(spec)
"""


@pytest.mark.parametrize("bad,good", [
    (DISARM_PREFIX_CACHE_BAD, DISARM_PREFIX_CACHE_GOOD),
    (DISARM_SPEC_BAD, DISARM_SPEC_GOOD),
])
def test_disarmed_discipline_cache_and_spec_arming(bad, good):
    """ISSUE 17 satellite: the cache/spec arming decisions follow the
    armed-or-warns discipline — silently refusing a requested feature
    fires; a DISARMED warn naming the blocker (sampling != greedy,
    int8-off-profitability, draining) is quiet."""
    path = "deepspeed_tpu/serving/engine.py"
    assert rule_names(lint(bad, path,
                           rules=["disarmed-discipline"])) \
        == ["disarmed-discipline"]
    assert lint(good, path, rules=["disarmed-discipline"]) == []


DISARM_QUANT_KV_BAD = """
class PagedKVPool:
    def _arm_quantized_kv(self, requested):
        if not requested:
            return False
        elem = np.dtype(self.dtype).itemsize
        if self.cfg.head_dim * (elem - 1) <= 4:
            return False
        return True
"""

DISARM_QUANT_KV_GOOD = """
class PagedKVPool:
    def _arm_quantized_kv(self, requested):
        if not requested:
            return False
        elem = np.dtype(self.dtype).itemsize
        if self.cfg.head_dim * (elem - 1) <= 4:
            logger.warning("PagedKVPool: int8 KV quantization DISARMED "
                           "- the per-row f32 scale outweighs the "
                           "element savings; int8 would GROW the pool")
            return False
        return True
"""


def test_disarmed_discipline_covers_arm_quantized_kv():
    """ISSUE 19 satellite: the KV pool's int8 arming decision follows
    the armed-or-warns discipline — silently serving full-precision KV
    after int8 was REQUESTED (off-profitability head_dim) fires; a
    DISARMED warn naming the blocker is quiet."""
    path = "deepspeed_tpu/serving/kv_cache.py"
    got = lint(DISARM_QUANT_KV_BAD, path, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_quantized_kv" in got[0].message
    assert lint(DISARM_QUANT_KV_GOOD, path,
                rules=["disarmed-discipline"]) == []


# ---------------------------------------------------------------------------
# 0/1 Adam wire (PR 18): arming discipline + hot step/pack fn coverage
# ---------------------------------------------------------------------------

DISARM_ZEROONE_BAD = """
class E:
    def _arm_zeroone(self, params):
        self._zeroone_armed = False
        if self.dp_world_size <= 1 or self.zero_optimization_stage() != 0:
            return False
        self._zeroone_armed = True
        return True
"""

DISARM_ZEROONE_GOOD = """
class E:
    def _arm_zeroone(self, params):
        self._zeroone_armed = False
        blockers = []
        if self.dp_world_size <= 1:
            blockers.append("data-parallel degree is 1")
        if self.zero_optimization_stage() != 0:
            blockers.append("zero_optimization.stage shards the "
                            "accumulator")
        if blockers:
            log_dist("ZeroOneAdam: wire compression DISARMED - "
                     f"({', '.join(blockers)})", ranks=[0],
                     level=logging.WARNING)
            return False
        self._zeroone_armed = True
        return True
"""

DISARM_QAR_BAD = """
class E:
    def _arm_quantized_allreduce(self, dp, params=None):
        self._qar_armed = False
        if dp <= 1:
            return 0
        self._qar_armed = True
        return self._resolve_intra(dp, params)
"""

DISARM_QAR_GOOD = """
class E:
    def _arm_quantized_allreduce(self, dp, params=None):
        self._qar_armed = False
        if dp <= 1:
            log_dist("quantized_all_reduce: DISARMED - data-parallel "
                     "degree is 1, no wire to shrink", ranks=[0],
                     level=logging.WARNING)
            return 0
        self._qar_armed = True
        return self._resolve_intra(dp, params)
"""


@pytest.mark.parametrize("bad,good,name", [
    (DISARM_ZEROONE_BAD, DISARM_ZEROONE_GOOD, "_arm_zeroone"),
    (DISARM_QAR_BAD, DISARM_QAR_GOOD, "_arm_quantized_allreduce"),
])
def test_disarmed_discipline_covers_zeroone_arming(bad, good, name):
    """PR 18 satellite: the 0/1 Adam wire arming decisions follow the
    armed-or-warns discipline — silently falling back to the dense
    optimizer path (or the flat wire) fires; a DISARMED warn naming the
    blockers (dp=1, zero stage, offload, sparse grads) is quiet."""
    path = "deepspeed_tpu/runtime/engine.py"
    got = lint(bad, path, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert name in got[0].message
    assert lint(good, path, rules=["disarmed-discipline"]) == []


# phase selection that re-reads a device counter per step — the exact
# serialization the _zeroone_frozen_latch exists to avoid
HS_ZEROONE_STEP_BAD = """
class E:
    def _zeroone_phase(self):
        while self._pending:
            s = int(self._step_counter.item())
            self._pending.pop()
        return self.optimizer.cadence(s)
"""

HS_ZEROONE_STEP_GOOD = """
class E:
    def _zeroone_phase(self):
        return self.optimizer.cadence(self.global_steps -
                                      self.skipped_steps)
"""

# a sign-pack kernel that syncs per block — inside every sync round's
# program this would stall the wire once per 128 floats
HS_PACK_BAD = """
def quantize_signs_rows(x, block_size=128):
    scales = []
    for blk in split_blocks(x, block_size):
        scales.append(float(jax.device_get(abs_mean(blk))))
    return pack_bits(x), scales
"""

HS_PACK_GOOD = """
def quantize_signs_rows(x, block_size=128):
    blocks = reshape_blocks(x, block_size)
    scales = abs_mean(blocks)
    return pack_bits(x), scales
"""


def test_host_sync_covers_zeroone_step_and_pack_fns():
    """PR 18 satellite: the per-step phase selector (engine.py) and the
    sign pack/quantize kernels (quantization.py / custom_collectives.py)
    are hot — a device sync in any of their loops fires; pure host
    bookkeeping / straight-line array math is quiet."""
    epath = "deepspeed_tpu/runtime/engine.py"
    got = lint(HS_ZEROONE_STEP_BAD, epath, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"]
    assert "per-iteration loop" in got[0].message
    assert lint(HS_ZEROONE_STEP_GOOD, epath, rules=["host-sync"]) == []
    for qpath in ("deepspeed_tpu/runtime/quantization.py",
                  "deepspeed_tpu/runtime/custom_collectives.py"):
        got = lint(HS_PACK_BAD, qpath, rules=["host-sync"])
        assert rule_names(got) == ["host-sync"], qpath
        assert lint(HS_PACK_GOOD, qpath, rules=["host-sync"]) == []
    # scope: the same pack loop outside the wire files is plain host code
    assert lint(HS_PACK_BAD, "tools/somefile.py", rules=["host-sync"]) == []


HS_REARM_BAD = """
class E:
    def train_batch(self, batch):
        self._arm_zeroone(self._opt_params)
        self._compile_zeroone()
        return self._jit_micro(batch)
"""

HS_REARM_GOOD = """
class E:
    def _configure_optimizer(self):
        if self._arm_zeroone(self._opt_params):
            self._intra = self._arm_quantized_allreduce(self.dp)

    def train_batch(self, batch):
        return self._jit_micro(batch)
"""


def test_host_sync_flags_zeroone_rearm_in_hot_fn():
    """PR 18 satellite: re-arming the wire (blocker scan + program-cache
    rebuild) from a hot step fn is flagged as cold-builder work — arm
    once at configure time, reuse the decision."""
    path = "deepspeed_tpu/runtime/engine.py"
    got = lint(HS_REARM_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync", "host-sync"]
    assert "arming time" in got[0].message
    assert lint(HS_REARM_GOOD, path, rules=["host-sync"]) == []


# ---------------------------------------------------------------------------
# sparse page attention (ISSUE 20): LUT walk hot, arming cold + disarmed
# ---------------------------------------------------------------------------

HS_ACTIVE_ROW_BAD = """
class SparseContext:
    def active_row(self, table_row, pos):
        qb = min(int(pos) // self.bs, self.W - 1)
        phys = [int(jax.device_get(table_row[max(b, 0)]))
                for b in self.lut[qb]]
        return phys, self.lut[qb] * self.bs
"""

HS_WINDOW_FREE_BAD = """
class PagedKVPool:
    def window_expired_free(self, rid, first_active_block, keep_blocks=0):
        for i in range(keep_blocks, first_active_block):
            b = self._blocks[rid][i]
            if float(jax.device_get(self.tensors.k[0, b]).sum()) == 0:
                continue
            self._blocks[rid][i] = None
"""

HS_SPARSE_GOOD = """
class SparseContext:
    def active_row(self, table_row, pos):
        qb = min(int(pos) // self.bs, self.W - 1)
        row = self.lut[qb]
        phys = table_row[np.maximum(row, 0)].astype(np.int32)
        live = (row >= 0) & (phys != TRASH_BLOCK)
        return (np.where(live, phys, 0),
                np.where(live, row * self.bs, self.sentinel))

    def prefill_active_row(self, table_row, start, n, bucket):
        row = self.lut[min(int(start) // self.bs, self.W - 1)]
        return table_row[np.maximum(row, 0)], row * self.bs
"""


@pytest.mark.parametrize("src,path,label", [
    (HS_ACTIVE_ROW_BAD, "deepspeed_tpu/serving/sparse_context.py",
     "active_row"),
    (HS_WINDOW_FREE_BAD, "deepspeed_tpu/serving/kv_cache.py",
     "window_expired_free"),
])
def test_host_sync_covers_sparse_lut_walk(src, path, label):
    """ISSUE 20 satellite: the per-lane LUT walk and the window-expired
    sweep run once per decode dispatch over every running lane — a
    device fetch per lane (or per candidate block) serializes decode
    against the host and fires; the pure-numpy row refresh is quiet."""
    got = lint(src, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync"], (label, path)
    # scoped to the hot files: the same walk elsewhere is free
    assert lint(src, "tests/unit/t.py", rules=["host-sync"]) == []


def test_host_sync_sparse_row_refresh_quiet():
    assert lint(HS_SPARSE_GOOD, "deepspeed_tpu/serving/sparse_context.py",
                rules=["host-sync"]) == []


HS_SPARSE_REARM_BAD = """
class InferenceEngine:
    def _decode_tick(self, events):
        sparse = self._arm_sparse_context(self._sparse_spec)
        sparse._compile_luts()
        return self._decode(*self._decode_args())
"""

HS_SPARSE_REARM_GOOD = """
class InferenceEngine:
    def __init__(self, spec):
        self.sparse = self._arm_sparse_context(spec)

    def _decode_tick(self, events):
        return self._decode(*self._decode_args())
"""


def test_host_sync_flags_sparse_rearm_in_hot_fn():
    """Arming the policy (blocker scan + (W, K) LUT compile) is cold
    -builder work: re-arming per decode tick rebuilds the LUTs and the
    DISARMED decision every step and fires; arm-once at engine build is
    quiet."""
    path = "deepspeed_tpu/serving/engine.py"
    got = lint(HS_SPARSE_REARM_BAD, path, rules=["host-sync"])
    assert rule_names(got) == ["host-sync", "host-sync"]
    assert "arming time" in got[0].message
    assert lint(HS_SPARSE_REARM_GOOD, path, rules=["host-sync"]) == []


DISARM_SPARSE_BAD = """
class InferenceEngine:
    def _arm_sparse_context(self, spec):
        if not spec:
            return None
        if self.spec_k:
            return None
        if int(spec.get("window_tokens", 0)) % self.bs != 0:
            return None
        return SparseContext(block_size=self.bs, table_width=self.W)
"""

DISARM_SPARSE_GOOD = """
class InferenceEngine:
    def _arm_sparse_context(self, spec):
        if not spec:
            return None
        if self.spec_k:
            logger.warning("sparse context: DISARMED - draft-k "
                           "speculation gathers the full table; "
                           "composing the policies is unsupported")
            return None
        if int(spec.get("window_tokens", 0)) % self.bs != 0:
            logger.warning("sparse context: DISARMED - window_tokens "
                           "is not a multiple of the KV block size; "
                           "the window edge would land mid-page")
            return None
        return SparseContext(block_size=self.bs, table_width=self.W)
"""


def test_disarmed_discipline_covers_sparse_context_arming():
    """ISSUE 20 satellite: _arm_sparse_context follows the armed-or-
    warns discipline — silently serving dense when a sparse policy was
    requested fires; DISARMED warns naming the blocker (speculation,
    mid-page window edge) are quiet."""
    path = "deepspeed_tpu/serving/engine.py"
    got = lint(DISARM_SPARSE_BAD, path, rules=["disarmed-discipline"])
    assert rule_names(got) == ["disarmed-discipline"]
    assert "_arm_sparse_context" in got[0].message
    assert lint(DISARM_SPARSE_GOOD, path,
                rules=["disarmed-discipline"]) == []
