"""KV-cache generation: decode math must match the training forward."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.models.generation import generate
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model


def _model(scan_layers):
    cfg = GPT2Config(vocab_size=97, n_positions=32, n_embd=32, n_layer=3,
                     n_head=4, dtype=jnp.float32, scan_layers=scan_layers,
                     loss_chunk_tokens=0)
    model = GPT2Model(cfg)
    ids = np.random.default_rng(0).integers(0, 97, (2, 8))
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": ids, "labels": ids})
    return model, params


@pytest.mark.parametrize("scan_layers", [False, True])
def test_greedy_matches_full_forward(scan_layers):
    """Greedy decode with the KV cache must equal greedy decode by
    re-running the full training forward each step."""
    model, params = _model(scan_layers)
    prompt = np.random.default_rng(1).integers(0, 97, (2, 4))
    out = generate(model, params, prompt, max_new_tokens=6)

    seq = prompt.copy()
    for _ in range(6):
        logits = model.module.apply({"params": params},
                                    jnp.asarray(seq, jnp.int32),
                                    train=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))[:, None]
        seq = np.concatenate([seq, nxt], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_prompt_is_preserved():
    model, params = _model(False)
    prompt = np.random.default_rng(2).integers(0, 97, (3, 5))
    out = generate(model, params, prompt, max_new_tokens=3)
    np.testing.assert_array_equal(out[:, :5], prompt)
    assert out.shape == (3, 8)


def test_sampling_deterministic_per_key_and_in_vocab():
    model, params = _model(False)
    prompt = np.random.default_rng(3).integers(0, 97, (2, 3))
    a = generate(model, params, prompt, max_new_tokens=5, temperature=0.8,
                 top_k=10, rng=jax.random.PRNGKey(7))
    b = generate(model, params, prompt, max_new_tokens=5, temperature=0.8,
                 top_k=10, rng=jax.random.PRNGKey(7))
    c = generate(model, params, prompt, max_new_tokens=5, temperature=0.8,
                 top_k=10, rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a < 97).all()
    assert not np.array_equal(a, c), "different keys produced same sample"


def test_context_limit_asserted():
    model, params = _model(False)
    with pytest.raises(AssertionError, match="n_positions"):
        generate(model, params, np.zeros((1, 30), np.int32),
                 max_new_tokens=10)


def test_moe_generation_smoke():
    """MoE configs generate (round 5; previously rejected): finite in-vocab
    tokens through the dense/MoE-alternating stack. Exact parity with the
    training forward is pinned by
    test_moe_generation_matches_training_forward."""
    cfg = GPT2Config(vocab_size=64, n_embd=16, n_layer=2, n_head=2,
                     n_positions=32, dtype=np.float32, moe_num_experts=4)
    model = GPT2Model(cfg)
    prompt = jnp.asarray(np.random.default_rng(2).integers(0, 64, (1, 4)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": prompt, "labels": prompt})
    out = generate(model, params, prompt, max_new_tokens=4)
    assert out.shape == (1, 8) and out.max() < 64


def test_huge_top_k_is_safe():
    model, params = _model(False)
    prompt = np.random.default_rng(4).integers(0, 97, (1, 3))
    out = generate(model, params, prompt, max_new_tokens=3,
                   temperature=1.0, top_k=500)
    assert out.shape == (1, 6)


def test_decode_program_is_cached():
    from deepspeed_tpu.models.generation import _decode_fn

    model, params = _model(False)
    prompt = np.random.default_rng(5).integers(0, 97, (2, 4))
    _decode_fn.cache_clear()
    generate(model, params, prompt, max_new_tokens=3)
    generate(model, params, prompt, max_new_tokens=3)
    info = _decode_fn.cache_info()
    assert info.hits >= 1, info


def test_greedy_generation_matches_transformers():
    """End-to-end interop: HF FlaxGPT2 weights loaded via module_inject,
    greedy KV-cache decode matches transformers' own greedy generate."""
    transformers = pytest.importorskip("transformers")
    from deepspeed_tpu.module_inject.policy import load_hf_gpt2_params

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        pad_token_id=0, eos_token_id=None, bos_token_id=None)
    hf = transformers.FlaxGPT2LMHeadModel(hf_cfg, seed=0)

    model = GPT2Model(GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2,
        dtype=jnp.float32, loss_chunk_tokens=0))
    params = load_hf_gpt2_params(hf.params)

    prompt = np.random.default_rng(6).integers(1, 128, (2, 5))
    # manual greedy loop over the HF forward (FlaxGPT2's generate() API
    # insists on a usable eos token; greedy argmax is the same math)
    seq = prompt.copy()
    for _ in range(7):
        logits = np.asarray(hf(jnp.asarray(seq)).logits)
        seq = np.concatenate([seq, logits[:, -1].argmax(-1)[:, None]],
                             axis=1)
    got = generate(model, params, prompt, max_new_tokens=7)
    np.testing.assert_array_equal(got, seq)


def test_moe_generation_matches_training_forward():
    """MoE configs generate: greedy decode must match teacher-forced argmax
    over the training forward, given capacity generous enough that neither
    path drops tokens (drop competition is the one documented divergence —
    decode gates one token per step; see generation._moe_ffn)."""
    cfg = GPT2Config(vocab_size=97, n_positions=32, n_embd=32, n_layer=4,
                     n_head=2, dtype=jnp.float32, loss_chunk_tokens=0,
                     moe_num_experts=4, moe_top_k=2,
                     moe_capacity_factor=8.0)   # no drops at these sizes
    model = GPT2Model(cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, 97, (2, 6)), jnp.int32)
    params = model.init(jax.random.PRNGKey(1),
                        {"input_ids": prompt, "labels": prompt})

    out = generate(model, params, prompt, 8)          # greedy KV-cache path
    assert out.shape == (2, 14)
    assert out.max() < 97

    # teacher-forced reference: argmax of the training forward at each step
    seq = np.asarray(prompt)
    for _ in range(8):
        logits = model.module.apply({"params": params},
                                    jnp.asarray(seq), train=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))[:, None]
        seq = np.concatenate([seq, nxt], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_top_p_restricts_to_nucleus():
    """top_p must only ever emit tokens from the smallest head of the
    distribution reaching that mass; a peaked distribution with top_p
    below the top token's own probability becomes deterministic."""
    from deepspeed_tpu.models.generation import _sample

    # hand-built distribution: token 3 carries ~88% of the mass
    logits = jnp.asarray([[0.0, 1.0, 2.0, 6.0, -1.0]])
    for i in range(20):
        tok = _sample(logits, jax.random.PRNGKey(i), temperature=1.0,
                      top_k=0, top_p=0.5)
        assert int(tok[0]) == 3, int(tok[0])
    # top_p=1.0 filters nothing: other tokens appear across seeds
    seen = {int(_sample(logits, jax.random.PRNGKey(i), 1.0, 0, 1.0)[0])
            for i in range(200)}
    assert len(seen) > 1, seen


def test_top_p_end_to_end_in_vocab():
    model, params = _model(False)
    prompt = np.random.default_rng(7).integers(0, 97, (2, 4))
    out = generate(model, params, prompt, max_new_tokens=5,
                   temperature=1.0, top_p=0.9, rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 9)
    assert out.max() < 97


def test_beam_one_equals_greedy():
    from deepspeed_tpu.models.generation import generate_beam

    model, params = _model(False)
    prompt = np.random.default_rng(8).integers(0, 97, (2, 4))
    greedy = generate(model, params, prompt, max_new_tokens=6)
    beam1 = generate_beam(model, params, prompt, max_new_tokens=6,
                          num_beams=1)
    np.testing.assert_array_equal(beam1, greedy)


def test_beam_search_finds_higher_likelihood():
    """A wider beam should return a continuation at least as likely as
    greedy's. (Not a mathematical guarantee — beam search can prune the
    greedy path and end worse — but with THESE pinned seeds it holds
    exactly, and the slack absorbs numerics drift across backends.)"""
    from deepspeed_tpu.models.generation import generate_beam

    model, params = _model(False)
    prompt = np.random.default_rng(9).integers(0, 97, (3, 4))
    greedy = generate(model, params, prompt, max_new_tokens=6)
    beam = generate_beam(model, params, prompt, max_new_tokens=6,
                         num_beams=4)
    np.testing.assert_array_equal(beam[:, :4], prompt)
    assert beam.max() < 97

    def seq_logp(seq):
        logits = model.module.apply({"params": params},
                                    jnp.asarray(seq, jnp.int32),
                                    train=False)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tgt = jnp.asarray(seq[:, 1:], jnp.int32)
        tok = jnp.take_along_axis(logp[:, :-1], tgt[..., None], -1)[..., 0]
        return np.asarray(tok[:, 3:].sum(axis=-1))  # continuation part

    g, b = seq_logp(greedy), seq_logp(beam)
    assert (b >= g - 0.5).all(), (b, g)


def test_eos_latches_and_pads():
    """Once a row emits eos, every later position must repeat eos; rows
    that never emit it are unaffected (identical to the no-eos run)."""
    model, params = _model(False)
    prompt = np.random.default_rng(12).integers(0, 97, (3, 4))
    base = generate(model, params, prompt, max_new_tokens=8)
    # pick an eos id that appears mid-continuation for at least one row
    eos = int(base[0, 4 + 2])
    out = generate(model, params, prompt, max_new_tokens=8,
                   eos_token_id=eos)
    for b in range(3):
        row = out[b, 4:]
        hits = np.flatnonzero(row == eos)
        if hits.size:
            first = hits[0]
            assert (row[first:] == eos).all(), row
            # tokens before the first eos match the unconstrained run
            np.testing.assert_array_equal(row[:first], base[b, 4:4 + first])
        else:
            np.testing.assert_array_equal(row, base[b, 4:])
