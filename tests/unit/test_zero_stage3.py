"""Scheduled ZeRO stage-3 (ISSUE 8): prefetched int8 parameter gathers
that persist through the backward.

Every tentpole claim lands as a proof in the repo's idioms:

- **parity** — fp32 loss trajectory within 2% of stage 2 over a pinned
  run (the int8 weight wire costs <1% accuracy per ZeRO++);
- **HLO contracts** (tools/graftlint/hlo_contracts.py) — the stage-3
  micro jit's gather wire is s8-only (plus the small fp32 per-block
  scales), gather bytes stay within the comm_accounting analytic budget,
  and there is EXACTLY one all-gather per partitioned param per step:
  the split forward gathers once, the backward jit contains zero
  all-gathers (the gathered weight persisted as a vjp residual — no
  remat refetch);
- **donation contracts** — the stash (vjp residuals incl. gathered
  weights) is donated at wgrad: every stash leaf is output-aliased or a
  buffer donor in the bwd jit's HLO header, and runtime leaves are
  consumed;
- **acceptance bound** — quantized stage-3 gather bytes <= 2/7 of the
  bf16 implicit path's double-gather bytes (fwd + remat-bwd refetch),
  per the analytic accounting;
- **DISARMED discipline** — budget/config blockers fall back to the
  XLA-implicit path with a warning naming each blocker.
"""
import logging
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from tools.graftlint import hlo_contracts as hc  # noqa: E402
from tests.unit.simple_model import SimpleModel, random_dataloader  # noqa: E402

HIDDEN = 16


def _engine(hidden=HIDDEN, gas=1, fp16=False, bf16=False, **zero_over):
    zero = {"stage": 3}
    zero.update(zero_over)
    cfg = {
        "train_batch_size": 8 * gas, "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 0.02}},
        "zero_optimization": zero,
        "mesh": {"data": 8}, "steps_per_print": 10 ** 9,
    }
    if fp16:
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8,
                       "hysteresis": 1}
    if bf16:
        cfg["bf16"] = {"enabled": True}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden), config_params=cfg)
    return engine


def _train(engine, steps=10, hidden=HIDDEN, seed=0):
    it = random_dataloader(hidden, 64, 8, seed=seed)
    losses = []
    for _ in range(steps):
        loss = engine(next(it))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


# ---------------------------------------------------------------------------
# arming, plan, and the DISARMED discipline
# ---------------------------------------------------------------------------

def test_stage3_scheduled_armed_by_default(eight_devices):
    e = _engine()
    _train(e, steps=1)
    assert e._s3_sched_armed
    report = e.stage3_report()
    assert report["armed"] and report["n_blocks"] >= 1
    # w1 (16,16), b1 (16,), w2 (16,4) partition over dp=8; b2 (4,) cannot
    assert report["n_gathered_leaves"] == 3
    assert report["n_replicated_leaves"] == 1
    assert report["peak_gathered_bytes"] == (256 + 16 + 64) * 4
    # the staged API routed through the split fwd/bwd jits
    assert e._jit_s3_fwd is not None and e._jit_s3_bwd is not None


def test_stage3_params_stay_sharded(eight_devices):
    e = _engine()
    _train(e, steps=1)
    w1 = e.state.params["w1"]
    assert str(w1.sharding.spec).startswith("PartitionSpec('data'")
    assert len({str(s.index) for s in w1.addressable_shards}) == 8


def test_stage3_disarmed_by_budget_warns_loudly(eight_devices, caplog):
    from deepspeed_tpu.utils.logging import logger as ds_logger

    ds_logger.propagate = True
    try:
        with caplog.at_level(logging.WARNING):
            e = _engine(stage3_prefetch_budget=100)
            _train(e, steps=2)
    finally:
        ds_logger.propagate = False
    assert not e._s3_sched_armed
    assert e._jit_s3_fwd is None  # implicit path: plain donating micro
    msgs = [r.message for r in caplog.records if "DISARMED" in r.message]
    assert msgs and "stage3_prefetch_budget=100" in msgs[0]
    assert "1344 B" in msgs[0]  # names the plan's actual peak bytes
    # the report still says what the plan WOULD cost, and that it is off
    rep = e.stage3_report()
    assert rep["armed"] is False and rep["peak_gathered_bytes"] == 1344


def test_stage3_scheduled_gathers_false_keeps_implicit_path(eight_devices):
    e = _engine(stage3_scheduled_gathers=False)
    losses = _train(e, steps=8)
    assert not e._s3_sched_armed and losses[-1] < losses[0]
    rep = e.comm_volume_report()
    # honest implicit model: TWO dense gathers per micro (fwd + the
    # remat'd backward refetch), none quantized
    assert rep["config"]["param_gathers_per_step"] == 2
    assert rep["param_gather_quantized_bytes_per_step"] == 0
    assert rep["param_gather_dense_bytes_per_step"] == \
        rep["baseline"]["implicit_param_gather_bytes_per_step"]


# ---------------------------------------------------------------------------
# numerics: parity + overflow
# ---------------------------------------------------------------------------

def test_stage3_fp32_parity_vs_stage2_within_2pct(eight_devices):
    """Acceptance: fp32 loss trajectory drifts <= 2% from stage 2 over a
    pinned run — the int8 weight-gather wire is numerically benign
    (ZeRO++ qwZ's <1% claim, straight-through gradients)."""
    l2 = _train(_engine(stage=2), steps=12)
    l3 = _train(_engine(stage=3), steps=12)
    assert np.isfinite(l3).all() and l3[-1] < l3[0]
    for a, b in zip(l2, l3):
        assert abs(a - b) / abs(a) < 0.02, (l2, l3)


def test_stage3_overflow_still_trips_loss_scaler(eight_devices):
    """Non-finite weights/grads survive the quantized gather (non-finite
    block scales propagate) so the fp16 loss-scale machinery still sees
    the overflow."""
    e = _engine(fp16=True)
    it = random_dataloader(HIDDEN, 64, 8)
    good = next(it)
    loss = e(good)
    e.backward(loss)
    e.step()
    assert e._s3_sched_armed
    scale_before = e.loss_scale()
    bad = {"x": np.full((8, HIDDEN), np.nan, np.float32),
           "y": good["y"].copy()}
    loss = e(bad)
    e.backward(loss)
    e.step()
    assert e.skipped_steps >= 1
    assert e.loss_scale() == scale_before / 2


def test_stage3_fused_train_batch_with_accumulation(eight_devices):
    e = _engine(gas=2)
    it = random_dataloader(HIDDEN, 64, 8)
    losses = [float(jax.device_get(e.train_batch(data_iter=it)))
              for _ in range(6)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    # satellite: the per-step metrics carry the dense/quantized gather
    # split (gas=2 -> two quantized gathers per optimizer step)
    m = e._last_metrics
    assert m["param_gather_quantized_bytes_per_step"] == 378 * 2
    assert m["param_gather_dense_bytes_per_step"] == 0
    assert m["param_gather_bytes_per_step"] == 378 * 2


def test_stage3_forward_twice_without_backward_raises(eight_devices):
    e = _engine()
    it = random_dataloader(HIDDEN, 64, 8)
    e(next(it))
    with pytest.raises(RuntimeError, match="forward"):
        e(next(it))
    e.backward(None)
    e.step()
    # and a save mid-window is refused with the actionable story
    e(next(it))
    with pytest.raises(AssertionError, match="backward"):
        e.save_checkpoint("/tmp/nope")
    e.backward(None)
    e.step()


# ---------------------------------------------------------------------------
# HLO contracts: s8-only gather wire, one gather per param, no bwd refetch
# ---------------------------------------------------------------------------

def _gather_ops(hlo):
    return [c for c in hc.collective_ops(hlo) if c.op == "all-gather"]


def test_stage3_micro_jit_gather_wire_is_s8_within_budget(eight_devices):
    """The fused micro jit (one fwd+bwd): every weight-sized all-gather
    moves s8 (the fp32 gathers are the per-block scales, tiny), the
    gather count is exactly one per partitioned leaf — the backward
    reuses the residual instead of regathering — and total gather bytes
    stay within the analytic param-gather budget (converted to HLO
    output terms by the ring factor dp/(dp-1))."""
    e = _engine()
    _train(e, steps=1)
    rng = np.random.default_rng(0)
    batch = {"x": rng.standard_normal((8, HIDDEN)).astype(np.float32),
             "y": rng.integers(0, 4, (8,)).astype(np.int32)}
    dev = e._shard_batch(batch)
    with jax.set_mesh(e.mesh):
        hlo = e._jit_micro.lower(e.state, dev).compile().as_text()
    hc.assert_no_host_transfers(hlo, "stage-3 micro jit")
    ags = _gather_ops(hlo)
    s8 = [c for c in ags if c.dtype == "s8"]
    fat = [c for c in ags if c.dtype in ("f32", "bf16", "f16")
           and c.elements >= 64]
    assert not fat, f"non-s8 weight-sized gather on the stage-3 wire: {fat}"
    # EXACTLY one s8 gather per partitioned leaf: 3 (w1, b1, w2) — a 4th
    # would be a backward refetch, a 2nd per leaf a remat replay
    assert len(s8) == e._s3_plan.n_gathered_leaves == 3, s8
    # bytes: HLO counts gathered OUTPUT bytes; the analytic budget counts
    # ring-send bytes = output * (dp-1)/dp, so scale it back up — any
    # excess means an unplanned gather sneaked in
    dp = e.dp_world_size
    budget = e.comm_volume_report()["param_gather_bytes_per_step"]
    measured = sum(c.bytes for c in ags)
    assert measured <= int(budget * dp / (dp - 1)) + 1, (measured, budget)


# the staged fwd/bwd split contracts (all gathers in the forward,
# zero in the backward, stash donated across the handoff) are
# declared on s3_fwd/s3_bwd in the program registry and checked by
# the --programs autopilot (tests/unit/test_program_lint.py)


def test_quantized_all_gather_unit_parity_and_grad(eight_devices):
    """custom_collectives.quantized_all_gather: value matches the dense
    gather within blockwise-int8 error, and the straight-through vjp
    delivers the dense cotangent (no zeroed gradients through round)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.runtime.custom_collectives import \
        quantized_all_gather

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    rng = np.random.default_rng(0)
    x_host = rng.standard_normal((64, 16)).astype(np.float32)
    x = jax.device_put(x_host, NamedSharding(mesh, P("data", None)))

    with jax.set_mesh(mesh):
        out = jax.jit(lambda v: quantized_all_gather(
            v, mesh, dim=0, block_size=32))(x)
        got = np.asarray(jax.device_get(out))
    # blockwise-int8: |err| <= scale/2 = max|block|/254 per element
    assert np.abs(got - x_host).max() <= np.abs(x_host).max() / 254 + 1e-7

    def f(v):
        return (quantized_all_gather(v, mesh, dim=0, block_size=32)
                * 2.0).sum()

    with jax.set_mesh(mesh):
        g = jax.jit(jax.grad(f))(x)
    np.testing.assert_allclose(np.asarray(jax.device_get(g)), 2.0)


# ---------------------------------------------------------------------------
# acceptance bound: bytes vs the bf16 implicit double-gather
# ---------------------------------------------------------------------------

def test_stage3_gather_bytes_le_two_sevenths_of_bf16_implicit(eight_devices):
    """Acceptance: the scheduled int8 gather wire moves <= 2/7 the bytes
    of the bf16 implicit path (which gathers every weight TWICE per
    micro: forward + the remat'd backward refetch) — int8+scales once
    vs bf16 twice is (1 + 4/128) / 4 = 0.258 at block 128."""
    e = _engine(hidden=128, bf16=True)
    _train(e, steps=1, hidden=128)
    assert e._s3_sched_armed
    rep = e.comm_volume_report()
    assert rep["config"]["param_dtype"] == "bfloat16"
    quant = rep["param_gather_bytes_per_step"]
    implicit = rep["baseline"]["implicit_param_gather_bytes_per_step"]
    assert implicit == \
        rep["baseline"]["dense_param_gather_bytes_per_step"] * 2
    assert quant * 7 <= implicit * 2, (quant, implicit)
    # and the split keys say the whole wire is quantized
    assert rep["param_gather_quantized_bytes_per_step"] == quant
    assert rep["param_gather_dense_bytes_per_step"] == 0


def test_stage3_plan_pure_math_blocks_and_budget():
    """runtime/zero/stage3.py unit: grouping follows forward order by
    layer-block key, bytes are byte-exact vs block_layout, and the
    budget check is peak-based."""
    from deepspeed_tpu.runtime.quantization import block_layout
    from deepspeed_tpu.runtime.zero import stage3 as s3

    names = ["wte", "h_0/qkv", "h_0/mlp", "h_1/qkv", "h_1/mlp", "ln_f"]
    shapes = [(512, 64), (64, 192), (64, 256), (64, 192), (64, 256), (7,)]
    dims = [0, 1, 1, 1, 1, None]
    plan = s3.build_gather_plan(names, shapes, dims, 8, block_size=128,
                                param_dtype="bfloat16")
    assert [b.key for b in plan.blocks] == ["wte", "h_0", "h_1"]
    assert [len(b.leaves) for b in plan.blocks] == [1, 2, 2]
    assert plan.replicated == [5]
    n = 512 * 64
    _, nb, npad = block_layout(n // 8, 128)
    ring = 7 / 8
    assert plan.blocks[0].wire_bytes == \
        int(round(ring * 8 * npad)) + int(round(ring * 8 * nb * 4))
    assert plan.blocks[0].gathered_bytes == n * 2  # bf16
    assert plan.within_budget(0)                   # 0 = unbounded
    assert plan.within_budget(plan.gathered_bytes)
    assert not plan.within_budget(plan.gathered_bytes - 1)
    rep = plan.report()
    assert rep["n_blocks"] == 3 and rep["n_gathered_leaves"] == 5


# ---------------------------------------------------------------------------
# pipe-engine interaction
# ---------------------------------------------------------------------------

def test_stage3_pipe_engine_downgrades_with_warning(eight_devices, caplog):
    """PipelineEngine has no cross-stage 'data' shard to gather: stage 3
    DISARMs down to stage 2 loudly instead of dying on an assert."""
    from deepspeed_tpu.utils.logging import logger as ds_logger
    from tests.unit.simple_model import make_stack_specs
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    specs, loss_fn, input_fn = make_stack_specs(16, 4)
    module = PipelineModule(specs, loss_fn=loss_fn, input_fn=input_fn,
                            partition_method="uniform")
    ds_logger.propagate = True
    try:
        with caplog.at_level(logging.WARNING):
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=module, config_params={
                    "train_batch_size": 8,
                    "train_micro_batch_size_per_gpu": 2,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3},
                    "mesh": {"pipe": 2, "data": 2, "model": 1,
                             "allow_partial": True},
                    "steps_per_print": 10 ** 9})
    finally:
        ds_logger.propagate = False
    msgs = [r.message for r in caplog.records if "DISARMED" in r.message]
    assert msgs and "stage 2" in msgs[0]
    assert engine.zero_optimization_stage() == 2
    data = random_dataloader(16, 64, 4)
    loss = engine.train_batch(data_iter=data)
    assert np.isfinite(float(jax.device_get(loss)))
