"""Partitioning utils tests (mirrors reference tests/unit/test_partition.py)."""
import numpy as np
import pytest

from deepspeed_tpu.runtime.utils import (PartitionedTensor, partition_balanced,
                                         partition_uniform, prefix_sum_inc)


def test_prefix_sum():
    assert prefix_sum_inc([1, 2, 3]) == [1, 3, 6]


def test_partition_uniform_even():
    parts = partition_uniform(8, 4)
    assert parts == [0, 2, 4, 6, 8]


def test_partition_uniform_residual():
    parts = partition_uniform(10, 4)
    assert parts[0] == 0 and parts[-1] == 10
    sizes = [parts[i + 1] - parts[i] for i in range(4)]
    assert sorted(sizes) == [2, 2, 3, 3]


def test_partition_uniform_fewer_items():
    parts = partition_uniform(2, 4)
    assert parts[0] == 0 and parts[-1] == 2
    sizes = [parts[i + 1] - parts[i] for i in range(4)]
    assert sum(sizes) == 2 and max(sizes) <= 1


def test_partition_balanced_uniform_weights():
    parts = partition_balanced([1] * 8, 4)
    assert parts == [0, 2, 4, 6, 8]


def test_partition_balanced_skewed():
    weights = [1, 1, 1, 1, 10]
    parts = partition_balanced(weights, 2)
    # heavy item should be alone-ish: bottleneck minimized
    sizes = [sum(weights[parts[i]:parts[i + 1]]) for i in range(2)]
    assert max(sizes) == 10


def test_partition_balanced_monotone_boundaries():
    weights = list(np.random.RandomState(0).randint(1, 10, size=20))
    parts = partition_balanced(weights, 4)
    assert parts[0] == 0 and parts[-1] == 20
    assert all(parts[i] <= parts[i + 1] for i in range(4))


def test_partitioned_tensor_roundtrip():
    import jax.numpy as jnp

    x = jnp.arange(20, dtype=jnp.float32).reshape(4, 5)
    world = 4
    parts = [PartitionedTensor(x, world, r) for r in range(world)]
    meta = parts[0].to_meta()
    assert meta["part_size"] * world >= 20
    full = parts[0].full([p.data() for p in parts])
    np.testing.assert_array_equal(np.asarray(full), np.asarray(x))


def test_partitioned_tensor_uneven():
    import jax.numpy as jnp

    x = jnp.arange(7, dtype=jnp.float32)
    world = 4
    parts = [PartitionedTensor(x, world, r) for r in range(world)]
    full = parts[0].full([p.data() for p in parts])
    np.testing.assert_array_equal(np.asarray(full), np.asarray(x))
