"""Sparse page attention for the paged KV pool (ISSUE 20).

The three load-bearing acceptance properties:

- **Bit-identity escape hatch**: a window covering the whole table
  (``globals + window >= W``) makes the sparse decode/prefill jits
  gather exactly the dense page view — greedy tokens are BIT-IDENTICAL
  to the dense engine and to single-sequence ``generate()``.
- **Reference parity**: the policy's per-lane active rows, expanded to
  token granularity, equal the XLA ``layout_to_token_mask`` reference
  over ``SparseContext.layout()`` (the ops/sparse_attention mask path)
  for every query position — decode AND chunked prefill.
- **Zero-recompile pin**: with sparse armed, admission/finish churn
  across >= 20 decode steps compiles NOTHING after warmup — fixed K
  keeps the sparse jits inside the one-compile-per-program contract.

Plus the satellites: window-expired reclamation composing with
prefix-cache refcounts, admission validation, and chunked-prefill
fairness.
"""
import logging

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.models.generation import generate
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    layout_to_token_mask)
from deepspeed_tpu.runtime import comm_accounting as ca
from deepspeed_tpu.runtime import memory_accounting as ma
from deepspeed_tpu.serving.engine import InferenceEngine
from deepspeed_tpu.serving.kv_cache import TRASH_BLOCK, PagedKVPool
from deepspeed_tpu.serving.metrics import CompilationCounter
from deepspeed_tpu.serving.reliability import ABORT_EXPIRED
from deepspeed_tpu.serving.sparse_context import (SparseContext,
                                                  _policy_layout)
from deepspeed_tpu.utils.logging import logger as ds_logger


@pytest.fixture(scope="module")
def toy():
    cfg = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                     n_head=4, dtype=jnp.float32, loss_chunk_tokens=0)
    model = GPT2Model(cfg)
    ids = np.random.default_rng(0).integers(0, 97, (2, 8))
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": ids, "labels": ids})
    refs = {}

    def ref(prompt, max_new):
        key = (tuple(int(t) for t in prompt), max_new)
        if key not in refs:
            refs[key] = generate(model, params,
                                 np.asarray(prompt, np.int32)[None],
                                 max_new_tokens=max_new)[0]
        return refs[key]

    return model, params, ref


def _engine(model, params, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("kv_block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_blocks_per_seq", 16)
    return InferenceEngine(model, params, **kw)


def _prompts(seed, lens):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 97, n).astype(np.int32) for n in lens]


# ---------------------------------------------------------------------------
# policy compilation (LUTs, layout, active rows)
# ---------------------------------------------------------------------------

def test_lut_shape_and_causal_clipping():
    p = SparseContext(block_size=4, table_width=8,
                      num_sliding_window_blocks=3, num_global_blocks=2)
    assert p.K == 5 and p.lut.shape == (8, 5)
    # query block 0: only itself (global 0 IS block 0); pads are -1
    assert p.lut[0].tolist() == [0, -1, -1, -1, -1]
    # query block 1: both visible globals + itself, no duplicates
    assert p.lut[1].tolist() == [0, 1, -1, -1, -1]
    # deep query block: globals [0, 1] + window [5, 6, 7], ascending
    assert p.lut[7].tolist() == [0, 1, 5, 6, 7]
    # every row: sorted, unique, within range, causally clipped
    for qb in range(8):
        act = [b for b in p.lut[qb] if b >= 0]
        assert act == sorted(set(act)) and all(0 <= b <= qb for b in act)


def test_full_window_K_clamps_to_table_width():
    p = SparseContext(block_size=4, table_width=8,
                      num_sliding_window_blocks=8, num_global_blocks=3)
    assert p.K == 8
    for qb in range(8):
        assert [b for b in p.lut[qb] if b >= 0] == list(range(qb + 1))


def test_layout_is_causal_bslongformer_shape():
    lay = _policy_layout(3, 2, 8)
    assert lay.shape == (8, 8)
    assert np.all(np.triu(lay, 1) == 0)          # causal: never forward
    assert np.all(lay[:, :2] == np.tril(np.ones((8, 2)))[:, :2])  # anchors
    assert lay[7].tolist() == [1, 1, 0, 0, 0, 1, 1, 1]
    p = SparseContext(block_size=4, table_width=8,
                      num_sliding_window_blocks=3, num_global_blocks=2)
    np.testing.assert_array_equal(p.layout(8), lay)


def test_active_row_matches_layout_to_token_mask_reference():
    """Decode-side reference parity: the token positions a lane's active
    row exposes (sentinel pads dropped, causally clipped) equal the XLA
    ``layout_to_token_mask`` expansion of ``layout()`` at EVERY query
    position — the policy compiler and the ops/sparse_attention mask
    path agree token-for-token."""
    bs, W = 4, 16
    p = SparseContext(block_size=bs, table_width=W,
                      num_sliding_window_blocks=3, num_global_blocks=2)
    mask = np.asarray(layout_to_token_mask(p.layout(W)[None], bs))[0]
    table_row = np.arange(1, W + 1, dtype=np.int32)   # every block live
    for pos in range(W * bs):
        stables, sbase = p.active_row(table_row, pos)
        vis = {int(b) + o
               for b, k in zip(sbase, stables) if b != p.sentinel
               for o in range(bs) if int(b) + o <= pos}
        ref = {j for j in range(pos + 1) if mask[pos, j]}
        assert vis == ref, f"pos={pos}"


def test_prefill_union_row_matches_token_mask_reference():
    """Prefill-side reference parity: the chunk's union gather row
    restricted by the in-jit per-query layout mask equals the token
    mask reference for every query in the chunk."""
    bs, W, C = 4, 16, 8
    p = SparseContext(block_size=bs, table_width=W,
                      num_sliding_window_blocks=3, num_global_blocks=1)
    mask = np.asarray(layout_to_token_mask(p.layout(W)[None], bs))[0]
    lay = p.layout(W) > 0
    table_row = np.arange(1, W + 1, dtype=np.int32)
    for start in range(0, W * bs - C, C):
        stables, sbase = p.prefill_active_row(table_row, start, C, C)
        assert len(stables) == p.prefill_K(C)
        for q in range(C):
            pos = start + q
            qb = min(pos // bs, W - 1)
            vis = set()
            for b in sbase:
                if b == p.sentinel:
                    continue
                for o in range(bs):
                    j = int(b) + o
                    # the in-jit allow mask: layout[qb, key block]
                    if j <= pos and lay[qb, min(j // bs, W - 1)]:
                        vis.add(j)
            ref = {j for j in range(pos + 1) if mask[pos, j]}
            assert vis == ref, f"start={start} q={q}"


def test_active_row_maps_holes_and_pads_to_trash_sentinel():
    p = SparseContext(block_size=4, table_width=8,
                      num_sliding_window_blocks=2, num_global_blocks=1)
    # logical blocks 1..2 window-expired (trash in the table row)
    table_row = np.asarray([7, TRASH_BLOCK, TRASH_BLOCK, 5, 9, 0, 0, 0],
                           np.int32)
    stables, sbase = p.active_row(table_row, 17)   # query block 4
    # active set {0, 3, 4} -> phys {7, 5, 9}; no trash page is ever live
    assert stables.tolist() == [7, 5, 9]
    assert sbase.tolist() == [0, 12, 16]
    stables, sbase = p.active_row(table_row, 9)    # qb 2: holes in-window
    assert stables.tolist() == [7, TRASH_BLOCK, TRASH_BLOCK]
    assert sbase.tolist() == [0, int(p.sentinel), int(p.sentinel)]
    live = sbase != p.sentinel
    assert np.all(stables[~live] == TRASH_BLOCK)
    assert np.all(stables[live] != TRASH_BLOCK)


def test_first_active_block_and_prefill_K():
    p = SparseContext(block_size=4, table_width=16,
                      num_sliding_window_blocks=3, num_global_blocks=1)
    assert p.first_active_block(0) == 0
    assert p.first_active_block(11) == 0
    assert p.first_active_block(12) == 1
    assert p.first_active_block(63) == 13
    # chunk of 8 tokens spans <= 3 blocks: g + win + span
    assert p.prefill_K(8) == min(16, 1 + 3 + 3)
    assert p.prefill_K(64) == 16                    # clamps at W


def test_from_sparsity_config_object():
    class SC:                      # BSLongformer-style duck type
        num_sliding_window_blocks = 4
        global_block_indices = [0]
        global_block_end_indices = [2]

    p = SparseContext.from_sparsity_config(SC(), block_size=4,
                                           table_width=16)
    assert p.win == 3 and p.g == 2                 # w//2+1 causal clip

    class Bad:
        num_sliding_window_blocks = 4
        global_block_indices = [0, 5]              # not a leading prefix

    with pytest.raises(ValueError, match="leading prefix"):
        SparseContext.from_sparsity_config(Bad(), block_size=4,
                                           table_width=16)


# ---------------------------------------------------------------------------
# engine parity (acceptance)
# ---------------------------------------------------------------------------

def test_full_window_sparse_is_bit_identical_to_dense(toy):
    """The acceptance escape hatch: globals + window >= W makes every
    gather row the dense table — greedy tokens match the dense engine
    AND single-sequence generate() exactly."""
    model, params, ref = toy
    prompts = _prompts(3, (5, 11, 3, 9))
    maxnew = [6, 9, 12, 5]
    dense = _engine(model, params)
    sparse = _engine(model, params,
                     sparse_context={"num_sliding_window_blocks": 16,
                                     "num_global_blocks": 0})
    assert sparse.sparse is not None and sparse.sparse.K == 16
    outs = {}
    for eng in (dense, sparse):
        rids = [eng.submit(p, max_new_tokens=m)
                for p, m in zip(prompts, maxnew)]
        res = eng.serve(max_steps=500)
        outs[eng] = [res[r]["tokens"] for r in rids]
    for d, s, p, m in zip(outs[dense], outs[sparse], prompts, maxnew):
        np.testing.assert_array_equal(d, s)
        np.testing.assert_array_equal(s, ref(p, m))
    rep = sparse.serving_report()
    assert rep["config"]["sparse_context"]["active_pages_per_lane"] == 16
    assert rep["sparse_context"]["active_page_fraction"] == 1.0


def test_narrow_window_is_chunk_invariant_and_actually_sparse(toy):
    """Under a genuinely narrow window the greedy continuation must be
    IDENTICAL whichever prefill chunking produced the KV (per-query
    masking makes chunk boundaries invisible), and must DIFFER from the
    dense continuation once the prompt outgrows the window (the mask
    actually bites)."""
    model, params, ref = toy
    prompt = _prompts(5, (37,))[0]
    sc = {"num_sliding_window_blocks": 3, "num_global_blocks": 1}
    outs = []
    for chunk in (8, 16, 64):
        eng = _engine(model, params, prefill_chunk=chunk,
                      sparse_context=dict(sc))
        rid = eng.submit(prompt, max_new_tokens=8)
        eng.serve(max_steps=500)
        outs.append(eng.result(rid).tolist())
    assert outs[0] == outs[1] == outs[2]
    assert outs[0] != ref(prompt, 8).tolist()     # sparsity engaged
    frac = eng.serving_report()["sparse_context"]["active_page_fraction"]
    assert frac is not None and frac < 1.0


def test_sparse_zero_recompiles_after_warmup(toy):
    """The zero-recompile pin holds with sparse armed: fixed K keeps
    the sparse decode + bucketed prefill jits at one compile each, so
    admission/finish churn compiles nothing after warmup."""
    model, params, ref = toy
    eng = _engine(model, params,
                  sparse_context={"num_sliding_window_blocks": 2,
                                  "num_global_blocks": 1})
    eng.warmup()
    prompts = _prompts(7, (5, 11, 3, 9, 6))
    maxnew = [6, 9, 12, 5, 7]
    with CompilationCounter() as cc:
        rids = []
        for p, m in zip(prompts, maxnew):
            rids.append(eng.submit(p, max_new_tokens=m))
            eng.step()
            eng.step()
        eng.serve(max_steps=500)
    assert eng.metrics.decode_steps >= 20
    assert cc.count == 0, \
        f"{cc.count} XLA compilations during sparse steady-state churn"
    names = set(eng.program_registry.names())
    assert "sparse_decode_step" in names
    assert any(n.startswith("sparse_prefill_chunk") for n in names)


def test_window_expired_frees_shrink_resident_blocks(toy):
    """As decode slides past the window, expired private pages go back
    to the allocator mid-flight: the pool's free count recovers while
    the request is still RUNNING, and the freed total is reported."""
    model, params, _ = toy
    eng = _engine(model, params, max_slots=1,
                  sparse_context={"num_sliding_window_blocks": 2,
                                  "num_global_blocks": 1})
    prompt = _prompts(9, (30,))[0]
    rid = eng.submit(prompt, max_new_tokens=16)
    free_during = []
    steps = 0
    while eng.scheduler.has_work() and steps < 400:
        eng.step()
        steps += 1
        if rid not in eng.results:
            free_during.append(eng.pool.free_blocks(0))
    assert rid in eng.results and eng.results[rid]["tokens"].size == 46
    assert eng.pool.window_frees > 0
    assert eng.pool.stats()["window_expired_frees"] == eng.pool.window_frees
    # blocks came BACK while running (window slid past them), not only
    # at finish — the long-context residency win
    assert max(free_during) > min(free_during)
    assert eng.serving_report()["sparse_context"][
        "window_expired_frees"] > 0


# ---------------------------------------------------------------------------
# pool: window-expired reclamation x prefix-cache refcounts
# ---------------------------------------------------------------------------

def test_pool_window_expired_free_keeps_holes_and_anchors():
    cfg = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=1,
                     n_head=2, dtype=jnp.float32, loss_chunk_tokens=0)
    pool = PagedKVPool(cfg, num_blocks=16, block_size=4)
    assert pool.alloc(1, 0, 24)                      # 6 blocks
    before = list(pool._blocks[1])
    # window start at logical block 4, one global anchor kept
    n = pool.window_expired_free(1, 4, keep_blocks=1)
    assert n == 3                                    # blocks 1, 2, 3
    blocks = pool._blocks[1]
    assert blocks[0] == before[0] and blocks[1:4] == [None] * 3
    assert blocks[4:] == before[4:]
    # positional indexing preserved: holes map to trash in the table row
    row = pool.table_row(1, 8)
    assert row[0] == before[0] and list(row[1:4]) == [TRASH_BLOCK] * 3
    assert pool.blocks_of(1) == 3
    # idempotent: a second sweep over the same range frees nothing
    assert pool.window_expired_free(1, 4, keep_blocks=1) == 0
    assert pool.window_frees == 3
    pool.free(1)                                     # holes don't crash
    assert pool.free_blocks(0) == 15


def test_window_free_skips_prefix_shared_blocks_engine_level(toy):
    """Satellite: COW-attach a cached prefix that lies partly OUTSIDE
    the sparse window.  The radix tree's ownership outranks the window:
    tree-held shared pages are never window-freed, refcounts stay
    consistent, and the pool balances to empty after both finish."""
    model, params, _ = toy
    eng = _engine(model, params, max_slots=1, prefix_cache=True,
                  sparse_context={"num_sliding_window_blocks": 2,
                                  "num_global_blocks": 1})
    free0 = sum(eng.pool.free_blocks(s) for s in range(eng.pool.shards))
    shared = _prompts(11, (16,))[0]                  # 4 full blocks
    r1 = eng.submit(shared, max_new_tokens=4)
    eng.serve(max_steps=300)
    # the finished prefix is now tree-held; its blocks sit outside a
    # win=2 window almost immediately for the second request
    assert len(eng.pool.prefix_lookup(0, shared)[0]) > 0
    r2 = eng.submit(np.concatenate([shared, _prompts(12, (8,))[0]])
                    .astype(np.int32), max_new_tokens=6)
    eng.serve(max_steps=300)
    assert eng.results[r1]["tokens"].size == 20
    assert eng.results[r2]["tokens"].size == 30
    # the cached prefix SURVIVED the second request's window sweeps
    assert len(eng.pool.prefix_lookup(0, shared)[0]) > 0
    # no double-free: every non-tree block is back; the allocator's
    # books balance (tree-held blocks are the only residents)
    free_now = sum(eng.pool.free_blocks(s) for s in range(eng.pool.shards))
    held = free0 - free_now
    assert 0 < held <= 6                             # prefix + extension
    assert eng.pool.fragmentation() >= 0.0


def test_full_window_sparse_with_prefix_cache_matches_dense(toy):
    """Prefix sharing + sparse gather compose bit-identically at full
    window: COW-attached pages are gathered via the same stables row."""
    model, params, ref = toy
    shared = _prompts(13, (9,))[0]
    p2 = np.concatenate([shared, _prompts(14, (4,))[0]]).astype(np.int32)
    outs = {}
    for name, kw in (("dense", {}),
                     ("sparse", {"sparse_context":
                                 {"num_sliding_window_blocks": 16}})):
        eng = _engine(model, params, prefix_cache=True, **kw)
        ra = eng.submit(shared, max_new_tokens=5)
        eng.serve(max_steps=300)
        rb = eng.submit(p2, max_new_tokens=5)
        eng.serve(max_steps=300)
        outs[name] = (eng.results[ra]["tokens"], eng.results[rb]["tokens"])
        if name == "sparse":
            assert eng.metrics.prefix_hits >= 1
    np.testing.assert_array_equal(outs["dense"][0], outs["sparse"][0])
    np.testing.assert_array_equal(outs["dense"][1], outs["sparse"][1])
    np.testing.assert_array_equal(outs["sparse"][0], ref(shared, 5))


# ---------------------------------------------------------------------------
# admission validation (satellite)
# ---------------------------------------------------------------------------

def test_submit_rejects_oversized_prompt_with_actionable_error(toy):
    model, params, _ = toy
    eng = _engine(model, params)                     # capacity 16*4 = 64
    with pytest.raises(AssertionError) as e:
        eng.submit(_prompts(15, (60,))[0], max_new_tokens=10)
    msg = str(e.value)
    assert "70" in msg and "64" in msg               # the numbers, named
    assert "capacity" in msg and "blocks" in msg     # and the knobs


def test_submit_rejects_nonpositive_deadline(toy):
    model, params, _ = toy
    eng = _engine(model, params)
    with pytest.raises(ValueError, match="deadline_s=0"):
        eng.submit(_prompts(15, (5,))[0], max_new_tokens=4, deadline_s=0)
    with pytest.raises(ValueError, match="positive"):
        eng.submit(_prompts(15, (5,))[0], max_new_tokens=4,
                   deadline_s=-1.5)


def test_submit_rejects_deadline_impossible_max_new(toy, caplog):
    """A deadline even PERFECT service cannot meet is rejected at
    admission — status expired, prompt echoed, zero prefill work — and
    the warning names the lower bound and both remedies."""
    model, params, _ = toy
    eng = _engine(model, params)
    r0 = eng.submit(_prompts(16, (5,))[0], max_new_tokens=4)
    eng.serve(max_steps=200)                         # establish step EMA
    assert eng.metrics.step_time() is not None
    ds_logger.propagate = True
    try:
        with caplog.at_level(logging.WARNING):
            rid = eng.submit(_prompts(16, (8,))[0], max_new_tokens=40,
                             deadline_s=1e-9)
    finally:
        ds_logger.propagate = False
    assert eng.results[rid]["status"] == ABORT_EXPIRED
    assert eng.results[rid]["tokens"].size == 8      # prompt only
    assert any("deadline-impossible" in r.message for r in caplog.records)
    # feasible-in-isolation is NEVER predictively rejected
    r2 = eng.submit(_prompts(16, (5,))[0], max_new_tokens=4,
                    deadline_s=3600.0)
    eng.serve(max_steps=200)
    assert eng.results[r2]["tokens"].size == 9
    del r0


# ---------------------------------------------------------------------------
# chunked-prefill fairness (scheduler + engine)
# ---------------------------------------------------------------------------

def test_prefill_fairness_pauses_long_prompt_for_short(toy):
    """With a 1-chunk quantum, a giant prompt yields its lane after
    every chunk: the short request's first token lands BEFORE the giant
    finishes prefill, and both streams still match generate() exactly
    (pausing keeps the slot's pool pages and prefill progress)."""
    model, params, ref = toy
    long_p, short_p = _prompts(17, (33, 3))
    done_order = {}

    def run(fairness):
        eng = _engine(model, params, max_slots=2, prefill_chunk=8,
                      prefill_fairness=fairness)
        rl = eng.submit(long_p, max_new_tokens=4)
        rs = eng.submit(short_p, max_new_tokens=4)
        steps = 0
        order = []
        while eng.scheduler.has_work() and steps < 400:
            eng.step()
            steps += 1
            for r in (rl, rs):
                if r in eng.results and r not in order:
                    order.append(r)
        np.testing.assert_array_equal(eng.results[rl]["tokens"],
                                      ref(long_p, 4))
        np.testing.assert_array_equal(eng.results[rs]["tokens"],
                                      ref(short_p, 4))
        done_order[fairness] = [("long" if r == rl else "short")
                                for r in order]
        return eng

    run(0)
    assert done_order[0] == ["long", "short"]        # FCFS starves short
    eng = run(1)
    assert done_order[1] == ["short", "long"]        # fairness preempts
    assert eng.serving_report()["config"]["prefill_fairness"] == 1


def test_prefill_fairness_quantum_bounds_pauses(toy):
    """A larger quantum pauses less: with quantum >= total chunks the
    giant never yields (degenerates to FCFS), so fairness is a dial."""
    model, params, ref = toy
    long_p, short_p = _prompts(18, (33, 3))
    eng = _engine(model, params, max_slots=2, prefill_chunk=8,
                  prefill_fairness=10)
    rl = eng.submit(long_p, max_new_tokens=4)
    rs = eng.submit(short_p, max_new_tokens=4)
    eng.serve(max_steps=400)
    np.testing.assert_array_equal(eng.results[rl]["tokens"],
                                  ref(long_p, 4))
    np.testing.assert_array_equal(eng.results[rs]["tokens"],
                                  ref(short_p, 4))
    assert not eng.scheduler.paused


# ---------------------------------------------------------------------------
# DISARMED discipline
# ---------------------------------------------------------------------------

def _warns_disarmed(caplog, fn):
    ds_logger.propagate = True
    try:
        with caplog.at_level(logging.WARNING):
            eng = fn()
    finally:
        ds_logger.propagate = False
    assert any("sparse context: DISARMED" in r.message
               for r in caplog.records)
    assert eng.sparse is None and eng._decode_name == "decode_step"
    return eng


def test_sparse_disarms_on_misaligned_window_tokens(toy, caplog):
    model, params, _ = toy
    eng = _warns_disarmed(caplog, lambda: _engine(
        model, params, sparse_context={"window_tokens": 10}))
    # the warning suggests both block-aligned roundings
    assert any("8 or 12" in r.message.replace("\n", " ")
               for r in caplog.records) or \
        any("Round the window" in r.message for r in caplog.records)
    del eng


def test_sparse_disarms_on_beam_width(toy, caplog):
    model, params, _ = toy
    _warns_disarmed(caplog, lambda: _engine(
        model, params,
        sparse_context={"num_sliding_window_blocks": 2, "beam_width": 4}))


def test_sparse_disarms_under_speculation(toy, caplog):
    model, params, _ = toy
    eng = _warns_disarmed(caplog, lambda: _engine(
        model, params, speculative=3,
        sparse_context={"num_sliding_window_blocks": 2}))
    assert eng.spec_k == 3                           # speculation wins


def test_sparse_disarms_on_mismatched_prebuilt_context(toy, caplog):
    model, params, _ = toy
    wrong = SparseContext(block_size=8, table_width=4,
                          num_sliding_window_blocks=2)
    _warns_disarmed(caplog, lambda: _engine(
        model, params, sparse_context=wrong))


def test_sparse_disarms_on_nonprefix_globals(toy, caplog):
    class SC:
        num_sliding_window_blocks = 4
        global_block_indices = [0, 7]

    model, params, _ = toy
    _warns_disarmed(caplog, lambda: _engine(
        model, params, sparse_context=SC()))


def test_window_tokens_arms_when_block_aligned(toy):
    model, params, _ = toy
    eng = _engine(model, params, sparse_context={"window_tokens": 12})
    assert eng.sparse is not None and eng.sparse.win == 3
    assert eng._decode_name == "sparse_decode_step"


# ---------------------------------------------------------------------------
# accounting + metrics
# ---------------------------------------------------------------------------

def test_sparse_kv_blocks_per_seq():
    # short sequences: bounded by their own length
    assert ma.sparse_kv_blocks_per_seq(
        1000, 512, num_sliding_window_blocks=8, num_global_blocks=2) == 2
    # long sequences: bounded by the policy
    assert ma.sparse_kv_blocks_per_seq(
        32768, 512, num_sliding_window_blocks=8, num_global_blocks=2) == 10
    dense = -(-32768 // 512)
    assert dense == 64                               # the 6.4x story


def test_serving_gather_and_flops_scale_with_active_pages():
    kw = dict(batch=2, kv_dtype="bfloat16")
    dense = ca.serving_gather_bytes_per_step(24, 16, 512, 64, pages=64,
                                             **kw)
    sparse = ca.serving_gather_bytes_per_step(24, 16, 512, 64, pages=10,
                                              **kw)
    assert dense == sparse * 64 // 10 or dense / sparse == 6.4
    q = ca.serving_gather_bytes_per_step(24, 16, 512, 64, pages=10,
                                         batch=2, quantized=True)
    assert q < sparse                                # int8 + scales < bf16
    f_dense = ca.serving_decode_attn_flops(24, 16, 64, attended=32768)
    f_sparse = ca.serving_decode_attn_flops(24, 16, 64, attended=5120)
    assert f_dense / f_sparse == 6.4


def test_metrics_active_page_fraction_honest_gap():
    from deepspeed_tpu.serving.metrics import ServingMetrics
    m = ServingMetrics()
    assert m.active_page_fraction() is None          # no gathers yet
    m.record_gather(2, 20, 128, 18)
    m.record_gather(2, 20, 128, 16)
    assert m.active_page_fraction() == 40 / 256
    m.record_window_expired(3)
    rep = m.report()["sparse_context"]
    assert rep["window_expired_frees"] == 3
    assert rep["gathered_pages_per_lane_step"] == 10.0
    assert rep["active_pages_per_lane_step"] == 8.5
    m.record_submit(1, klass="short")
    m.record_submit(2, klass="long")
    assert m.class_ttft_p95("short") is None         # no tokens yet
