"""Flash-attention kernel parity vs the jnp reference path.

Mirrors the reference's kernel parity strategy
(reference tests/unit/test_cuda_forward.py / test_cuda_backward.py: fused
kernel vs Python BertEncoder with atol~1e-2); here the Pallas kernel runs in
interpreter mode on the CPU mesh and is compared against the dense jnp
softmax-attention implementation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.flash_attention import flash_attention
from deepspeed_tpu.ops.transformer.functional import scaled_dot_product_attention


def _rand_qkv(rng, b, h, s, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, h, s, d), dtype)
    v = jax.random.normal(kv, (b, h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s,d", [(128, 64), (256, 64)])
def test_flash_forward_matches_reference(causal, s, d):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 2, 2, s, d)
    ref = scaled_dot_product_attention(q, k, v, causal=causal, use_pallas=False)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    s, d = 128, 64
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), 1, 2, s, d)

    def loss_ref(q, k, v):
        o = scaled_dot_product_attention(q, k, v, causal=causal, use_pallas=False)
        return jnp.sum(jnp.sin(o))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(jnp.sin(o))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


def test_flash_multiblock_causal_grad():
    # multiple q/k blocks exercises the block-skip logic under causality
    s, d = 256, 64
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), 1, 1, s, d)

    def loss_fl(args):
        o = flash_attention(*args, causal=True, block_q=128, block_k=128,
                            interpret=True)
        return jnp.mean(o ** 2)

    def loss_ref(args):
        o = scaled_dot_product_attention(*args, causal=True, use_pallas=False)
        return jnp.mean(o ** 2)

    g_fl = jax.grad(loss_fl)((q, k, v))
    g_ref = jax.grad(loss_ref)((q, k, v))
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6, rtol=5e-4)


@pytest.mark.parametrize("kind", ["key", "full"])
def test_flash_bias_matches_reference(kind):
    """Additive bias (HF extended mask / full scores bias) in-kernel must
    match the jnp reference path, forward and q/k/v gradients."""
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention
    from deepspeed_tpu.ops.transformer.functional import (
        scaled_dot_product_attention)

    rng = np.random.default_rng(3)
    B, H, S, D = 2, 3, 256, 64
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    if kind == "key":
        # key-padding: mask out the tail keys of each batch row
        bias = np.zeros((B, 1, 1, S), np.float32)
        bias[0, ..., 200:] = -1e9
        bias[1, ..., 100:] = -1e9
    else:
        bias = rng.standard_normal((B, H, S, S)).astype(np.float32)
    bias = jnp.asarray(bias)

    ref = scaled_dot_product_attention(q, k, v, bias=bias, use_pallas=False)
    got = flash_attention(q, k, v, bias=bias, interpret=True,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)

    def loss_ref(q, k, v):
        return scaled_dot_product_attention(
            q, k, v, bias=bias, use_pallas=False).sum()

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, bias=bias, interpret=True,
                               block_q=128, block_k=128).sum()

    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2)


def test_flash_bias_constant_no_grad():
    """The kernel treats bias as constant: its cotangent is zero (a learned
    bias must use the jnp path — functional._pallas_attention_ok guards the
    auto-dispatch accordingly)."""
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    B, H, S, D = 1, 2, 128, 64
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal((B, 1, 1, S)), jnp.float32)
    g = jax.grad(lambda b: flash_attention(
        q, q, q, bias=b, interpret=True).sum())(bias)
    np.testing.assert_array_equal(np.asarray(g), 0.0)


def test_boolean_keypad_mask_dispatches_and_matches():
    """A boolean keep-mask (B,1,1,S) converts to additive bias in-kernel and
    matches the jnp reference path."""
    from deepspeed_tpu.ops.transformer.functional import (
        scaled_dot_product_attention)

    rng = np.random.default_rng(5)
    B, H, S, D = 2, 2, 256, 64
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    mask = np.ones((B, 1, 1, S), bool)
    mask[0, ..., 180:] = False
    mask = jnp.asarray(mask)
    ref = scaled_dot_product_attention(q, q, q, mask=mask, use_pallas=False)
    got = scaled_dot_product_attention(q, q, q, mask=mask, use_pallas=True)
    # compare only unmasked query rows? mask is over KEYS: all rows valid
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# in-kernel counter-based dropout
# ---------------------------------------------------------------------------

def _flash(q, k, v, **kw):
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

    return flash_attention(q, k, v, interpret=True, **kw)


def test_dropout_zero_rate_matches_no_dropout():
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((1, 2, 128, 64)), jnp.float32)
    base = _flash(q, q, q)
    # rate 0 never builds the seeded path, seed ignored
    same = _flash(q, q, q, dropout_rate=0.0, dropout_seed=123)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(same))


def test_dropout_deterministic_per_seed():
    rng = np.random.default_rng(8)
    q = jnp.asarray(rng.standard_normal((1, 2, 128, 64)), jnp.float32)
    a = _flash(q, q, q, dropout_rate=0.3, dropout_seed=5)
    b = _flash(q, q, q, dropout_rate=0.3, dropout_seed=5)
    c = _flash(q, q, q, dropout_rate=0.3, dropout_seed=6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(jnp.max(jnp.abs(a - c))) > 1e-4, "seed has no effect"


def test_dropout_mean_preserving():
    """E[dropout(attn)] == attn: average over many seeds approaches the
    undropped output (inverted-scaling check)."""
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((1, 2, 128, 64)), jnp.float32)
    base = np.asarray(_flash(q, q, q))
    acc = np.zeros_like(base)
    n = 24
    for s in range(n):
        acc += np.asarray(_flash(q, q, q, dropout_rate=0.4,
                                 dropout_seed=1000 + s))
    mean = acc / n
    # per-element agreement is noisy at n=24; the overall scale must match
    np.testing.assert_allclose(mean.mean(), base.mean(), rtol=0.05,
                               atol=0.02)
    np.testing.assert_allclose(
        np.abs(mean).mean(), np.abs(base).mean(), rtol=0.15)


def test_dropout_gradients_match_forward_mask():
    """Finite-difference check: backward regenerates the same keep mask
    the forward used (a mask mismatch fails check_grads immediately)."""
    from jax.test_util import check_grads

    rng = np.random.default_rng(10)
    q = jnp.asarray(rng.standard_normal((1, 1, 128, 64)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 1, 128, 64)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 1, 128, 64)) * 0.3, jnp.float32)

    def f(q, k, v):
        return _flash(q, k, v, dropout_rate=0.25, dropout_seed=42,
                      causal=True).astype(jnp.float32).sum()

    check_grads(f, (q, k, v), order=1, modes=["rev"], rtol=2e-2, atol=2e-2)


def test_dropout_causal_blocks_consistent():
    """Multi-block grid (block 128 over seq 256): dropout + causal combine
    without breaking row normalization: rows with all-kept slots still
    average to the undropped scale across seeds."""
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((1, 1, 256, 64)), jnp.float32)
    base = np.asarray(_flash(q, q, q, causal=True, block_q=128, block_k=128))
    acc = np.zeros_like(base)
    n = 16
    for s in range(n):
        acc += np.asarray(_flash(q, q, q, causal=True, dropout_rate=0.3,
                                 dropout_seed=s, block_q=128, block_k=128))
    np.testing.assert_allclose((acc / n).mean(), base.mean(), rtol=0.1,
                               atol=0.03)


def test_dropout_dispatch_from_functional():
    """scaled_dot_product_attention routes dropout to the kernel when a
    rng is provided and use_pallas=True is forced (CPU backend here)."""
    from deepspeed_tpu.ops.transformer.functional import (
        scaled_dot_product_attention)

    rng = np.random.default_rng(12)
    q = jnp.asarray(rng.standard_normal((1, 2, 128, 64)), jnp.float32)
    out = scaled_dot_product_attention(
        q, q, q, causal=True, dropout_rng=jax.random.PRNGKey(0),
        dropout_rate=0.2, use_pallas=True)
    ref = scaled_dot_product_attention(q, q, q, causal=True,
                                       use_pallas=True)
    assert out.shape == q.shape
    assert float(jnp.max(jnp.abs(out - ref))) > 1e-4

def test_dropout_gradients_multiblock():
    """Same FD guard across a multi-block grid: the regenerated masks must
    use the right (q_start, k_start) offsets in BOTH backward sweeps."""
    from jax.test_util import check_grads

    rng = np.random.default_rng(13)
    q = jnp.asarray(rng.standard_normal((1, 1, 256, 64)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 1, 256, 64)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 1, 256, 64)) * 0.3, jnp.float32)

    def f(q, k, v):
        return _flash(q, k, v, dropout_rate=0.25, dropout_seed=7,
                      causal=True, block_q=128, block_k=128)\
            .astype(jnp.float32).sum()

    check_grads(f, (q, k, v), order=1, modes=["rev"], rtol=2e-2, atol=2e-2)


def test_lse_compact_wire_format_matches(monkeypatch):
    """DSTPU_FLASH_LSE2D=1 carries lse/delta as compact (bh, s_q) tiles
    instead of 128-lane broadcasts; outputs and gradients must be
    bit-identical to the legacy layout (it is pure wire format)."""
    import deepspeed_tpu.ops.transformer.flash_attention as fa

    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.standard_normal((1, 2, 256, 64)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 2, 256, 64)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 2, 256, 64)) * 0.3, jnp.float32)

    def run():
        def f(q, k, v):
            return fa.flash_attention(
                q, k, v, causal=True, block_q=128, block_k=128,
                interpret=True).astype(jnp.float32).sum()
        return f(q, k, v), jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    monkeypatch.setattr(fa, "_LSE_2D", False)
    base_loss, base_g = run()
    monkeypatch.setattr(fa, "_LSE_2D", True)
    new_loss, new_g = run()
    np.testing.assert_array_equal(np.asarray(base_loss), np.asarray(new_loss))
    for a, b in zip(base_g, new_g):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
