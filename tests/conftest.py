"""Test harness: run everything on a virtual 8-device CPU mesh.

TPU-build analog of the reference's @distributed_test fork-N-processes harness
(reference tests/unit/common.py:16-104): instead of spawning N NCCL processes we
give XLA 8 virtual CPU devices, so mesh/sharding/collective logic runs exactly
as it would across chips.
"""
import os

# force the CPU mesh even when a TPU plugin (axon) injects itself into
# jax_platforms; opt out with DSTPU_TEST_PLATFORM=tpu to run on real hardware
_platform = os.environ.get("DSTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform

import jax  # noqa: E402

if _platform == "cpu":
    # NOT redundant with the env var: the axon TPU plugin prepends itself to
    # jax_platforms at import ("axon,cpu") even when JAX_PLATFORMS=cpu is set;
    # only an explicit config update wins.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "multiprocess: spawns a real 2-process jax.distributed world")
