"""Test harness: run everything on a virtual 8-device CPU mesh.

TPU-build analog of the reference's @distributed_test fork-N-processes harness
(reference tests/unit/common.py:16-104): instead of spawning N NCCL processes we
give XLA 8 virtual CPU devices, so mesh/sharding/collective logic runs exactly
as it would across chips.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]
