"""Test harness: run everything on a virtual 8-device CPU mesh.

TPU-build analog of the reference's @distributed_test fork-N-processes harness
(reference tests/unit/common.py:16-104): instead of spawning N NCCL processes we
give XLA 8 virtual CPU devices, so mesh/sharding/collective logic runs exactly
as it would across chips.
"""
import os

# force the CPU mesh even when a TPU plugin (axon) injects itself into
# jax_platforms; opt out with DSTPU_TEST_PLATFORM=tpu to run on real hardware
_platform = os.environ.get("DSTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
def _jax_has_num_cpu_devices_config():
    # decided BEFORE importing jax (the XLA flag must be in the env first);
    # the jax_num_cpu_devices config option landed in jax 0.5
    try:
        from importlib.metadata import version

        major, minor = (int(p) for p in version("jax").split(".")[:2])
        return (major, minor) >= (0, 5)
    except Exception:
        return False


_use_xla_flag = False
if _platform == "cpu":
    # jax >= 0.5 rejects setting BOTH the XLA flag and jax_num_cpu_devices,
    # so exactly one mechanism is used: the flag on older jax (which only
    # honors the flag, set before the backend initializes) or one already
    # present in the user's XLA_FLAGS, else the config option below
    _flags = os.environ.get("XLA_FLAGS", "")
    _use_xla_flag = "xla_force_host_platform_device_count" in _flags
    if not _use_xla_flag and not _jax_has_num_cpu_devices_config():
        os.environ["XLA_FLAGS"] = \
            (_flags + " --xla_force_host_platform_device_count=8").strip()
        _use_xla_flag = True

import jax  # noqa: E402

if _platform == "cpu":
    # NOT redundant with the env var: the axon TPU plugin prepends itself to
    # jax_platforms at import ("axon,cpu") even when JAX_PLATFORMS=cpu is set;
    # only an explicit config update wins.
    jax.config.update("jax_platforms", "cpu")
    if not _use_xla_flag:
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:  # version sniff was wrong; tests then see a
            pass                # 1-device mesh and fail loudly, not at import
jax.config.update("jax_threefry_partitionable", True)

from deepspeed_tpu.utils.jax_compat import ensure_compat  # noqa: E402

ensure_compat()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "multiprocess: spawns a real 2-process jax.distributed world")
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy / long-running tests (parallelism matrices, "
        "HF interop, e2e convergence). Fast gate: "
        "pytest -m 'not slow and not multiprocess'")


# Tests measured >= ~5 s on the 1-core reference box (pytest --durations,
# round 5): auto-marked `slow` here so the fast gate stays under 5 minutes
# without sprinkling decorators through every file. Explicit
# @pytest.mark.slow in a test file works too — this list is additive.
# Names match the node id up to (not including) any [param] suffix.
_SLOW_TESTS = {
    "test_checkpointing.py": {
        "test_elastic_restage", "test_orbax_backend_roundtrip",
        "test_roundtrip"},
    "test_cpu_adam.py": {
        "test_engine_offload_e2e",
        "test_engine_offload_gas_accumulation_matches"},
    "test_csr.py": {
        "test_csr_dp_armed_only_where_layout_survives",
        "test_csr_dp_collective_bytes_scale_with_tokens_not_vocab",
        "test_csr_dp_matches_dense_trajectory",
        "test_sparse_gradients_offload_matches_dense"},
    "test_engine.py": {
        "test_bf16_training", "test_chunked_lm_cross_entropy_matches_dense",
        "test_empty_grad_params", "test_fp16_dynamic_scale_training",
        "test_fp32_convergence", "test_gpt2_scan_layers_trains",
        "test_gradient_accumulation_equivalence",
        "test_loss_scale_doubles_after_window",
        "test_overflow_skips_step_and_halves_scale", "test_scheduler_wiring",
        "test_static_loss_scale", "test_train_batch_fused_path"},
    "test_flash_attention.py": {
        "test_dropout_causal_blocks_consistent",
        "test_dropout_gradients_multiblock", "test_dropout_mean_preserving",
        "test_flash_backward_matches_reference",
        "test_flash_bias_constant_no_grad",
        "test_flash_bias_matches_reference",
        "test_flash_multiblock_causal_grad"},
    "test_generation.py": {
        "test_greedy_generation_matches_transformers",
        "test_greedy_matches_full_forward",
        "test_moe_generation_matches_training_forward"},
    "test_moe.py": {
        "test_eval_capacity_factor", "test_gpt2_moe_trains_on_engine",
        "test_moe_elastic_checkpoint_dp8_to_dp4",
        "test_moe_grads_reach_all_params",
        "test_moe_matches_per_token_expert_math",
        "test_moe_sharded_matches_single_device",
        "test_moe_with_tensor_parallel_matches_dp_only",
        "test_moe_with_zero_offload_trains",
        "test_pipeline_moe_depth_invariant", "test_pipeline_moe_router_learns",
        "test_router_z_loss", "test_single_expert_matches_dense_ffn"},
    "test_onebit.py": {
        "test_engine_with_onebit_adam",
        "test_onebit_adam_converges_after_freeze",
        "test_onebit_wire_gpt2_with_sharding_constraints",
        "test_onebit_wire_saves_gradient_bytes",
        "test_onebit_wire_trains_through_freeze"},
    "test_pipe.py": {
        "test_gpt2_pipe_single_stage_int_input",
        "test_pipe_4stage_matches_1stage", "test_pipe_checkpoint_restage",
        "test_pipe_checkpoint_restage_tied", "test_pipe_checkpoint_roundtrip",
        "test_pipe_checkpoint_roundtrip_bf16",
        "test_pipe_tied_matches_sequential",
        "test_pipe_tied_weights_stay_in_sync",
        "test_pipe_tied_with_clipping_matches_sequential",
        "test_pipe_tp_3d_matches_no_tp",
        "test_pipe_tp_params_sharded_over_model",
        "test_pipe_with_data_parallel_matches", "test_pipe_zero1"},
    "test_run.py": {"test_launch_sets_env"},
    "test_transformer_layer.py": {"test_bert_pretraining_e2e"},
    "test_ulysses.py": {
        "test_bert_fused_layer_seq_axis_parity",
        "test_engine_ring_mode_matches_dp_only",
        "test_engine_seq_axis_matches_dp_only",
        "test_pipeline_with_seq_axis_matches_pipe_only"},
    "test_vocab_padding.py": {"test_pad_rows_get_no_gradient"},
    "test_zero.py": {
        "test_zero2_accum_partitioned", "test_zero3_params_sharded_and_parity",
        "test_zero_stages_same_trajectory", "test_zero_state_is_partitioned"},
}


def pytest_collection_modifyitems(config, items):
    matched = set()
    collected_files = set()
    for item in items:
        fname = item.fspath.basename
        collected_files.add(fname)
        base = item.name.split("[", 1)[0]
        if base in _SLOW_TESTS.get(fname, ()):
            item.add_marker(pytest.mark.slow)
            matched.add((fname, base))
    # a renamed/deleted test must not silently rejoin the fast gate: flag
    # stale _SLOW_TESTS entries (only for files actually collected, so
    # running a single other file doesn't spray warnings; node-id selection
    # like file.py::test_x legitimately deselects siblings, so skip then)
    if any("::" in str(a) for a in config.args):
        return
    for fname, names in _SLOW_TESTS.items():
        if fname not in collected_files:
            continue
        for base in names:
            if (fname, base) not in matched:
                import warnings

                warnings.warn(
                    f"tests/conftest.py _SLOW_TESTS entry {fname}::{base} "
                    "matches no collected test — renamed or deleted? The "
                    "test (if renamed) now runs in the fast gate unmarked.")
