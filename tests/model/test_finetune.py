"""Model-level fine-tune convergence: BERT classification on real text.

TPU analog of the reference's SQuAD e2e fine-tune test
(reference tests/model/BingBertSquad/test_e2e_squad.py: fine-tune BERT
through the engine and require the task metric to land). SQuAD data isn't
available offline, so the task here is real-text provenance
classification: byte-chunks of English prose (tests/model/corpus.txt)
vs Python source (tests/model/corpus_code.txt — both frozen snapshots),
labeled by origin. A BERT encoder with the NSP head fine-tunes on it
through the full engine path; held-out accuracy must clear a margin, and
the ZeRO/offload variants must follow the same trajectory (fine-tuning,
like pretraining, is a memory-layout choice, not a math change).

Runs on the virtual 8-device CPU mesh; marked slow.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.bert import BertConfig, BertForPreTraining

pytestmark = pytest.mark.slow

SEQ = 64
BATCH = 8
STEPS = 120


def _task_rows():
    """(ids, labels): byte chunks, prose=0 / code=1, shuffled."""
    rows, labels = [], []
    for label, name in enumerate(("corpus.txt", "corpus_code.txt")):
        p = os.path.join(os.path.dirname(__file__), name)
        with open(p, "rb") as f:
            text = np.frombuffer(f.read(), np.uint8).astype(np.int32)
        n = (len(text) // SEQ) * SEQ
        chunks = text[:n].reshape(-1, SEQ)
        rows.append(chunks)
        labels.append(np.full((len(chunks),), label, np.int32))
    ids = np.concatenate(rows)
    y = np.concatenate(labels)
    order = np.random.default_rng(0).permutation(len(ids))
    return ids[order], y[order]


def _batches(ids, y, start, steps):
    out = []
    for i in range(steps):
        lo = (start + i * BATCH) % (len(ids) - BATCH)
        out.append({
            "input_ids": ids[lo:lo + BATCH][None],
            # all positions unmasked-LM-ignored: pure classification
            "masked_lm_labels": np.full((1, BATCH, SEQ), -100, np.int32),
            "next_sentence_label": y[lo:lo + BATCH][None],
        })
    return out


class _ClassifierModel(BertForPreTraining):
    """BertForPreTraining already carries the NSP (2-class) head and its
    loss; with every MLM label ignored the objective is pure
    classification, mirroring the reference's task-head fine-tune."""


def _model():
    return _ClassifierModel(BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=4,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=SEQ, dtype=jnp.float32,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))


def _config(extra=None):
    cfg = {"train_batch_size": BATCH, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 3e-4}},
           "mesh": {"data": 8}, "steps_per_print": 10 ** 9}
    if extra:
        cfg.update(extra)
    return cfg


def _accuracy(model, params, ids, y):
    logits, nsp = model.module.apply(
        {"params": params}, jnp.asarray(ids), None, train=False)
    pred = np.asarray(jnp.argmax(nsp, axis=-1))
    return float((pred == y).mean())


def _run(extra=None):
    ids, y = _task_rows()
    train_n = len(ids) - 64
    model = _model()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config_params=_config(extra))
    curve = [float(jax.device_get(engine.train_batch(batch=b)))
             for b in _batches(ids[:train_n], y[:train_n], 0, STEPS)]
    params = jax.device_get(engine.state.params)
    acc = _accuracy(model, params, ids[train_n:], y[train_n:])
    return curve, acc


@pytest.fixture(scope="module")
def base_run():
    return _run()


def test_finetune_learns_the_task(base_run):
    curve, acc = base_run
    assert curve[-1] < curve[0], (curve[0], curve[-1])
    # two-way classification on held-out chunks: must beat chance by a
    # clear margin (the two halves have distinct byte statistics)
    assert acc > 0.75, acc


def test_finetune_zero2_matches(base_run):
    curve, acc = _run({"zero_optimization": {"stage": 2}})
    np.testing.assert_allclose(curve, base_run[0], rtol=2e-3, atol=2e-3)
    assert acc > 0.75, acc


def test_finetune_offload_matches(base_run):
    curve, acc = _run({"zero_optimization": {"stage": 2,
                                             "cpu_offload": True}})
    np.testing.assert_allclose(curve, base_run[0], rtol=2e-2, atol=2e-2)
    assert acc > 0.75, acc
