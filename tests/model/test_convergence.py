"""Model-level convergence: real text, full training runs, config matrix.

TPU analog of the reference's e2e loss-curve comparisons
(reference tests/model/Megatron_GPT2/run_func_test.py: train the same model
under zero0/1/2/offload/pipeline variants and require matching curves).
Here a byte-level GPT-2 trains on a real text corpus (this repo's own docs
— deterministic, no network) for a couple hundred steps per config:

- zero0 / zero1 / zero2 must produce the SAME loss curve (ZeRO stages are
  memory layouts, not math changes) within float tolerance;
- zero2 + cpu offload follows the same curve (host fp32 Adam vs device
  Adam) within a looser tolerance;
- pipeline x2 trains its own init but must converge to the same
  neighborhood and strictly decrease.

Runs on the virtual 8-device CPU mesh; marked slow (compile-heavy).
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

pytestmark = pytest.mark.slow

STEPS = 150
SEQ = 64
BATCH = 8          # global batch (8 data ranks x micro 1)
VOCAB = 256        # byte-level


def _corpus_ids():
    """Byte-tokenize real prose (a frozen snapshot of this repo's docs —
    corpus.txt; frozen so the loss thresholds below never drift when the
    live docs are edited) into (N, SEQ) rows."""
    p = os.path.join(os.path.dirname(__file__), "corpus.txt")
    with open(p, "rb") as f:
        text = f.read()
    assert len(text) > STEPS * BATCH, "corpus too small"
    ids = np.frombuffer(text, np.uint8).astype(np.int32)
    n = (len(ids) // SEQ) * SEQ
    return ids[:n].reshape(-1, SEQ)


def _batches(rows, steps=STEPS, batch=BATCH):
    """Deterministic batch stream cycling the corpus."""
    rng = np.random.default_rng(0)
    order = rng.permutation(len(rows))
    out = []
    for i in range(steps):
        take = [order[(i * batch + j) % len(rows)] for j in range(batch)]
        chunk = rows[take]
        out.append({"input_ids": chunk[None], "labels": chunk[None].copy()})
    return out


def _gpt2():
    return GPT2Model(GPT2Config(
        vocab_size=VOCAB, n_positions=SEQ, n_embd=64, n_layer=4, n_head=4,
        dtype=jnp.float32, loss_chunk_tokens=0))


def _config(extra=None):
    cfg = {"train_batch_size": BATCH, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "mesh": {"data": 8}, "steps_per_print": 10 ** 9}
    if extra:
        cfg.update(extra)
    return cfg


def _run(extra=None):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=_gpt2(), config_params=_config(extra))
    return [float(jax.device_get(engine.train_batch(batch=b)))
            for b in _batches(_corpus_ids())]


@pytest.fixture(scope="module")
def zero0_curve():
    return _run()


def test_zero0_learns_real_text(zero0_curve):
    """The curve must actually model the corpus: large first-loss drop and
    a final loss far below ln(256) = 5.55 uniform-guess entropy (measured
    3.16 on the frozen corpus; 3.6 leaves noise margin while still proving
    a >1.9-nat gain over the uniform guess)."""
    assert zero0_curve[0] > 4.0, zero0_curve[0]
    assert zero0_curve[-1] < 3.6, zero0_curve[-1]
    # decreasing trend, not just endpoints
    thirds = np.array_split(np.asarray(zero0_curve), 3)
    assert thirds[0].mean() > thirds[1].mean() > thirds[2].mean()


@pytest.mark.parametrize("stage", [1, 2])
def test_zero_stages_follow_zero0_curve(zero0_curve, stage):
    curve = _run({"zero_optimization": {"stage": stage}})
    np.testing.assert_allclose(curve, zero0_curve, rtol=2e-3, atol=2e-3)


def test_offload_follows_zero0_curve(zero0_curve):
    curve = _run({"zero_optimization": {"stage": 2, "cpu_offload": True}})
    # host fp32 Adam (C++/numpy) vs device Adam: same math, different
    # accumulation order
    np.testing.assert_allclose(curve, zero0_curve, rtol=2e-2, atol=2e-2)


def test_pipeline_converges_to_same_neighborhood(zero0_curve):
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module

    module = gpt2_pipeline_module(
        GPT2Config(vocab_size=VOCAB, n_positions=SEQ, n_embd=64, n_layer=4,
                   n_head=4, dtype=jnp.float32),
        partition_method="uniform")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, config_params=_config(
            {"mesh": {"pipe": 2, "data": 4},
             "gradient_accumulation_steps": 2}))
    # same 8 rows per step, laid out (gas=2, dp*micro=4, S) for 1F1B
    curve = [float(jax.device_get(engine.train_batch(
                 batch={k: v.reshape(2, 4, SEQ) for k, v in b.items()})))
             for b in _batches(_corpus_ids())]
    assert all(np.isfinite(curve))
    # different init (LayerSpec RNG), same task: must land in the same
    # neighborhood and keep the decreasing trend
    thirds = np.array_split(np.asarray(curve), 3)
    assert thirds[0].mean() > thirds[2].mean()
    assert abs(curve[-1] - zero0_curve[-1]) < 0.8, (
        curve[-1], zero0_curve[-1])
