"""DeepSpeedCPUAdam — host-memory Adam for ZeRO-Offload.

Reference behavior: ops/adam/cpu_adam.py:12-147 over csrc/adam/cpu_adam.cpp
(AVX SIMD + OpenMP step with fused fp16 copy-back). Here the optimizer
state lives in host numpy arrays (the TPU-VM's RAM), the step runs the C++
kernel via ctypes (ops/op_builder.py), and the updated params are converted
to the compute dtype in the same pass for the host->HBM transfer. Falls
back to a vectorized numpy implementation when no toolchain is available.
"""
import ctypes

import numpy as np


def _as_f32_ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    name = "cpu_adam"
    needs_host_state = True   # engine keeps master/moments on host

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, bias_correction=True, adamw_mode=True,
                 amsgrad=False, full_precision_optimizer_states=True):
        assert not amsgrad, "CPU Adam does not support AMSGrad"
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.adamw_mode = adamw_mode
        from deepspeed_tpu.ops.op_builder import CPUAdamBuilder

        self._lib = CPUAdamBuilder().load()

    @property
    def using_native(self):
        return self._lib is not None

    def init_state(self, master_params):
        """Host state: contiguous fp32 m/v per leaf + step counter."""
        import jax

        flat = jax.tree_util.tree_leaves(master_params)
        return {
            "step": 0,
            "m": [np.zeros(np.shape(l), np.float32) for l in flat],
            "v": [np.zeros(np.shape(l), np.float32) for l in flat],
        }

    def step(self, master_leaves, grad_leaves, state, lr=None, grad_scale=1.0):
        """In-place update of the fp32 master leaves (numpy). Returns the
        incremented state."""
        lr = self.lr if lr is None else lr
        state["step"] += 1
        step = state["step"]
        for p_orig, g, m_orig, v_orig in zip(master_leaves, grad_leaves,
                                             state["m"], state["v"]):
            # the kernel needs contiguous memory; shard-local offload may
            # pass non-contiguous views (e.g. a dim-1 slice of a TP-sharded
            # leaf) — update a copy and write back so the promised in-place
            # semantics hold
            views = []
            bufs = []
            for orig in (p_orig, m_orig, v_orig):
                if orig.flags["C_CONTIGUOUS"]:
                    views.append(None)
                    bufs.append(orig)
                else:
                    views.append(orig)
                    bufs.append(np.ascontiguousarray(orig))
            p, m, v = bufs
            g32 = np.ascontiguousarray(g, dtype=np.float32)
            if self._lib is not None:
                self._lib.ds_adam_step(
                    _as_f32_ptr(p), _as_f32_ptr(g32), _as_f32_ptr(m),
                    _as_f32_ptr(v), p.size, lr, self.beta1, self.beta2,
                    self.eps, self.weight_decay, int(self.adamw_mode),
                    int(self.bias_correction), step, grad_scale)
            else:
                self._numpy_step(p, g32, m, v, lr, step, grad_scale)
            for orig, buf in zip(views, bufs):
                if orig is not None:
                    orig[...] = buf
        return state

    def _numpy_step(self, p, g, m, v, lr, step, grad_scale):
        g = g / grad_scale
        if not self.adamw_mode and self.weight_decay > 0:
            g = g + self.weight_decay * p
        m *= self.beta1
        m += (1 - self.beta1) * g
        v *= self.beta2
        v += (1 - self.beta2) * g * g
        if self.bias_correction:
            bc1 = 1 - self.beta1 ** step
            bc2 = 1 - self.beta2 ** step
        else:
            bc1 = bc2 = 1.0
        update = (m / bc1) / (np.sqrt(v / bc2) + self.eps)
        if self.adamw_mode and self.weight_decay > 0:
            update = update + self.weight_decay * p
        p -= lr * update

    def cast_to(self, leaves, dtype_name):
        """fp32 leaves -> compute dtype numpy arrays (bf16/fp16 via the C++
        converter; the host half of the async host->HBM staging)."""
        import ml_dtypes

        outs = []
        for p in leaves:
            p = np.ascontiguousarray(p, dtype=np.float32)
            if dtype_name == "bfloat16":
                out = np.empty(p.shape, np.uint16)
                if self._lib is not None:
                    self._lib.ds_fp32_to_bf16(
                        _as_f32_ptr(p),
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                        p.size)
                    outs.append(out.view(ml_dtypes.bfloat16))
                else:
                    outs.append(p.astype(ml_dtypes.bfloat16))
            elif dtype_name == "float16":
                out = np.empty(p.shape, np.uint16)
                if self._lib is not None:
                    self._lib.ds_fp32_to_fp16(
                        _as_f32_ptr(p),
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                        p.size)
                    outs.append(out.view(np.float16))
                else:
                    outs.append(p.astype(np.float16))
            else:
                outs.append(p)
        return outs
