"""Plain SGD with momentum — TPU extension (the reference passes torch.optim.SGD
through; here it is a first-class fused update)."""
from typing import NamedTuple


class SGDState(NamedTuple):
    step: object
    momentum_buf: object


class SGD:
    name = "sgd"

    def __init__(self, lr=1e-3, momentum=0.0, weight_decay=0.0, nesterov=False):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init_state(self, master_params) -> SGDState:
        import jax
        import jax.numpy as jnp

        return SGDState(
            step=jnp.int32(0),
            momentum_buf=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), master_params))

    def update(self, grads, state: SGDState, master_params, lr=None, scale=1.0):
        import jax
        import jax.numpy as jnp

        lr = self.lr if lr is None else lr
        inv = 1.0 / scale

        def leaf(g, buf, p):
            g = g.astype(jnp.float32) * inv
            if self.weight_decay > 0:
                g = g + self.weight_decay * p
            if self.momentum > 0:
                buf = self.momentum * buf + g
                d = g + self.momentum * buf if self.nesterov else buf
            else:
                d = g
            return p - lr * d, buf

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_b = jax.tree_util.tree_leaves(state.momentum_buf)
        flat_p = jax.tree_util.tree_leaves(master_params)
        out = [leaf(g, b, p) for g, b, p in zip(flat_g, flat_b, flat_p)]
        return (treedef.unflatten([o[0] for o in out]),
                SGDState(step=state.step + 1,
                         momentum_buf=treedef.unflatten([o[1] for o in out])))

    def state_spec(self, param_specs):
        return SGDState(step=None, momentum_buf=param_specs)
