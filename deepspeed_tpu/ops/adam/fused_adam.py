"""Fused Adam/AdamW for TPU.

Reference: csrc/adam/multi_tensor_adam.cu + ops/adam/fused_adam.py:15-182 —
an apex-style multi-tensor-apply chunked kernel.  On TPU the same fusion falls
out of XLA: the whole pytree update compiles into fused HBM-bandwidth-bound
loops inside the jitted train step, so the "kernel" is pure jnp (SURVEY §2.7).

Like the reference kernel, ``update`` takes an optional gradient ``scale`` so
fp16 unscaling fuses into the update (reference fused_adam.py `step(scale=...)`).
"""
from typing import NamedTuple

_ADAM_MODE_ADAMW = 0  # decoupled weight decay
_ADAM_MODE_L2 = 1     # L2 regularization added to grad


class AdamState(NamedTuple):
    step: object  # i32
    m: object     # pytree, fp32
    v: object     # pytree, fp32


class FusedAdam:
    """Adam/AdamW over fp32 master params; grads may be fp16/bf16 (cast in)."""

    name = "adam"

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, adam_w_mode=True, weight_decay=0.0, amsgrad=False):
        assert not amsgrad, "amsgrad not supported (parity with reference fused_adam.py:61)"
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def init_state(self, master_params) -> AdamState:
        import jax
        import jax.numpy as jnp

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), master_params)
        return AdamState(step=jnp.int32(0), m=zeros,
                         v=jax.tree_util.tree_map(jnp.copy, zeros))

    def update(self, grads, state: AdamState, master_params, lr=None, scale=1.0):
        """One fused step.  Returns (new_master_params, new_state).

        grads are divided by ``scale`` (fused unscale), cast to fp32.
        """
        import jax
        import jax.numpy as jnp

        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        inv_scale = 1.0 / scale

        def leaf(g, m, v, p):
            g = g.astype(jnp.float32) * inv_scale
            if not self.adam_w_mode and self.weight_decay > 0:
                g = g + self.weight_decay * p
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * jnp.square(g)
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.adam_w_mode and self.weight_decay > 0:
                update = update + self.weight_decay * p
            return p - lr * update, m_new, v_new

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = jax.tree_util.tree_leaves(state.m)
        flat_v = jax.tree_util.tree_leaves(state.v)
        flat_p = jax.tree_util.tree_leaves(master_params)
        new_p, new_m, new_v = [], [], []
        for g, m, v, p in zip(flat_g, flat_m, flat_v, flat_p):
            p2, m2, v2 = leaf(g, m, v, p)
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
        unflatten = treedef.unflatten
        return unflatten(new_p), AdamState(step=step, m=unflatten(new_m),
                                           v=unflatten(new_v))

    def state_spec(self, param_specs):
        """Sharding spec for the state, matching the master-param specs."""
        return AdamState(step=None, m=param_specs, v=param_specs)


class FusedAdamW(FusedAdam):
    name = "adamw"

    def __init__(self, **kw):
        kw.setdefault("adam_w_mode", True)
        super().__init__(**kw)
