"""Fused LAMB for TPU.

Reference: csrc/lamb/fused_lamb_cuda_kernel.cu (reduction-based per-tensor norms
+ trust-ratio update) wrapped by ops/lamb/fused_lamb.py:12-189.  On TPU the
per-tensor norm reductions and the elementwise update fuse under XLA; the math
is NVLAMB with per-tensor trust ratio clamped to [min_coeff, max_coeff].
"""
from typing import NamedTuple


class LambState(NamedTuple):
    step: object
    m: object
    v: object


class FusedLamb:
    name = "lamb"

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, eps_inside_sqrt=False, weight_decay=0.0,
                 max_grad_norm=0.0, max_coeff=10.0, min_coeff=0.01, amsgrad=False):
        assert not amsgrad, "amsgrad not supported (parity with reference fused_lamb.py)"
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.eps_inside_sqrt = eps_inside_sqrt
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff

    def init_state(self, master_params) -> LambState:
        import jax
        import jax.numpy as jnp

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), master_params)
        return LambState(step=jnp.int32(0), m=zeros,
                         v=jax.tree_util.tree_map(jnp.copy, zeros))

    def update(self, grads, state: LambState, master_params, lr=None, scale=1.0):
        import jax
        import jax.numpy as jnp

        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        inv_scale = 1.0 / scale

        def leaf(g, m, v, p):
            g = g.astype(jnp.float32) * inv_scale
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * jnp.square(g)
            m_hat = m_new / bc1
            v_hat = v_new / bc2
            if self.eps_inside_sqrt:
                update = m_hat / jnp.sqrt(v_hat + self.eps)
            else:
                update = m_hat / (jnp.sqrt(v_hat) + self.eps)
            if self.weight_decay > 0:
                update = update + self.weight_decay * p
            # per-tensor trust ratio (the part the CUDA kernel does with
            # two-pass block reductions; XLA fuses the reductions here)
            w_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(update.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.float32(1.0))
            return p - lr * trust * update, m_new, v_new

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = jax.tree_util.tree_leaves(state.m)
        flat_v = jax.tree_util.tree_leaves(state.v)
        flat_p = jax.tree_util.tree_leaves(master_params)
        out = [leaf(g, m, v, p) for g, m, v, p in zip(flat_g, flat_m, flat_v, flat_p)]
        new_p = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_p, LambState(step=step, m=new_m, v=new_v)

    def state_spec(self, param_specs):
        return LambState(step=None, m=param_specs, v=param_specs)
