"""Pallas TPU flash attention — fused memory-efficient attention kernel.

TPU-native replacement for the reference's fused CUDA attention path
(reference: csrc/transformer/softmax_kernels.cu + strided_batch_gemm.h,
dispatched from ds_transformer_cuda.cpp:146-291).  Instead of materialising
the [B,H,S,S] score matrix in HBM, the kernel streams K/V blocks through
VMEM with an online-softmax accumulator (running max / denominator), so
attention memory is O(S) and the matmuls stay on the MXU.

Forward saves only the per-row logsumexp; backward recomputes probabilities
blockwise (two sweeps: dk/dv then dq) — the flash-attention v2 scheme.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _interpret_default() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # pragma: no cover
        return True


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal
    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0].astype(jnp.float32)            # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, 0:1]                               # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)           # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                      # [bq, 1]
        p = jnp.exp(s - m_new)                               # [bq, bk]
        l_new = l_scr[:, 0:1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l_safe)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    nq = pl.cdiv(s_q, block_q)
    nk = pl.cdiv(s_k, block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=nk)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_q, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr,
                     *, scale, causal, block_q, block_k, num_q_blocks):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]                       # [bq, 1]
        delta = delta_ref[0][:, 0:1]                   # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, d]

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, scale, causal, block_q, block_k, num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]
        delta = delta_ref[0][:, 0:1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(res, g, *, scale, causal, block_q, block_k, interpret):
    q, k, v, out, lse = res
    do = g
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    nq = pl.cdiv(s_q, block_q)
    nk = pl.cdiv(s_k, block_k)

    # delta_i = rowsum(dO_i * O_i) — standard flash backward precompute
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse_w = jnp.broadcast_to(lse[:, :, None], (bh, s_q, 128)).astype(jnp.float32)
    delta_w = jnp.broadcast_to(delta[:, :, None], (bh, s_q, 128))

    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_w, delta_w)
    dk, dv = dkdv

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_w, delta_w)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_3d(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, scale=scale, causal=causal,
                        block_q=block_q, block_k=block_k, interpret=interpret)
    return out


def _flash_3d_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_3d_bwd(scale, causal, block_q, block_k, interpret, res, g):
    return _flash_bwd(res, g, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret)


_flash_attention_3d.defvjp(_flash_3d_fwd, _flash_3d_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """Flash attention over [batch, heads, seq, head_dim] tensors.

    Differentiable (custom VJP with blockwise recomputation).  On non-TPU
    backends runs in Pallas interpreter mode (slow; tests only).
    """
    if interpret is None:
        interpret = _interpret_default()
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    # kernel causal mask is top-left aligned (q_idx >= k_idx from 0); with
    # s_q != s_k that diverges from bottom-right-aligned decode semantics
    assert not causal or s_q == s_k, (
        f"causal flash attention requires equal q/k lengths, got ({s_q}, {s_k}); "
        f"use the jnp path for cross-length (decode) attention")
    assert s_q % min(block_q, s_q) == 0 and s_k % min(block_k, s_k) == 0, (
        f"seq lengths ({s_q}, {s_k}) must divide into blocks "
        f"({block_q}, {block_k}); pad the sequence or use the jnp path — "
        f"padded Pallas blocks would silently corrupt the softmax")
    scale = (d ** -0.5) if scale is None else scale
    q3 = q.reshape(b * h, s_q, d)
    k3 = k.reshape(b * h, k.shape[2], d)
    v3 = v.reshape(b * h, v.shape[2], d)
    out = _flash_attention_3d(q3, k3, v3, scale, causal, block_q, block_k,
                              interpret)
    return out.reshape(b, h, s_q, d)
