"""Pallas TPU flash attention — fused memory-efficient attention kernel.

TPU-native replacement for the reference's fused CUDA attention path
(reference: csrc/transformer/softmax_kernels.cu + strided_batch_gemm.h,
dispatched from ds_transformer_cuda.cpp:146-291).  Instead of materialising
the [B,H,S,S] score matrix in HBM, the kernel streams K/V blocks through
VMEM with an online-softmax accumulator (running max / denominator), so
attention memory is O(S) and the matmuls stay on the MXU.

Forward saves only the per-row logsumexp; backward recomputes probabilities
blockwise (two sweeps: dk/dv then dq) — the flash-attention v2 scheme.

Attention dropout runs IN-KERNEL with a counter-based hash PRNG: the keep
mask for (head, q, k) is a pure function of (seed, position), so backward
regenerates the exact forward mask instead of saving an S x S byte mask to
HBM (the reference's CUDA layer saves masks — dropout_kernels.cu +
attn_dropout_checkpoint; SURVEY §2.7 maps that to counter-based PRNG on
TPU). The hash is the murmur3 finalizer over plain uint32 ops, so the same
code runs compiled on TPU and in interpreter mode on CPU.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# tuned on TPU v5e at (8, 16, 1024, 64): 512/1024 reached 22 TF fwd /
# 45 TF fwd+bwd vs 13.6/25 for the fused-XLA jnp path (tools/flash_tune.py);
# blocks are clamped to the sequence length at call time
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
# optional overrides for the backward sweeps only (0 = inherit fwd blocks);
# settable via env DSTPU_FLASH_BWD_BLOCK_Q/K for on-chip sweeps
import os as _os
_BWD_BLOCK_Q = int(_os.environ.get("DSTPU_FLASH_BWD_BLOCK_Q", "0"))
_BWD_BLOCK_K = int(_os.environ.get("DSTPU_FLASH_BWD_BLOCK_K", "0"))
# lse/delta wire format: by default they travel 128-lane broadcast
# ((bh, s_q, 128), 127/128 of the bytes redundant — ~0.4 GB/tensor/layer at
# the gpt2-350m bench shapes). DSTPU_FLASH_LSE2D=1 switches to compact
# (bh, s_q) tiles with an in-kernel (1, bq) -> (bq, 1) relayout; flagged
# (not default) until the on-chip sweep proves the Mosaic relayout cheap.
_LSE_2D = _os.environ.get("DSTPU_FLASH_LSE2D", "0") == "1"
NEG_INF = -1e30


def _col(ref):
    """Per-row statistic from its wire block: (1, bq) compact row ->
    (bq, 1) column, or the legacy 128-lane block's first lane."""
    if _LSE_2D:
        return ref[...].reshape(-1, 1)
    return ref[0][:, 0:1]


def _dot(a, b, dims):
    """MXU dot: native (bf16) inputs, fp32 accumulation. Casting inputs to
    fp32 first would force fp32 MXU passes at a fraction of bf16 throughput —
    the round-4 profile showed exactly that (kernel slower than the jnp
    path); inputs stay in their storage dtype and only the accumulator is
    fp32."""
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _fit_block(block, seq):
    """Largest lane-aligned block <= `block` that divides `seq` (whole
    `seq` if smaller); None when no 128-aligned divisor exists — degenerate
    sub-tile blocks would fail deep in Mosaic or crawl, so the caller
    raises loudly instead."""
    if seq <= block:
        return seq
    while block >= 128:
        if seq % block == 0:
            return block
        block //= 2
    return None


def _interpret_default() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # pragma: no cover
        return True


def _dropout_keep(seed_ref, bh, q_start, k_start, block_q, block_k, s_k,
                  rate):
    """Keep-mask block for attention dropout: murmur3-finalizer hash of the
    global (q, k) position, pre-mixed with (seed, batch*head). Deterministic
    given the seed, so forward and both backward sweeps regenerate identical
    masks from the positions alone."""
    def mix(h):
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        return h ^ (h >> 16)

    seed = seed_ref[0].astype(jnp.uint32) \
        + jnp.uint32(0x9E3779B9) * jnp.uint32(bh)
    rows = jax.lax.broadcasted_iota(jnp.uint32, (block_q, 1), 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (1, block_k), 1)
    # q and k positions are mixed in two rounds rather than combined into a
    # q*s_k + k linear index: the product overflows uint32 beyond ~64k seq
    # (q rows 2^32/s_k apart would alias and share keep patterns)
    rh = mix(seed ^ (jnp.uint32(q_start) + rows))           # (bq, 1)
    h = mix(rh ^ (jnp.uint32(0x27D4EB2F) *
                  (jnp.uint32(k_start) + cols)))            # (bq, bk)
    return h >= jnp.uint32(min(rate, 0.9999) * 4294967296.0)


def _apply_bias(s, bias_ref, bias_kind):
    """Additive attention bias inside a kernel block.

    bias_kind 'key': bias_ref block is (1, block_k) — the HF extended-mask
    (B, 1, 1, S_k) case, broadcast over query rows; 'full': (1, block_q,
    block_k) per-(batch*head) scores bias."""
    if bias_kind == "key":
        return s + bias_ref[...]
    if bias_kind == "full":
        return s + bias_ref[0]
    return s


def _bias_specs(bias, bias_kind, num_heads, block_q, block_k, qmap, kmap):
    """(operands, in_specs) for the optional bias input. qmap/kmap map grid
    ids to the bias q/k block index."""
    if bias_kind == "none":
        return [], []
    if bias_kind == "key":
        spec = pl.BlockSpec(
            (1, block_k),
            lambda b, i, j: (b // num_heads, kmap(i, j)))
        return [bias], [spec]
    spec = pl.BlockSpec(
        (1, block_q, block_k),
        lambda b, i, j: (b, qmap(i, j), kmap(i, j)))
    return [bias], [spec]


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, scale, causal, bias_kind, dropout_rate, s_k_total,
                block_q, block_k, num_k_blocks):
    seed_ref = None
    if dropout_rate > 0.0:
        seed_ref, *refs = refs
    if bias_kind == "none":
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        bias_ref = None
    else:
        (q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    bi = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal
    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                # [bq, d] storage dtype
        k = k_ref[0]                                # [bk, d]
        v = v_ref[0]                                # [bk, d]
        s = _dot(q, k, ((1,), (1,))) * scale                 # [bq, bk] f32
        s = _apply_bias(s, bias_ref, bias_kind)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, 0:1]                               # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)           # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                      # [bq, 1]
        p = jnp.exp(s - m_new)                               # [bq, bk] f32
        # softmax denominator accumulates UNdropped p; dropout scales only
        # the value accumulation (normalize-then-drop semantics, same as
        # the reference applying dropout to softmax output)
        l_new = l_scr[:, 0:1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, bi, q_start,
                                 k_start, block_q, block_k, s_k_total,
                                 dropout_rate)
            p_acc = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        else:
            p_acc = p
        acc_scr[:] = acc_scr[:] * alpha + _dot(
            p_acc.astype(v.dtype), v, ((1,), (0,)))
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l_safe)
        if _LSE_2D:
            lse_ref[...] = lse.reshape(lse_ref.shape)
        else:
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _seed_ops(seed, dropout_rate):
    """(operands, in_specs) for the dropout seed — a scalar in SMEM."""
    if dropout_rate <= 0.0:
        return [], []
    return [seed], [pl.BlockSpec(memory_space=pltpu.SMEM)]


def _flash_fwd(q, k, v, bias, seed, *, scale, causal, bias_kind, num_heads,
               dropout_rate, block_q, block_k, interpret):
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    nq = pl.cdiv(s_q, block_q)
    nk = pl.cdiv(s_k, block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bias_kind=bias_kind,
        dropout_rate=dropout_rate, s_k_total=s_k,
        block_q=block_q, block_k=block_k, num_k_blocks=nk)
    seed_ops, seed_specs = _seed_ops(seed, dropout_rate)
    bias_ops, bias_specs = _bias_specs(
        bias, bias_kind, num_heads, block_q, block_k,
        qmap=lambda i, j: i, kmap=lambda i, j: j)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ] + bias_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)) if _LSE_2D
            else pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_q) if _LSE_2D else (bh, s_q, 128),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_ops, q, k, v, *bias_ops)
    return out, (lse if _LSE_2D else lse[:, :, 0])


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dkdv_kernel(*refs, scale, causal, bias_kind, dropout_rate,
                     s_k_total, block_q, block_k, num_q_blocks):
    seed_ref = None
    if dropout_rate > 0.0:
        seed_ref, *refs = refs
    if bias_kind == "none":
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        bias_ref = None
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    bi = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = _col(lse_ref)                            # [bq, 1]
        delta = _col(delta_ref)                        # [bq, 1]
        s = _dot(q, k, ((1,), (1,))) * scale                  # [bq, bk] f32
        s = _apply_bias(s, bias_ref, bias_kind)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                                  # [bq, bk] f32
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, bi, q_start,
                                 k_start, block_q, block_k, s_k_total,
                                 dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_drop = jnp.where(keep, p * inv, 0.0)
        else:
            p_drop = p
        dv_scr[:] += _dot(p_drop.astype(do.dtype), do, ((0,), (0,)))  # [bk,d]
        dp = _dot(do, v, ((1,), (1,)))                        # [bq, bk] f32
        if dropout_rate > 0.0:
            # dL/dP = keep/(1-r) * dO V^T; delta already equals
            # rowsum(P_drop o dP) = rowsum(dO o O)
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - delta) * scale
        dk_scr[:] += _dot(ds.astype(q.dtype), q, ((0,), (0,)))   # [bk, d]

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, scale, causal, bias_kind, dropout_rate, s_k_total,
                   block_q, block_k, num_k_blocks):
    seed_ref = None
    if dropout_rate > 0.0:
        seed_ref, *refs = refs
    if bias_kind == "none":
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
        bias_ref = None
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
         dq_ref, dq_scr) = refs
    bi = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = _col(lse_ref)
        delta = _col(delta_ref)
        s = _dot(q, k, ((1,), (1,))) * scale
        s = _apply_bias(s, bias_ref, bias_kind)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = _dot(do, v, ((1,), (1,)))
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, bi, q_start,
                                 k_start, block_q, block_k, s_k_total,
                                 dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta) * scale
        dq_scr[:] += _dot(ds.astype(k.dtype), k, ((1,), (0,)))

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(res, g, *, scale, causal, bias_kind, num_heads, dropout_rate,
               block_q, block_k, interpret):
    q, k, v, bias, seed, out, lse = res
    do = g
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    # the backward sweeps accumulate into (block, d) fp32 scratch and run a
    # 5-matmul body — their best tile shape differs from the forward's;
    # independent env knobs let tools/flash_tune.py sweep them on-chip.
    # A knob with no 128-aligned divisor fails as loudly as the forward
    # does (flash_attention.py asserts in flash_attention()) — a partial
    # Pallas block would silently corrupt the gradients.
    block_q = _fit_block(min(_BWD_BLOCK_Q or block_q, s_q), s_q)
    block_k = _fit_block(min(_BWD_BLOCK_K or block_k, s_k), s_k)
    assert block_q is not None and block_k is not None, (
        f"flash backward: DSTPU_FLASH_BWD_BLOCK_Q/K={_BWD_BLOCK_Q}/"
        f"{_BWD_BLOCK_K} have no 128-aligned divisor of seq ({s_q}, {s_k})")
    nq = pl.cdiv(s_q, block_q)
    nk = pl.cdiv(s_k, block_k)

    # delta_i = rowsum(dO_i * O_i) — standard flash backward precompute
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    if _LSE_2D:
        lse_w = lse.astype(jnp.float32)                      # (bh, s_q)
        delta_w = delta
    else:
        lse_w = jnp.broadcast_to(
            lse[:, :, None], (bh, s_q, 128)).astype(jnp.float32)
        delta_w = jnp.broadcast_to(delta[:, :, None], (bh, s_q, 128))

    def stat_spec(index_q):
        """BlockSpec for the lse/delta operands; index_q maps grid ids to
        the q-block index."""
        if _LSE_2D:
            return pl.BlockSpec((1, block_q),
                                lambda b, x, y: (b, index_q(x, y)))
        return pl.BlockSpec((1, block_q, 128),
                            lambda b, x, y: (b, index_q(x, y), 0))

    seed_ops, seed_specs = _seed_ops(seed, dropout_rate)
    # dkdv grid is (bh, k-block, q-block): bias maps transposed
    bias_ops, bias_specs = _bias_specs(
        bias, bias_kind, num_heads, block_q, block_k,
        qmap=lambda j, i: i, kmap=lambda j, i: j)
    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          bias_kind=bias_kind, dropout_rate=dropout_rate,
                          s_k_total=s_k,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq),
        grid=(bh, nk, nq),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            stat_spec(lambda j, i: i),
            stat_spec(lambda j, i: i),
        ] + bias_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_ops, q, k, v, do, lse_w, delta_w, *bias_ops)
    dk, dv = dkdv

    bias_ops, bias_specs = _bias_specs(
        bias, bias_kind, num_heads, block_q, block_k,
        qmap=lambda i, j: i, kmap=lambda i, j: j)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bias_kind=bias_kind, dropout_rate=dropout_rate,
                          s_k_total=s_k,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk),
        grid=(bh, nq, nk),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            stat_spec(lambda i, j: i),
            stat_spec(lambda i, j: i),
        ] + bias_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_ops, q, k, v, do, lse_w, delta_w, *bias_ops)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11,
                                                    12))
def _flash_attention_3d(q, k, v, bias, seed, scale, causal, bias_kind,
                        num_heads, dropout_rate, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, bias, seed, scale=scale, causal=causal,
                        bias_kind=bias_kind, num_heads=num_heads,
                        dropout_rate=dropout_rate,
                        block_q=block_q, block_k=block_k, interpret=interpret)
    return out


def _flash_3d_fwd(q, k, v, bias, seed, scale, causal, bias_kind, num_heads,
                  dropout_rate, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, bias, seed, scale=scale, causal=causal,
                          bias_kind=bias_kind, num_heads=num_heads,
                          dropout_rate=dropout_rate,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)
    return out, (q, k, v, bias, seed, out, lse)


def _flash_3d_bwd(scale, causal, bias_kind, num_heads, dropout_rate, block_q,
                  block_k, interpret, res, g):
    dq, dk, dv = _flash_bwd(res, g, scale=scale, causal=causal,
                            bias_kind=bias_kind, num_heads=num_heads,
                            dropout_rate=dropout_rate,
                            block_q=block_q, block_k=block_k,
                            interpret=interpret)
    # bias is a constant additive mask (HF extended mask / key padding):
    # no gradient is produced for it (zeros keep the vjp total)
    dbias = None if res[3] is None else jnp.zeros_like(res[3])
    # integer primals take float0 cotangents (JAX convention for the int32
    # seed; a zeros_like int cotangent only works by accident)
    dseed = None if res[4] is None else \
        jnp.zeros(res[4].shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


# nondiff args start at 5: scale, causal, bias_kind, num_heads,
# dropout_rate, blocks, interpret
_flash_attention_3d.defvjp(_flash_3d_fwd, _flash_3d_bwd)


def flash_attention(q, k, v, *, bias=None, causal: bool = False,
                    scale: Optional[float] = None,
                    dropout_rate: float = 0.0, dropout_seed=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """Flash attention over [batch, heads, seq, head_dim] tensors.

    bias: optional ADDITIVE attention bias — (B, 1, 1, S_k) HF extended
    mask / key-padding form, or any shape broadcastable to (B, H, S_q, S_k).
    Treated as a constant (no bias gradient). Differentiable in q/k/v
    (custom VJP with blockwise recomputation). On non-TPU backends runs in
    Pallas interpreter mode (slow; tests only).

    dropout_rate/dropout_seed: in-kernel attention dropout. The seed (int
    scalar or 0-d/1-elem int32 array, typically drawn per-step from the
    engine's dropout rng) fully determines the keep mask; backward
    regenerates it from positions, nothing is stored.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    # kernel causal mask is top-left aligned (q_idx >= k_idx from 0); with
    # s_q != s_k that diverges from bottom-right-aligned decode semantics
    assert not causal or s_q == s_k, (
        f"causal flash attention requires equal q/k lengths, got ({s_q}, {s_k}); "
        f"use the jnp path for cross-length (decode) attention")
    # shrink each block to the largest 128-aligned divisor of the sequence
    # length: any s % 128 == 0 stays on the kernel (e.g. 640 uses
    # 128-blocks rather than failing the 512-default divisibility — partial
    # Pallas blocks would silently corrupt the softmax, so divisibility is
    # non-negotiable and unaligned lengths fail loudly)
    block_q = _fit_block(block_q, s_q)
    block_k = _fit_block(block_k, s_k)
    assert block_q is not None and block_k is not None, (
        f"seq lengths ({s_q}, {s_k}) have no 128-aligned block divisor; "
        f"pad the sequence to a multiple of 128 or use the jnp path")
    scale = (d ** -0.5) if scale is None else scale
    bias_kind = "none"
    bias3 = None
    if bias is not None:
        assert bias.ndim == 4, f"bias must be 4D, got shape {bias.shape}"
        if bias.shape[1] == 1 and bias.shape[2] == 1:
            # key-padding bias: one row per batch, broadcast over heads/rows
            bias_kind = "key"
            bias3 = jnp.broadcast_to(
                bias[:, 0, 0, :], (b, s_k)).astype(jnp.float32)
        else:
            bias_kind = "full"
            bias3 = jnp.broadcast_to(
                bias, (b, h, s_q, s_k)).astype(jnp.float32).reshape(
                    b * h, s_q, s_k)
    dropout_rate = float(dropout_rate)
    assert 0.0 <= dropout_rate < 1.0, f"bad dropout_rate {dropout_rate}"
    seed1 = None
    if dropout_rate > 0.0:
        assert dropout_seed is not None, \
            "dropout_rate > 0 requires dropout_seed"
        seed1 = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    q3 = q.reshape(b * h, s_q, d)
    k3 = k.reshape(b * h, k.shape[2], d)
    v3 = v.reshape(b * h, v.shape[2], d)
    out = _flash_attention_3d(q3, k3, v3, bias3, seed1, scale, causal,
                              bias_kind, h, dropout_rate, block_q, block_k,
                              interpret)
    return out.reshape(b, h, s_q, d)
