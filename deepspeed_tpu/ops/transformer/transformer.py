"""DeepSpeedTransformerLayer — the fused BERT-style encoder layer.

Reference behavior: deepspeed/ops/transformer/transformer.py:39-614 backed by
the CUDA fused kernel (csrc/transformer/ds_transformer_cuda.cpp:146-546:
QKV GEMM -> strided-batch attention GEMMs -> fused-bias softmax -> fused
bias+residual LayerNorm -> fused bias-GeLU, with saved dropout masks).

TPU formulation: one flax module whose whole body lives inside the jitted
train step — XLA fuses bias/dropout/residual/LayerNorm into the GEMMs the
same way the CUDA kernel hand-fuses them, and the attention core routes
through the Pallas flash kernel (ops/transformer/functional.py). The
memory-saving config flags map to rematerialization policies instead of
manual buffer reuse:
- normalize_invertible / attn_dropout_checkpoint / gelu_checkpoint ->
  jax.checkpoint over the layer body (recompute instead of save);
- stochastic_mode -> nothing to relax (TPU execution is deterministic).
"""
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.functional import \
    scaled_dot_product_attention


class TransformerConfig:
    """Base config (reference transformer.py:21-37)."""

    def __init__(self, batch_size=-1, hidden_size=-1, intermediate_size=-1,
                 heads=-1, attn_dropout_ratio=-1, hidden_dropout_ratio=-1,
                 num_hidden_layers=-1, initializer_range=-1):
        self.layer_id = -1
        self.batch_size = batch_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.heads = heads
        self.attn_dropout_ratio = attn_dropout_ratio
        self.hidden_dropout_ratio = hidden_dropout_ratio
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = initializer_range


class DeepSpeedTransformerConfig(TransformerConfig):
    """Config with the exact reference surface (transformer.py:39-140).

    TPU notes: fp16 selects the compute dtype (bf16 is the TPU-native
    choice; fp16 kept for parity); local_rank/seed/test_gemm are accepted
    for compatibility (device binding and RNG are engine concerns here).
    """

    def __init__(self, batch_size=-1, hidden_size=-1, intermediate_size=-1,
                 heads=-1, attn_dropout_ratio=-1, hidden_dropout_ratio=-1,
                 num_hidden_layers=-1, initializer_range=-1,
                 layer_norm_eps=1e-12, local_rank=-1, seed=-1, fp16=False,
                 bf16=False, pre_layer_norm=True, normalize_invertible=False,
                 gelu_checkpoint=False, adjust_init_range=True,
                 attn_dropout_checkpoint=False, stochastic_mode=False,
                 huggingface=False, training=True, sparsity_config=None):
        super().__init__(
            batch_size, hidden_size,
            intermediate_size if intermediate_size > 0 else 4 * hidden_size,
            heads, attn_dropout_ratio, hidden_dropout_ratio,
            num_hidden_layers, initializer_range)
        self.fp16 = fp16
        self.bf16 = bf16
        self.pre_layer_norm = pre_layer_norm
        self.local_rank = local_rank
        self.seed = seed
        self.normalize_invertible = normalize_invertible
        self.gelu_checkpoint = gelu_checkpoint
        self.adjust_init_range = adjust_init_range
        self.test_gemm = False
        self.layer_norm_eps = layer_norm_eps
        self.training = training
        self.is_grad_enabled = True
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.stochastic_mode = stochastic_mode
        self.huggingface = huggingface
        # a SparsityConfig (ops/sparse_attention) routes the attention core
        # through the block-sparse path — same params (QKV/out projections
        # untouched), different attention pattern. The reference swaps
        # whole modules (sparse_attention_utils.py:85-150); here the swap
        # is this one config field.
        self.sparsity_config = sparsity_config

    @classmethod
    def from_dict(cls, json_object):
        config = cls()
        for key, value in json_object.items():
            config.__dict__[key] = value
        return config

    @property
    def compute_dtype(self):
        if self.fp16:
            return jnp.float16
        if self.bf16:
            return jnp.bfloat16
        return jnp.float32

    @property
    def remat(self):
        """Any memory-saving flag -> rematerialize the layer body."""
        return (self.normalize_invertible or self.gelu_checkpoint
                or self.attn_dropout_checkpoint)


class _EncoderBody(nn.Module):
    """BERT encoder layer body (attention + FFN), pre- or post-LN."""
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask, train: bool):
        cfg = self.config
        dtype = cfg.compute_dtype
        E = cfg.hidden_size
        H = cfg.heads
        B, S, _ = hidden_states.shape
        head_dim = E // H
        init_std = cfg.initializer_range
        out_std = init_std / math.sqrt(2.0 * max(1, cfg.num_hidden_layers)) \
            if cfg.adjust_init_range else init_std

        def dense(features, name, std):
            return nn.Dense(features, dtype=dtype, name=name,
                            kernel_init=nn.initializers.normal(std))

        x = hidden_states.astype(dtype)
        residual = x

        # --- attention -------------------------------------------------
        attn_in = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                               name="attn_ln")(x) if cfg.pre_layer_norm else x
        qkv = dense(3 * E, "qkv", init_std)(attn_in)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, H, head_dim).transpose(0, 2, 1, 3)

        drop_rng = self.make_rng("dropout") \
            if (train and cfg.attn_dropout_ratio > 0) else None
        # Ulysses sequence parallelism: under a nontrivial 'seq' mesh axis
        # the heads dim picks up the seq shard and the sequence dim goes
        # full (GSPMD all_to_all) — same flip as models/gpt2.py; every dim
        # names its axes so data/model sharding is preserved
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.parallel import mesh as mesh_lib

        head_sp = P("data", ("model", "seq"), None, None)
        qh = mesh_lib.constrain(heads(q), head_sp)
        kh = mesh_lib.constrain(heads(k), head_sp)
        vh = mesh_lib.constrain(heads(v), head_sp)
        if cfg.sparsity_config is not None:
            from deepspeed_tpu.ops.sparse_attention.sparse_self_attention \
                import block_sparse_attention

            assert drop_rng is None, (
                "sparsity_config does not support attention dropout "
                "(the reference's sparse path has none either); set "
                "attn_dropout_ratio=0")
            # HF extended additive mask (B,1,1,S) -> per-key additions;
            # anything with per-query structure cannot collapse to a key
            # bias and must fail loudly, not attend wrongly
            kpm = None
            if attention_mask is not None:
                assert attention_mask.shape[1] == 1 \
                    and attention_mask.shape[2] == 1, (
                        "sparsity_config supports key-padding masks "
                        "(B, 1, 1, S) only; got attention_mask shape "
                        f"{attention_mask.shape} — per-query masks need "
                        "the dense path (sparsity_config=None)")
                kpm = attention_mask[:, 0, 0, :]
            ctx = block_sparse_attention(
                qh, kh, vh,
                cfg.sparsity_config.make_layout(S),
                cfg.sparsity_config.block,
                key_padding_mask=kpm, key_padding_mask_mode="add")
        else:
            ctx = scaled_dot_product_attention(
                qh, kh, vh, causal=False, bias=attention_mask,
                dropout_rng=drop_rng,
                dropout_rate=cfg.attn_dropout_ratio if train else 0.0)
        ctx = mesh_lib.constrain(ctx, P("data", "model", "seq", None))
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, E)
        attn_out = dense(E, "attn_out", out_std)(ctx)
        if train and cfg.hidden_dropout_ratio > 0:
            attn_out = nn.Dropout(cfg.hidden_dropout_ratio)(
                attn_out, deterministic=False)
        x = residual + attn_out
        if not cfg.pre_layer_norm:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                             name="attn_ln")(x)

        # --- feed-forward ---------------------------------------------
        residual = x
        ffn_in = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                              name="ffn_ln")(x) if cfg.pre_layer_norm else x
        h = dense(cfg.intermediate_size, "ffn_inter", init_std)(ffn_in)
        h = nn.gelu(h, approximate=False)
        h = dense(E, "ffn_out", out_std)(h)
        if train and cfg.hidden_dropout_ratio > 0:
            h = nn.Dropout(cfg.hidden_dropout_ratio)(h, deterministic=False)
        x = residual + h
        if not cfg.pre_layer_norm:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                             name="ffn_ln")(x)
        return x


class DeepSpeedTransformerLayer(nn.Module):
    """Drop-in encoder layer (reference transformer.py:470-614).

    __call__(hidden_states, attention_mask) -> hidden_states, where
    attention_mask is an additive bias broadcastable to (B, H, S, S)
    (HF-style extended mask) or None.
    """
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 train: Optional[bool] = None):
        cfg = self.config
        train = cfg.training if train is None else train
        body = _EncoderBody
        if cfg.remat and train:
            body = nn.remat(_EncoderBody, static_argnums=(3,))
        return body(cfg, name="body")(hidden_states, attention_mask, train)
