"""Attention + fused-elementwise functional ops: the dispatch point between the
jnp reference path and Pallas TPU kernels.

Reference analog: csrc/transformer/*.cu fused kernels (SURVEY §2.7).  Every op
here has a jnp reference implementation (always correct, XLA-fused) and may
have a Pallas fast path registered; `deepspeed_tpu.ops.registry` reports which
is active (the ds_report analog).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp


def scaled_dot_product_attention(q, k, v, *, mask=None, bias=None, causal=False,
                                 dropout_rng=None, dropout_rate=0.0,
                                 scale: Optional[float] = None,
                                 use_pallas: Optional[bool] = None):
    """Attention over [batch, heads, seq, head_dim] tensors.

    jnp reference path; the Pallas flash-attention kernel is dispatched for TPU
    when shapes allow (see deepspeed_tpu.ops.transformer.flash_attention).
    """
    if use_pallas is None:
        use_pallas = _pallas_attention_ok(q, k, v, mask, bias, dropout_rate,
                                          dropout_rng)
    if use_pallas:
        assert dropout_rate == 0.0 or dropout_rng is not None, (
            "pallas flash attention dropout needs a dropout_rng to derive "
            "the in-kernel counter seed")
        from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

        if mask is not None:
            # boolean keep-mask -> additive bias (the kernel's in-block
            # form); combined with any explicit bias by addition, matching
            # the jnp path's where(mask, logits+bias, -inf)
            mask_bias = jnp.where(mask, jnp.float32(0.0), jnp.float32(-1e30))
            bias = mask_bias if bias is None else bias + mask_bias
            mask = None
        seed = None
        if dropout_rate > 0.0:
            # per-step scalar seed for the in-kernel counter-based PRNG
            seed = jax.random.randint(dropout_rng, (1,), 0, 2 ** 31 - 1,
                                      dtype=jnp.int32)
        return flash_attention(q, k, v, bias=bias, causal=causal, scale=scale,
                               dropout_rate=dropout_rate, dropout_seed=seed)

    head_dim = q.shape[-1]
    scale = (head_dim ** -0.5) if scale is None else scale
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((q_len, k_len), dtype=bool),
                               k_len - q_len)
        logits = jnp.where(causal_mask, logits, jnp.float32(-1e30))
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _pallas_attention_ok(q, k, v, mask, bias, dropout_rate,
                         dropout_rng=None) -> bool:
    # Pallas path: TPU backend, seq and head_dim aligned to MXU tiles;
    # causal, additive bias, boolean keep-masks, and dropout (counter-based
    # PRNG) are all handled in-kernel. Bias/mask gradients are not produced
    # (fine for constant masks — a learned bias needs use_pallas=False).
    if dropout_rate > 0.0 and dropout_rng is None:
        return False

    def key_padding_shaped(m):
        # auto-dispatch only for key-padding-shaped (B, 1, 1, S_k) masks/
        # biases — in practice always constants. A full (learned) bias
        # would silently get zero gradient through the kernel; it must opt
        # in with use_pallas=True.
        return (getattr(m, "ndim", 0) == 4 and m.shape[1] == 1
                and m.shape[2] == 1)

    if bias is not None and not key_padding_shaped(bias):
        return False
    if mask is not None and not key_padding_shaped(mask):
        return False
    try:
        if jax.default_backend() not in ("tpu",):
            return False
    except Exception:
        return False
    b, h, s, d = q.shape
    return s % 128 == 0 and d in (64, 128, 256) and k.shape == q.shape


def gelu(x, approximate=True):
    return jax.nn.gelu(x, approximate=approximate)


def bias_gelu(x, bias):
    """Fused bias+GeLU (reference csrc/transformer/gelu_kernels.cu); XLA fuses."""
    return jax.nn.gelu(x + bias, approximate=True)


def layer_norm(x, gamma, beta, eps=1e-12):
    """LayerNorm in fp32 accumulations (reference normalize_kernels.cu)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma + beta).astype(x.dtype)


def bias_residual_layer_norm(x, bias, residual, gamma, beta, eps=1e-12):
    """Fused bias+residual+LayerNorm (reference: fused add+LN in
    normalize_kernels.cu)."""
    return layer_norm(x + bias + residual, gamma, beta, eps)


def dropout(x, rng, rate, deterministic=False):
    if deterministic or rate == 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)
