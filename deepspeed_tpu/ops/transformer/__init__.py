from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer, TransformerConfig)
from deepspeed_tpu.ops.transformer.functional import \
    scaled_dot_product_attention
