"""1-bit Adam — communication-compressed Adam for TPU.

Reference behavior (deepspeed/runtime/fp16/onebit_adam.py:18-374):
- warmup (step < freeze_step): exact Adam *without* bias correction
  (update = m / (sqrt(v) + eps), onebit_adam.py:325-327);
- after freeze_step: the variance v is FROZEN; only the momentum m is
  updated and synchronized via the error-compensated 1-bit allreduce
  (onebit_adam.py:330-349), cutting gradient-sync traffic ~32x.

TPU-native formulation: in the engine's SPMD flow gradients arrive already
mesh-averaged (XLA reduce-scatter over 'data'), so the per-worker and server
compression stages collapse into `quantize_with_error_feedback` — the same
two-stage residual numerics with identical input on every worker. The real
multi-device collective (`compressed_allreduce`, bit-packed all_to_all +
all_gather over a named axis) lives in runtime/custom_collectives.py for
shard_map-driven comm-bound setups (DCN-connected pods).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.custom_collectives import (
    compressed_allreduce, quantize_with_error_feedback)


class OnebitAdamState(NamedTuple):
    step: object           # i32
    m: object              # momentum pytree, fp32
    v: object              # variance pytree, fp32 (frozen after freeze_step)
    worker_error: object   # error-feedback residual pytree (worker stage)
    server_error: object   # error-feedback residual pytree (server stage)


class OnebitAdam:
    name = "onebitadam"

    def __init__(self, lr=1e-3, freeze_step=100000, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, max_grad_norm=0.0,
                 bias_correction=True, amsgrad=False, cuda_aware=False,
                 eps_inside_sqrt=False, comm_backend_name="xla", mesh=None,
                 axis_name=None, axis_size=1):
        assert not amsgrad, "1-bit Adam does not support the AMSGrad variant."
        self.lr = lr
        self.freeze_step = freeze_step
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.mesh = mesh
        # reference parity: comm_backend_name selects the wire
        # ('nccl'/'mpi' there; 'xla' here). 'none' opts out of the
        # shard_map wire path even when the engine would enable it.
        self.comm_backend_name = comm_backend_name
        # when set, update() runs under shard_map with this axis bound and
        # uses the true bit-packed collective instead of local quantization;
        # axis_size is needed at trace time to pad leaves (the reference's
        # corrected_tensor_size, onebit_adam.py:293-298).  Error-feedback
        # residuals are per-device: they carry a leading (axis_size,) dim
        # sharded over the axis.
        self.axis_name = axis_name
        self.axis_size = axis_size

    def init_state(self, master_params) -> OnebitAdamState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), master_params)
        if self.axis_name is not None:
            # per-device residuals: leading axis dim, sharded over the axis
            err = lambda: jax.tree_util.tree_map(
                lambda p: jnp.zeros((self.axis_size,) + p.shape, jnp.float32),
                master_params)
        else:
            err = zeros
        return OnebitAdamState(step=jnp.int32(0), m=zeros(), v=zeros(),
                               worker_error=err(), server_error=err())

    def update(self, grads, state: OnebitAdamState, master_params, lr=None,
               scale=1.0, frozen=None):
        """One optimizer step. ``frozen`` statically selects the branch
        (None = runtime lax.cond on step vs freeze_step); the engine compiles
        warmup and post-freeze as separate programs so the post-freeze HLO
        contains only the bit-packed collective."""
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        dyn_frozen = step > self.freeze_step  # variance freezes after warmup

        def leaf(g, m, v, we, se, p):
            g = g.astype(jnp.float32) / scale

            def compressed(_):
                m_new = b1 * m + (1.0 - b1) * g
                flat = m_new.reshape(-1)
                fwe, fse = we.reshape(-1), se.reshape(-1)
                if self.axis_name is not None:
                    quantum = 8 * self.axis_size
                    pad = (-flat.size) % quantum
                    q, we_new, se_new = compressed_allreduce(
                        jnp.pad(flat, (0, pad)), jnp.pad(fwe, (0, pad)),
                        jnp.pad(fse, (0, pad)), self.axis_name)
                    q, we_new, se_new = (t[:flat.size]
                                         for t in (q, we_new, se_new))
                else:
                    q, we_new, se_new = quantize_with_error_feedback(
                        flat, fwe, fse)
                return (q.reshape(m.shape), v,
                        we_new.reshape(we.shape), se_new.reshape(se.shape))

            def warmup(_):
                # warmup parity: reference runs exact all-reduced Adam before
                # freeze (onebit_adam.py:321-327); after freeze the compressed
                # branch carries local momenta instead
                g_sync = jax.lax.pmean(g, self.axis_name) \
                    if self.axis_name is not None else g
                m_warm = b1 * m + (1.0 - b1) * g_sync
                v_warm = b2 * v + (1.0 - b2) * jnp.square(g_sync)
                return m_warm, v_warm, we, se

            # static frozen compiles exactly one branch (the engine swaps
            # programs at the freeze boundary — the post-freeze HLO then
            # provably contains no dense gradient collective); dynamic falls
            # back to lax.cond so warmup steps still skip the quantization
            if frozen is None:
                m_out, v_out, we_out, se_out = jax.lax.cond(
                    dyn_frozen, compressed, warmup, None)
            elif frozen:
                m_out, v_out, we_out, se_out = compressed(None)
            else:
                m_out, v_out, we_out, se_out = warmup(None)

            update = m_out / (jnp.sqrt(v_out) + self.eps)
            if self.weight_decay > 0.0:
                update = update + self.weight_decay * p
            return p - lr * update, m_out, v_out, we_out, se_out

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat = lambda t: jax.tree_util.tree_leaves(t)
        outs = [leaf(g, m, v, we, se, p) for g, m, v, we, se, p in
                zip(flat_g, flat(state.m), flat(state.v),
                    flat(state.worker_error), flat(state.server_error),
                    flat(master_params))]
        unf = treedef.unflatten
        new_p, new_m, new_v, new_we, new_se = (unf(list(t)) for t in zip(*outs))
        return new_p, OnebitAdamState(step=step, m=new_m, v=new_v,
                                      worker_error=new_we, server_error=new_se)

    def state_spec(self, param_specs):
        from jax.sharding import PartitionSpec as P

        err_specs = param_specs
        if self.axis_name is not None:
            # residuals carry a leading per-device dim sharded over the axis
            err_specs = jax.tree_util.tree_map(
                lambda s: P(self.axis_name, *s), param_specs,
                is_leaf=lambda x: isinstance(x, P))
        return OnebitAdamState(step=None, m=param_specs, v=param_specs,
                               worker_error=err_specs,
                               server_error=err_specs)
