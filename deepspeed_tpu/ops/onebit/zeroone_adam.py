"""0/1 Adam — variance-frozen, 1-bit-compressed, locally-skipped Adam.

Reference behavior (arxiv 2202.06009; deepspeed/runtime/fp16/onebit/
zoadam.py): 0/1 Adam extends 1-bit Adam with two levers —
- **variance freeze**: after ``var_freeze_step`` optimizer steps the second
  moment v stops updating (1-bit Adam's freeze), and
- **adaptive local steps**: synced rounds happen only every k-th step;
  between syncs workers take LOCAL steps with no communication at all, and
  k grows on a schedule (``local_step_scaler`` / ``local_step_clipper``),
  amortizing even the 1-bit wire over k steps.

SPMD-honest formulation: the paper lets worker replicas diverge between
syncs.  Under the engine's shard_map step (replicated params, out_specs
P()) silently-divergent params would break the replication invariant the
checkpoint/eval paths rely on, so local rounds here ACCUMULATE the device-
local gradient into a per-device buffer instead of applying it; the sync
round averages the accumulated k-step gradient through the 1-bit wire
(:func:`~deepspeed_tpu.runtime.custom_collectives.quantized_all_reduce`)
and applies one lr*k-compensated update.  Per-device divergence is
confined to the error-feedback residuals and the local accumulator —
exactly the state that already carries a leading per-device axis.  The
parity caveat (forward does not see local progress between syncs) is
documented in docs/tutorials/quantized_comms.md.

Phase selection is a PURE FUNCTION of the completed-optimizer-step count
(:func:`zeroone_cadence`), so an elastic resume re-derives the phase from
restored counters alone.
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.custom_collectives import (
    quantized_all_reduce, quantized_error_feedback)


def zeroone_cadence(completed_steps, var_freeze_step, local_steps=1,
                    local_step_scaler=0, local_step_clipper=16):
    """(phase, k_round) for the optimizer step about to be taken after
    ``completed_steps`` finished ones.  Pure host-side function of the
    step index — the engine (and an elastic resume) re-derive the phase
    from counters, never from traced state.

    - ``completed_steps < var_freeze_step`` -> ``('warmup', 1)``: exact
      (bias-correction-free) Adam, v still updating.
    - after the freeze, steps are partitioned into rounds of length k:
      ``k - 1`` 'local' steps then one 'sync' step.  k starts at
      ``local_steps`` and doubles every ``local_step_scaler`` ROUNDS
      (0 = fixed k), capped at ``local_step_clipper`` (0 = uncapped) —
      the deterministic variant of the paper's adaptive policy.

    ``k_round`` is the length of the current round (1 during warmup):
    the sync step scales lr by it and divides the accumulated gradient.
    """
    if completed_steps < var_freeze_step:
        return "warmup", 1
    j = completed_steps - var_freeze_step
    start, r = 0, 0
    while True:
        k = max(1, int(local_steps))
        if local_step_scaler:
            k = k * (2 ** (r // int(local_step_scaler)))
        if local_step_clipper:
            k = min(k, max(1, int(local_step_clipper)))
        if j < start + k:
            return ("sync" if j == start + k - 1 else "local"), k
        start += k
        r += 1


class ZeroOneAdamState(NamedTuple):
    step: object           # i32 — completed optimizer steps (every phase)
    m: object              # momentum pytree, fp32, replicated
    v: object              # variance pytree, fp32 (frozen after warmup)
    worker_error: object   # per-device EF residual pytree (worker stage)
    server_error: object   # per-device EF residual pytree (server chunks)
    local_accum: object    # per-device gradient accumulator (local rounds)


class ZeroOneAdam:
    name = "zerooneadam"

    def __init__(self, lr=1e-3, var_freeze_step=100000, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, max_grad_norm=0.0,
                 local_steps=1, local_step_scaler=0, local_step_clipper=16,
                 bits=1, quantization_block_size=None, intra_size=0,
                 cuda_aware=False, comm_backend_name="xla", mesh=None,
                 axis_name=None, axis_size=1):
        self.lr = lr
        self.var_freeze_step = var_freeze_step
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.local_steps = local_steps
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper
        self.bits = bits
        self.quantization_block_size = quantization_block_size
        self.intra_size = intra_size
        self.comm_backend_name = comm_backend_name
        self.mesh = mesh
        # when set, sync rounds run the true packed-wire collective inside
        # shard_map with this axis bound; per-device state (residuals +
        # accumulator) carries a leading (axis_size,) dim sharded over it
        self.axis_name = axis_name
        self.axis_size = axis_size

    def cadence(self, completed_steps):
        return zeroone_cadence(completed_steps, self.var_freeze_step,
                               self.local_steps, self.local_step_scaler,
                               self.local_step_clipper)

    def _chunk(self, n):
        """Per-device server-residual length for an n-element leaf: the
        leaf is padded to a multiple of the axis size before the wire."""
        w = max(1, self.axis_size if self.axis_name is not None else 1)
        return (n + (-n) % w) // w

    def init_state(self, master_params) -> ZeroOneAdamState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), master_params)
        if self.axis_name is not None:
            dev = lambda: jax.tree_util.tree_map(
                lambda p: jnp.zeros((self.axis_size,) + p.shape,
                                    jnp.float32), master_params)
            serr = lambda: jax.tree_util.tree_map(
                lambda p: jnp.zeros((self.axis_size, self._chunk(p.size)),
                                    jnp.float32), master_params)
        else:
            dev = zeros
            serr = zeros
        return ZeroOneAdamState(step=jnp.int32(0), m=zeros(), v=zeros(),
                                worker_error=dev(), server_error=serr(),
                                local_accum=dev())

    def update(self, grads, state: ZeroOneAdamState, master_params,
               lr=None, scale=1.0, phase="warmup", k_round=1):
        """One optimizer step of the statically-selected ``phase``
        ('warmup' | 'sync' | 'local', from :func:`zeroone_cadence` for
        ``state.step``).  The engine compiles one program per phase, so
        local-round HLO provably contains ZERO cross-device collectives
        and sync-round HLO only the packed sub-byte wire.  ``k_round``
        (traced scalar ok) is the current round length: the sync step
        divides the accumulated gradient and scales lr by it."""
        assert phase in ("warmup", "sync", "local"), phase
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        kf = jnp.float32(k_round)

        def leaf(g, m, v, we, se, acc, p):
            g = g.astype(jnp.float32) / scale

            if phase == "local":
                # accumulate only: params, m, v untouched — no collective
                return p, m, v, we, se, acc + g

            if phase == "warmup":
                g_sync = jax.lax.pmean(g, self.axis_name) \
                    if self.axis_name is not None else g
                m_out = b1 * m + (1.0 - b1) * g_sync
                v_out = b2 * v + (1.0 - b2) * jnp.square(g_sync)
                acc_out, we_out, se_out = acc, we, se
                lr_eff = lr
            else:  # sync: compressed round gradient, frozen variance
                g_round = (acc + g) / kf
                flat = g_round.reshape(-1)
                fwe = we.reshape(-1)
                fse = se.reshape(-1)
                if self.axis_name is not None:
                    pad = (-flat.size) % self.axis_size
                    g_avg, we_new, se_new = quantized_all_reduce(
                        jnp.pad(flat, (0, pad)), self.axis_name,
                        bits=self.bits,
                        block_size=self.quantization_block_size,
                        intra_size=self.intra_size,
                        worker_error=jnp.pad(fwe, (0, pad)),
                        server_error=fse)
                    g_avg = g_avg[:flat.size]
                    we_new = we_new[:flat.size]
                else:
                    g_avg, we_new, se_new = quantized_error_feedback(
                        flat, fwe, fse, bits=self.bits,
                        block_size=self.quantization_block_size)
                m_out = b1 * m + (1.0 - b1) * g_avg.reshape(m.shape)
                v_out = v
                we_out = we_new.reshape(we.shape)
                se_out = se_new.reshape(se.shape)
                acc_out = jnp.zeros_like(acc)
                # one update stands in for the k steps of its round
                lr_eff = lr * kf

            update = m_out / (jnp.sqrt(v_out) + self.eps)
            if self.weight_decay > 0.0:
                update = update + self.weight_decay * p
            return p - lr_eff * update, m_out, v_out, we_out, se_out, acc_out

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat = lambda t: jax.tree_util.tree_leaves(t)
        outs = [leaf(g, m, v, we, se, acc, p) for g, m, v, we, se, acc, p in
                zip(flat_g, flat(state.m), flat(state.v),
                    flat(state.worker_error), flat(state.server_error),
                    flat(state.local_accum), flat(master_params))]
        unf = treedef.unflatten
        new_p, new_m, new_v, new_we, new_se, new_acc = \
            (unf(list(t)) for t in zip(*outs))
        return new_p, ZeroOneAdamState(step=step, m=new_m, v=new_v,
                                       worker_error=new_we,
                                       server_error=new_se,
                                       local_accum=new_acc)

    def state_spec(self, param_specs):
        from jax.sharding import PartitionSpec as P

        err_specs = param_specs
        chunk_specs = param_specs
        if self.axis_name is not None:
            # per-device state: leading dim sharded over the axis; server
            # residuals are 2-D (axis_size, chunk) regardless of leaf rank
            err_specs = jax.tree_util.tree_map(
                lambda s: P(self.axis_name, *s), param_specs,
                is_leaf=lambda x: isinstance(x, P))
            chunk_specs = jax.tree_util.tree_map(
                lambda s: P(self.axis_name, None), param_specs,
                is_leaf=lambda x: isinstance(x, P))
        return ZeroOneAdamState(step=None, m=param_specs, v=param_specs,
                                worker_error=err_specs,
                                server_error=chunk_specs,
                                local_accum=err_specs)
