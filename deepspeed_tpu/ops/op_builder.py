"""JIT builder for native (C++) ops — the reference op_builder analog.

Reference behavior: op_builder/builder.py:78-286 (JIT ninja compile via
torch cpp_extension, AVX capability autodetect, compatibility checks).
Here: direct g++ -shared compile of C sources into a cached .so loaded with
ctypes (no pybind11/torch in the loop), with the same per-op builder-class
shape so `ds_report` can enumerate ops and their compatibility.
"""
import ctypes
import os
import subprocess
import tempfile

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_CACHE_DIR = os.environ.get(
    "DSTPU_OPS_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops"))


class OpBuilder:
    NAME = "base"
    SOURCES = []           # repo-relative .cpp paths
    EXTRA_FLAGS = []

    def absolute_sources(self):
        return [os.path.join(_REPO_ROOT, s) for s in self.SOURCES]

    def is_compatible(self):
        if not all(os.path.exists(s) for s in self.absolute_sources()):
            return False
        try:
            subprocess.run(["g++", "--version"], capture_output=True,
                           check=True)
            return True
        except (OSError, subprocess.CalledProcessError):
            return False

    def cpu_arch_flags(self):
        """March autodetect (reference op_builder/cpu_adam.py:24-40)."""
        flags = ["-march=native"]
        try:
            with open("/proc/cpuinfo") as f:
                info = f.read()
            if "avx512f" not in info and "avx2" not in info:
                flags = []
        except OSError:
            pass
        return flags

    def so_path(self):
        return os.path.join(_CACHE_DIR, f"{self.NAME}.so")

    def jit_load(self):
        """Compile (if stale) and dlopen. Returns a ctypes.CDLL or None on
        failure (callers fall back to the numpy path)."""
        sources = self.absolute_sources()
        so = self.so_path()
        if not self.is_compatible():
            logger.warning(f"op '{self.NAME}': no compatible toolchain; "
                           f"using fallback implementation")
            return None
        stale = not os.path.exists(so) or any(
            os.path.getmtime(s) > os.path.getmtime(so) for s in sources)
        if stale:
            os.makedirs(_CACHE_DIR, exist_ok=True)
            # unique temp per process: concurrent builders (multi-host NFS
            # home, parallel pytest) must not interleave writes; os.replace
            # promotes atomically, last writer wins
            tmp = f"{so}.tmp.{os.getpid()}"
            cmd = (["g++", "-O3", "-shared", "-fPIC", "-fopenmp"]
                   + self.cpu_arch_flags() + self.EXTRA_FLAGS
                   + sources + ["-o", tmp])
            try:
                subprocess.run(cmd, capture_output=True, check=True, text=True)
                os.replace(tmp, so)
                logger.info(f"op '{self.NAME}': compiled {so}")
            except subprocess.CalledProcessError as e:
                logger.warning(f"op '{self.NAME}': compile failed "
                               f"({e.stderr[-500:] if e.stderr else e}); "
                               f"using fallback implementation")
                return None
        try:
            return ctypes.CDLL(so)
        except OSError as e:
            logger.warning(f"op '{self.NAME}': dlopen failed ({e})")
            return None


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"
    SOURCES = ["csrc/adam/cpu_adam.cpp"]

    def load(self):
        lib = self.jit_load()
        if lib is None:
            return None
        lib.ds_adam_step.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int,
            ctypes.c_int64, ctypes.c_float]
        lib.ds_fp32_to_bf16.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_int64]
        lib.ds_fp32_to_fp16.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_int64]
        lib.ds_simd_width.restype = ctypes.c_int
        return lib


ALL_OPS = {"cpu_adam": CPUAdamBuilder}
