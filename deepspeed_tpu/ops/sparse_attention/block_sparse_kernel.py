"""Pallas TPU block-sparse attention — layout-driven flash kernel.

TPU-native replacement for the reference's Triton SDD/softmax/DSD pipeline
(reference deepspeed/ops/sparse_attention/matmul.py:16-750, softmax.py:17-304,
trsrc/*.tr): instead of three kernel launches with materialized block-sparse
score storage, ONE fused kernel walks, per (batch*head, q_block), only the
active k-blocks listed in a lookup table built from the SparsityConfig
layout (the analog of the reference's LUT construction, matmul.py:98-241),
maintaining a flash-style online softmax. Compute and memory are
O(active_blocks), giving the reference's "10x longer sequences" scaling law
on the MXU.

LUT encoding (host-built from the (H, nb, nb) layout):
  cols[h, qb, a]  = column (k-block) index of the a'th active block
  nnz[h, qb]      = number of active blocks in the row
  rows_t / nnz_t  = the transpose LUT (per k-block active q-blocks), used by
                    the dk/dv backward sweep.
Padded entries point at block 0 and are skipped via `a < nnz`.

Masking is block-granular, matching the XLA reference path
(sparse_self_attention.layout_to_token_mask).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret_default() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # pragma: no cover
        return True


def build_luts(layout):
    """layout (H, nb, nb) 0/1 -> (cols, nnz, rows_t, nnz_t) int32 arrays.

    cols: (H, nb, max_nnz) forward LUT; rows_t: (H, nb, max_nnz_t)
    transpose LUT. Padding entries are 0 (skipped via the nnz counts)."""
    layout = np.asarray(layout) != 0
    H, nb, _ = layout.shape
    nnz = layout.sum(-1).astype(np.int32)                  # (H, nb)
    nnz_t = layout.sum(1).astype(np.int32)                 # (H, nb)
    max_nnz = max(1, int(nnz.max()))
    max_nnz_t = max(1, int(nnz_t.max()))
    cols = np.zeros((H, nb, max_nnz), np.int32)
    rows_t = np.zeros((H, nb, max_nnz_t), np.int32)
    for h in range(H):
        for qb in range(nb):
            idx = np.flatnonzero(layout[h, qb])
            cols[h, qb, :len(idx)] = idx
        for kb in range(nb):
            idx = np.flatnonzero(layout[h, :, kb])
            rows_t[h, kb, :len(idx)] = idx
    return cols, nnz, rows_t, nnz_t


# ---------------------------------------------------------------------------
# forward: grid (bh, nq, max_nnz), k/v blocks indexed through the LUT
# ---------------------------------------------------------------------------
def _fwd_kernel(cols_ref, nnz_ref, *refs, scale, heads, max_nnz, nq,
                has_bias):
    if has_bias:
        (q_ref, k_ref, v_ref, kb_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        kb_ref = None
    ai = pl.program_id(2)

    @pl.when(ai == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    b = pl.program_id(0)
    qi = pl.program_id(1)
    h = jax.lax.rem(b, heads)
    active = ai < nnz_ref[h * nq + qi]

    @pl.when(active)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if kb_ref is not None:
            # per-key additive bias (key padding): (1, block) row broadcast
            s = s + kb_ref[...]
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, 0:1] * alpha + jnp.sum(p, -1, keepdims=True),
            l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ai == max_nnz - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # empty rows (no active block) emit zeros, like the XLA path
        o_ref[0] = jnp.where(l > 0.0, acc_scr[:] / l_safe, 0.0
                             ).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m_scr[:, 0:1] + jnp.log(l_safe),
                                      lse_ref.shape[1:])


def _sparse_fwd(q, k, v, cols, nnz, *, scale, block, heads, interpret,
                key_bias=None):
    bh, S, d = q.shape
    nq = S // block
    max_nnz = cols.shape[-1]
    cols_flat = jnp.asarray(np.asarray(cols).reshape(-1), jnp.int32)
    nnz_flat = jnp.asarray(np.asarray(nnz).reshape(-1), jnp.int32)

    def kv_index(b, qi, ai, cols_ref, nnz_ref):
        h = jax.lax.rem(b, heads)
        kb = cols_ref[(h * nq + qi) * max_nnz + ai]
        return (b, kb, 0)

    def kb_index(b, qi, ai, cols_ref, nnz_ref):
        h = jax.lax.rem(b, heads)
        kb = cols_ref[(h * nq + qi) * max_nnz + ai]
        return (b // heads, kb)

    bias_ops = [] if key_bias is None else [key_bias]
    bias_specs = [] if key_bias is None else \
        [pl.BlockSpec((1, block), kb_index)]
    kernel = functools.partial(_fwd_kernel, scale=scale, heads=heads,
                               max_nnz=max_nnz, nq=nq,
                               has_bias=key_bias is not None)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, max_nnz),
        in_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, qi, ai, cols_ref, nnz_ref: (b, qi, 0)),
            pl.BlockSpec((1, block, d), kv_index),
            pl.BlockSpec((1, block, d), kv_index),
        ] + bias_specs,
        out_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, qi, ai, cols_ref, nnz_ref: (b, qi, 0)),
            pl.BlockSpec((1, block, 128),
                         lambda b, qi, ai, cols_ref, nnz_ref: (b, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((bh, S, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, S, 128), jnp.float32)],
        interpret=interpret,
    )(cols_flat, nnz_flat, q, k, v, *bias_ops)
    return out, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward: dq walks the forward LUT; dk/dv walk the transpose LUT
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(cols_ref, nnz_ref, *refs, scale, heads, max_nnz, nq,
                   has_bias):
    if has_bias:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kb_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
        kb_ref = None
    ai = pl.program_id(2)

    @pl.when(ai == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    b = pl.program_id(0)
    qi = pl.program_id(1)
    h = jax.lax.rem(b, heads)
    active = ai < nnz_ref[h * nq + qi]

    @pl.when(active)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]
        delta = delta_ref[0][:, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if kb_ref is not None:
            s = s + kb_ref[...]
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ai == max_nnz - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkdv_kernel(rows_ref, nnzt_ref, *refs, scale, heads, max_nnz_t, nk,
                     has_bias):
    if has_bias:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kb_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        kb_ref = None
    ai = pl.program_id(2)

    @pl.when(ai == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    b = pl.program_id(0)
    ki = pl.program_id(1)
    h = jax.lax.rem(b, heads)
    active = ai < nnzt_ref[h * nk + ki]

    @pl.when(active)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]
        delta = delta_ref[0][:, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if kb_ref is not None:
            # this kernel's s is (q_rows, k_rows) with k fixed to block ki:
            # the bias row for block ki broadcasts over q rows
            s = s + kb_ref[...]
        p = jnp.exp(s - lse)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ai == max_nnz_t - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _sparse_bwd(res, do, *, scale, block, heads, interpret):
    q, k, v, key_bias, out, lse, cols, nnz, rows_t, nnz_t = res
    bh, S, d = q.shape
    nq = S // block
    max_nnz = cols.shape[-1]
    max_nnz_t = rows_t.shape[-1]
    cols_flat = jnp.asarray(np.asarray(cols).reshape(-1), jnp.int32)
    nnz_flat = jnp.asarray(np.asarray(nnz).reshape(-1), jnp.int32)
    rows_flat = jnp.asarray(np.asarray(rows_t).reshape(-1), jnp.int32)
    nnzt_flat = jnp.asarray(np.asarray(nnz_t).reshape(-1), jnp.int32)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                    # (bh, S)
    lse_w = jnp.broadcast_to(lse[:, :, None], (bh, S, 128)).astype(jnp.float32)
    delta_w = jnp.broadcast_to(delta[:, :, None], (bh, S, 128))

    def q_row(b, i, ai, *refs):
        return (b, i, 0)

    # ---- dq: forward LUT ------------------------------------------------
    def kv_from_cols(b, qi, ai, cols_ref, nnz_ref):
        h = jax.lax.rem(b, heads)
        return (b, cols_ref[(h * nq + qi) * max_nnz + ai], 0)

    def kb_from_cols(b, qi, ai, cols_ref, nnz_ref):
        h = jax.lax.rem(b, heads)
        return (b // heads, cols_ref[(h * nq + qi) * max_nnz + ai])

    bias_ops = [] if key_bias is None else [key_bias]
    dq_bias_specs = [] if key_bias is None else \
        [pl.BlockSpec((1, block), kb_from_cols)]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, heads=heads,
                          max_nnz=max_nnz, nq=nq,
                          has_bias=key_bias is not None),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nq, max_nnz),
            in_specs=[
                pl.BlockSpec((1, block, d), q_row),
                pl.BlockSpec((1, block, d), kv_from_cols),
                pl.BlockSpec((1, block, d), kv_from_cols),
                pl.BlockSpec((1, block, d), q_row),
                pl.BlockSpec((1, block, 128), q_row),
                pl.BlockSpec((1, block, 128), q_row),
            ] + dq_bias_specs,
            out_specs=pl.BlockSpec((1, block, d), q_row),
            scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, S, d), q.dtype),
        interpret=interpret,
    )(cols_flat, nnz_flat, q, k, v, do, lse_w, delta_w, *bias_ops)

    # ---- dk/dv: transpose LUT ------------------------------------------
    def q_from_rows(b, ki, ai, rows_ref, nnzt_ref):
        h = jax.lax.rem(b, heads)
        return (b, rows_ref[(h * nq + ki) * max_nnz_t + ai], 0)

    def k_row(b, ki, ai, *refs):
        return (b, ki, 0)

    dkdv_bias_specs = [] if key_bias is None else \
        [pl.BlockSpec((1, block), lambda b, ki, ai, *r: (b // heads, ki))]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, heads=heads,
                          max_nnz_t=max_nnz_t, nk=nq,
                          has_bias=key_bias is not None),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nq, max_nnz_t),
            in_specs=[
                pl.BlockSpec((1, block, d), q_from_rows),
                pl.BlockSpec((1, block, d), k_row),
                pl.BlockSpec((1, block, d), k_row),
                pl.BlockSpec((1, block, d), q_from_rows),
                pl.BlockSpec((1, block, 128), q_from_rows),
                pl.BlockSpec((1, block, 128), q_from_rows),
            ] + dkdv_bias_specs,
            out_specs=[pl.BlockSpec((1, block, d), k_row),
                       pl.BlockSpec((1, block, d), k_row)],
            scratch_shapes=[pltpu.VMEM((block, d), jnp.float32),
                            pltpu.VMEM((block, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bh, S, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, S, d), v.dtype)],
        interpret=interpret,
    )(rows_flat, nnzt_flat, q, k, v, do, lse_w, delta_w, *bias_ops)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry: differentiable block-sparse attention over a layout
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _sparse_attention_core(q3, k3, v3, key_bias, luts, scale, heads,
                           interpret):
    out, _ = _sparse_fwd(q3, k3, v3, luts[0], luts[1], scale=scale,
                         block=q3.shape[1] // luts[1].shape[1], heads=heads,
                         interpret=interpret, key_bias=key_bias)
    return out


def _core_fwd(q3, k3, v3, key_bias, luts, scale, heads, interpret):
    block = q3.shape[1] // luts[1].shape[1]
    out, lse = _sparse_fwd(q3, k3, v3, luts[0], luts[1], scale=scale,
                           block=block, heads=heads, interpret=interpret,
                           key_bias=key_bias)
    return out, (q3, k3, v3, key_bias, out, lse)


def _core_bwd(luts, scale, heads, interpret, res, do):
    q3, k3, v3, key_bias, out, lse = res
    block = q3.shape[1] // luts[1].shape[1]
    full_res = (q3, k3, v3, key_bias, out, lse,
                luts[0], luts[1], luts[2], luts[3])
    dq, dk, dv = _sparse_bwd(full_res, do, scale=scale, block=block,
                             heads=heads, interpret=interpret)
    # key padding is a constant mask, no gradient (flash kernel convention)
    dkb = None if key_bias is None else jnp.zeros_like(key_bias)
    return dq, dk, dv, dkb


_sparse_attention_core.defvjp(_core_fwd, _core_bwd)


def pallas_block_sparse_attention(q, k, v, layout, block: int,
                                  scale: Optional[float] = None,
                                  key_bias=None,
                                  interpret: Optional[bool] = None):
    """(B, H, S, D) block-sparse attention over a (H, S/block, S/block)
    layout via the LUT-driven Pallas kernels. Differentiable in q/k/v.

    key_bias: optional (B, S) ADDITIVE per-key bias (key-padding mask,
    -inf/-1e30 for padded keys) applied inside the kernel — long-sequence
    BERT keeps its padding mask without falling back to the O(S^2) path.
    Treated as constant (no gradient)."""
    if interpret is None:
        interpret = _interpret_default()
    B, H, S, D = q.shape
    assert S % block == 0
    scale = (D ** -0.5) if scale is None else scale
    luts = build_luts(layout)
    # hashable static LUTs for custom_vjp nondiff arg
    luts = tuple(np.asarray(a) for a in luts)
    q3 = q.reshape(B * H, S, D)
    k3 = k.reshape(B * H, S, D)
    v3 = v.reshape(B * H, S, D)
    if key_bias is not None:
        assert key_bias.shape == (B, S), key_bias.shape
        key_bias = jnp.asarray(key_bias, jnp.float32)
    out = _sparse_attention_core(q3, k3, v3, key_bias, _HashableLuts(luts),
                                 scale, H, interpret)
    return out.reshape(B, H, S, D)


class _HashableLuts(tuple):
    """numpy LUTs as a hashable static arg (id-keyed hash is fine: LUTs are
    rebuilt per layout object and layouts are cached by SparseSelfAttention)."""

    def __new__(cls, arrays):
        return super().__new__(cls, arrays)

    def __hash__(self):
        return hash(tuple(a.tobytes() for a in self))

    def __eq__(self, other):
        return isinstance(other, _HashableLuts) and \
            all((a == b).all() for a, b in zip(self, other))
