from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparsityConfig, VariableSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention, block_sparse_attention, layout_to_token_mask)
from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
    SparseAttentionUtils)
