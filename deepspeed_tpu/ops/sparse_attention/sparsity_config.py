"""Block-sparse attention layout generators.

Reference behavior: deepspeed/ops/sparse_attention/sparsity_config.py:9-663
(Dense / Fixed / Variable / BigBird / BSLongformer patterns). Pure layout
math, re-implemented vectorized over numpy: every config emits an int
{0,1} array of shape (num_heads, seq_len//block, seq_len//block) where
layout[h, i, j] == 1 means query block i attends to key block j for head h.

The layouts feed the TPU block-sparse kernels (ops/sparse_attention/
sparse_self_attention.py) exactly as they fed the reference's Triton SDD/DSD
kernels — the generators are framework-agnostic.
"""
import random

import numpy as np


class SparsityConfig:
    """Shared config: head count, block size, per-head layout switch
    (reference sparsity_config.py:9-62)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence Length {seq_len} must be divisible by block size "
                f"{self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def _causal_clip(self, layout, h):
        """Zero the strict upper triangle for unidirectional attention."""
        n = layout.shape[1]
        layout[h] &= np.tril(np.ones((n, n), dtype=np.int64))
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks on — for comparison/debug (reference :63-94)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer-style fixed pattern: non-overlapping local windows
    + fixed global block columns (reference :97-243; Child et al. 2019)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                'only "uni/bi-directional" attentions are supported')
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                'only "bi-directional" attention supports horizontal global '
                'attention')
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "multiple global patterns require different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns "
                f"{num_different_global_patterns} cannot exceed "
                f"{num_local_blocks // num_global_blocks}")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        n = layout.shape[1]
        for start in range(0, n, self.num_local_blocks):
            end = min(start + self.num_local_blocks, n)
            layout[h, start:end, start:end] = 1
        if self.attention == "unidirectional":
            self._causal_clip(layout, h)
        return layout

    def set_global_layout(self, h, layout):
        n = layout.shape[1]
        # representative block of each window, rotated per head pattern
        first = self.num_local_blocks - (
            1 + h % self.num_different_global_patterns) * self.num_global_blocks
        end = n - (n % self.num_local_blocks)
        cols = list(range(first, end, self.num_local_blocks))
        # short trailing window keeps a (clamped) representative too
        if end < n:
            cols.append(min(end + first, n - self.num_global_blocks))
        for c in cols:
            first_row = 0 if self.attention == "bidirectional" else c
            layout[h, first_row:, c:c + self.num_global_blocks] = 1
            if self.horizontal_global_attention:
                layout[h, c:c + self.num_global_blocks, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed pattern generalized: random blocks + variable-width local
    windows + user-chosen global indices (reference :246-419)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices \
            if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have equal length")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                'only "uni/bi-directional" attentions are supported')
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                'only "bi-directional" attention supports horizontal global '
                'attention')
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def set_random_layout(self, h, layout):
        n = layout.shape[1]
        if n < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks {self.num_random_blocks} must be < "
                f"number of block rows {n}")
        for row in range(n):
            cols = random.sample(range(n), self.num_random_blocks)
            layout[h, row, cols] = 1
        return layout

    def set_local_layout(self, h, layout):
        n = layout.shape[1]
        start = 0
        for size in self.local_window_blocks:
            end = min(start + size, n)
            layout[h, start:end, start:end] = 1
            start += size
        # remaining windows reuse the last listed width
        while start < n:
            end = min(start + size, n)
            layout[h, start:end, start:end] = 1
            start += size
        if self.attention == "unidirectional":
            self._causal_clip(layout, h)
        return layout

    def set_global_layout(self, h, layout):
        n = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for s, e in spans:
            if s >= n:
                continue
            e = min(e, n)
            first_row = 0 if self.attention == "bidirectional" else s
            layout[h, first_row:, s:e] = 1
            if self.horizontal_global_attention:
                layout[h, s:e, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird ITC: random + sliding window + leading global blocks
    (reference :422-541; Zaheer et al. 2020)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks

    def set_random_layout(self, h, layout):
        n = layout.shape[1]
        if n < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks {self.num_random_blocks} must be < {n}")
        for row in range(n):
            cols = random.sample(range(n), self.num_random_blocks)
            layout[h, row, cols] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        n = layout.shape[1]
        if n < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks {self.num_sliding_window_blocks} "
                f"must be < {n}")
        w = self.num_sliding_window_blocks // 2
        rows = np.arange(n)[:, None]
        cols = np.arange(n)[None, :]
        layout[h] |= (np.abs(rows - cols) <= w).astype(np.int64)
        return layout

    def set_global_layout_itc(self, h, layout):
        n = layout.shape[1]
        if n < self.num_global_blocks:
            raise ValueError(
                f"num_global_blocks {self.num_global_blocks} must be < {n}")
        layout[h, :self.num_global_blocks, :] = 1
        layout[h, :, :self.num_global_blocks] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + symmetric global indices
    (reference :544-663; Beltagy et al. 2020)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices \
            if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have equal length")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        self.global_block_end_indices = global_block_end_indices

    def set_sliding_window_layout(self, h, layout):
        n = layout.shape[1]
        if n < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks {self.num_sliding_window_blocks} "
                f"must be < {n}")
        w = self.num_sliding_window_blocks // 2
        rows = np.arange(n)[:, None]
        cols = np.arange(n)[None, :]
        layout[h] |= (np.abs(rows - cols) <= w).astype(np.int64)
        return layout

    def set_global_layout(self, h, layout):
        n = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for s, e in spans:
            if s >= n:
                continue
            e = min(e, n)
            layout[h, s:e, :] = 1
            layout[h, :, s:e] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)
