"""Block-sparse self-attention over a SparsityConfig layout.

Reference behavior: deepspeed/ops/sparse_attention/sparse_self_attention.py:
14-164 (QKV -> SDD block matmul -> scaled masked block softmax -> DSD block
matmul, driven by a per-head block layout) with Triton kernels
(matmul.py:16-750, softmax.py:17-304).

TPU formulation: the layout expands to a block mask consumed by a fused
masked flash-style computation. Two execution paths:
- `block_sparse_attention` (default): XLA path — scores masked by the
  layout before softmax; XLA fuses mask+softmax+matmul, and masked blocks
  are skipped at the block level when the layout is head-uniform banded.
- a Pallas kernel that walks only active blocks per query-row (planned;
  tracked as the perf milestone — the API is identical, so callers are
  unaffected).

Masks follow the reference semantics: `key_padding_mask_mode`/
`attn_mask_mode` are 'add' (additive logits) or 'mul' (multiplicative 0/1)
(reference sparse_self_attention.py:27-43); `rpe` is added to the scores
(relative position embedding, reference softmax.py:17-219).
"""
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)


def layout_to_token_mask(layout, block: int):
    """(H, nb, nb) 0/1 block layout -> (H, S, S) boolean token mask."""
    import jax.numpy as jnp

    layout = jnp.asarray(layout, bool)
    return jnp.repeat(jnp.repeat(layout, block, axis=1), block, axis=2)


def block_sparse_attention(q, k, v, layout, block: int,
                           rpe=None, key_padding_mask=None, attn_mask=None,
                           key_padding_mask_mode: str = "add",
                           attn_mask_mode: str = "mul",
                           scale: Optional[float] = None,
                           use_pallas: Optional[bool] = None):
    """Masked block-sparse attention.

    q/k/v: (B, H, S, D); layout: (H, S/block, S/block) 0/1;
    rpe: (S, S) or broadcastable additive bias;
    key_padding_mask: (B, S) — 'add': float additions (-inf for pad),
        'mul': 0/1 multiplier; attn_mask: (S, S) likewise.

    On TPU with no rpe/masks, dispatches to the LUT-driven Pallas kernel
    (block_sparse_kernel.py) — O(active blocks) compute/memory; otherwise
    the XLA masked path runs (O(S^2) compute, still fused).
    """
    import jax
    import jax.numpy as jnp

    if use_pallas is None:
        # key padding rides the kernel as an in-kernel additive bias; only
        # rpe / full attn_mask (dense S x S structures) force the XLA path
        use_pallas = (rpe is None and attn_mask is None
                      and jax.default_backend() == "tpu"
                      and q.shape[2] % block == 0)
    if use_pallas:
        from deepspeed_tpu.ops.sparse_attention.block_sparse_kernel import \
            pallas_block_sparse_attention

        assert rpe is None and attn_mask is None
        key_bias = None
        if key_padding_mask is not None:
            kpm = jnp.asarray(key_padding_mask, jnp.float32)
            if key_padding_mask_mode == "mul":
                key_bias = jnp.where(kpm != 0, 0.0, -1e30)
            elif key_padding_mask_mode == "add":
                key_bias = kpm
            else:
                raise ValueError(
                    f"unknown key_padding_mask_mode "
                    f"{key_padding_mask_mode!r}")
        return pallas_block_sparse_attention(q, k, v, layout, block,
                                             scale=scale, key_bias=key_bias)

    B, H, S, D = q.shape
    nb = S // block
    assert layout.shape[-1] == nb, \
        f"layout {layout.shape} does not match seq {S} / block {block}"
    scale = (D ** -0.5) if scale is None else scale

    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if rpe is not None:
        scores = scores + jnp.asarray(rpe, jnp.float32)
    if attn_mask is not None:
        am = jnp.asarray(attn_mask, jnp.float32)
        if attn_mask_mode == "mul":
            scores = jnp.where(am[None, None] != 0, scores, -1e30)
        elif attn_mask_mode == "add":
            scores = scores + am[None, None]
        else:
            raise ValueError(f"unknown attn_mask_mode {attn_mask_mode!r}")
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask, jnp.float32)
        if key_padding_mask_mode == "mul":
            scores = jnp.where(kpm[:, None, None, :] != 0, scores, -1e30)
        elif key_padding_mask_mode == "add":
            scores = scores + kpm[:, None, None, :]
        else:
            raise ValueError(
                f"unknown key_padding_mask_mode {key_padding_mask_mode!r}")

    tok_mask = layout_to_token_mask(layout, block)        # (H, S, S)
    scores = jnp.where(tok_mask[None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked rows (no active block) produce uniform probs over -1e30
    # logits; zero them like the reference kernel's empty-row behavior
    any_active = jnp.any(tok_mask, axis=-1)               # (H, S)
    probs = probs * any_active[None, :, :, None]
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


class SparseSelfAttention:
    """Module-style wrapper with the reference's call signature
    (reference sparse_self_attention.py:14-60, forward :110-164)."""

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul"):
        self.sparsity_config = sparsity_config or \
            FixedSparsityConfig(num_heads=4)
        assert key_padding_mask_mode in ("add", "mul")
        assert attn_mask_mode in ("add", "mul")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layout_cache = {}   # seq_len -> layout (reference master_layout)

    def get_layout(self, seq_len):
        if seq_len not in self._layout_cache:
            self._layout_cache[seq_len] = np.asarray(
                self.sparsity_config.make_layout(seq_len))
        return self._layout_cache[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        B, H, S, D = query.shape
        assert H == self.sparsity_config.num_heads, \
            f"input has {H} heads, sparsity config has " \
            f"{self.sparsity_config.num_heads}"
        layout = self.get_layout(S)
        return block_sparse_attention(
            query, key, value, layout, self.sparsity_config.block,
            rpe=rpe, key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask_mode=self.attn_mask_mode)

    # torch-API alias
    forward = __call__
