"""Helpers for using block-sparse attention with real models.

Reference behavior: deepspeed/ops/sparse_attention/sparse_attention_utils.py:
13-225 (pad/unpad sequences to a block multiple, extend position
embeddings). The HF-model surgery part of the reference
(replace_self_attention_layer_with_sparse_self_attention_layer) lives with
module_inject in this build.
"""
from typing import Optional

import numpy as np


class SparseAttentionUtils:
    @staticmethod
    def extend_position_embedding(pos_embedding, max_position: int):
        """Tile an existing (P, E) position-embedding table to cover
        max_position rows (reference :25-59 extends HF models in place; here
        the array is returned for functional param surgery)."""
        import jax.numpy as jnp

        pos_embedding = jnp.asarray(pos_embedding)
        P, E = pos_embedding.shape
        assert max_position > P, \
            f"max_position {max_position} must exceed current {P}"
        reps = -(-max_position // P)
        return jnp.tile(pos_embedding, (reps, 1))[:max_position]

    @staticmethod
    def pad_to_block_size(block_size: int, input_ids=None, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id: int = 0,
                          model_embeddings=None):
        """Pad sequence dim (axis 1) up to a block multiple.

        Returns (pad_len, input_ids, attention_mask, token_type_ids,
        position_ids, inputs_embeds) — the reference's tuple layout
        (reference :61-147). Padded attention-mask entries are 0 so padding
        never attends/attended.
        """
        import jax.numpy as jnp

        ref = input_ids if input_ids is not None else inputs_embeds
        assert ref is not None, "need input_ids or inputs_embeds"
        seq_len = ref.shape[1]
        pad_len = (-seq_len) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad(x, value=0):
            if x is None:
                return None
            widths = [(0, 0)] * x.ndim
            widths[1] = (0, pad_len)
            return jnp.pad(jnp.asarray(x), widths, constant_values=value)

        input_ids = pad(input_ids, pad_token_id)
        attention_mask = pad(attention_mask, 0)
        token_type_ids = pad(token_type_ids, 0)
        if position_ids is not None:
            # continue positions monotonically so extended tables index fine
            import jax.numpy as jnp2

            extra = jnp2.arange(seq_len, seq_len + pad_len)
            extra = jnp2.broadcast_to(extra, position_ids.shape[:-1] +
                                      (pad_len,))
            position_ids = jnp2.concatenate(
                [jnp2.asarray(position_ids), extra], axis=1)
        if inputs_embeds is not None:
            assert model_embeddings is not None or pad_token_id == 0, \
                "padding embeddings needs the embedding table"
            if model_embeddings is not None:
                pad_embed = jnp.asarray(model_embeddings)[pad_token_id]
                pad_block = jnp.broadcast_to(
                    pad_embed, (inputs_embeds.shape[0], pad_len,
                                inputs_embeds.shape[2]))
            else:
                pad_block = jnp.zeros((inputs_embeds.shape[0], pad_len,
                                       inputs_embeds.shape[2]),
                                      inputs_embeds.dtype)
            inputs_embeds = jnp.concatenate(
                [jnp.asarray(inputs_embeds), pad_block], axis=1)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """Strip the padding added by pad_to_block_size (reference :149-163)."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]
