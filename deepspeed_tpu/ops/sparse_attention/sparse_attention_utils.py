"""Helpers for using block-sparse attention with real models.

Reference behavior: deepspeed/ops/sparse_attention/sparse_attention_utils.py:
13-225 (pad/unpad sequences to a block multiple, extend position
embeddings, and swap a model's self-attention for the sparse kernel).

The reference's swap mutates torch modules in place (:85-150). Models here
are (config -> module, params) pairs where the sparse and dense attention
share identical parameters (same QKV/out projections — only the attention
pattern differs), so the swap is functional: a new config carrying the
SparsityConfig plus untouched (or position-extended) params.
"""
from typing import Optional

import numpy as np


class SparseAttentionUtils:
    @staticmethod
    def extend_position_embedding(pos_embedding, max_position: int):
        """Tile an existing (P, E) position-embedding table to cover
        max_position rows (reference :25-59 extends HF models in place; here
        the array is returned for functional param surgery)."""
        import jax.numpy as jnp

        pos_embedding = jnp.asarray(pos_embedding)
        P, E = pos_embedding.shape
        assert max_position > P, \
            f"max_position {max_position} must exceed current {P}"
        reps = -(-max_position // P)
        return jnp.tile(pos_embedding, (reps, 1))[:max_position]

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position: int):
        """Reference :68-83 — point the tokenizer at the extended length."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            model, params, max_position: int, sparsity_config=None):
        """Functional analog of reference :85-121: return (new_model,
        new_params) where every encoder layer attends through the
        block-sparse kernel and position embeddings cover max_position.

        model: models/bert.BertForPreTraining (the fused-layer BERT this
        build ships); params: its param tree. Attention projections are
        reused verbatim — only the position table changes shape.
        """
        import dataclasses

        from deepspeed_tpu.models.bert import BertForPreTraining
        from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
            FixedSparsityConfig)

        if not isinstance(model, BertForPreTraining):
            raise TypeError(
                "replace_model_self_attention_with_sparse_self_attention "
                f"supports models/bert.BertForPreTraining, got {type(model)}"
                " — build other families with sparsity_config directly")
        if sparsity_config is None:
            sparsity_config = FixedSparsityConfig(
                num_heads=model.config.num_attention_heads)
        cfg = dataclasses.replace(
            model.config, sparsity_config=sparsity_config,
            max_position_embeddings=max_position,
            attention_probs_dropout_prob=0.0)
        new_model = BertForPreTraining(cfg)
        new_params = params
        if params is not None:
            pos = params["embeddings"]["position_embeddings"]
            if max_position > pos.shape[0]:
                import jax

                new_params = jax.tree_util.tree_map(lambda x: x, params)
                new_params["embeddings"] = dict(
                    params["embeddings"],
                    position_embeddings=SparseAttentionUtils
                    .extend_position_embedding(pos, max_position))
        return new_model, new_params

    @staticmethod
    def replace_self_attention_layer_with_sparse_self_attention_layer(
            layer_config, sparsity_config):
        """Reference :123-150, layer granularity: a DeepSpeedTransformerConfig
        whose attention core is the block-sparse kernel (same param names, so
        existing layer params load unchanged)."""
        import copy

        new_cfg = copy.copy(layer_config)
        new_cfg.sparsity_config = sparsity_config
        new_cfg.attn_dropout_ratio = 0.0
        return new_cfg

    @staticmethod
    def pad_to_block_size(block_size: int, input_ids=None, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id: int = 0,
                          model_embeddings=None):
        """Pad sequence dim (axis 1) up to a block multiple.

        Returns (pad_len, input_ids, attention_mask, token_type_ids,
        position_ids, inputs_embeds) — the reference's tuple layout
        (reference :61-147). Padded attention-mask entries are 0 so padding
        never attends/attended.
        """
        import jax.numpy as jnp

        ref = input_ids if input_ids is not None else inputs_embeds
        assert ref is not None, "need input_ids or inputs_embeds"
        seq_len = ref.shape[1]
        pad_len = (-seq_len) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad(x, value=0):
            if x is None:
                return None
            widths = [(0, 0)] * x.ndim
            widths[1] = (0, pad_len)
            return jnp.pad(jnp.asarray(x), widths, constant_values=value)

        input_ids = pad(input_ids, pad_token_id)
        attention_mask = pad(attention_mask, 0)
        token_type_ids = pad(token_type_ids, 0)
        if position_ids is not None:
            # continue positions monotonically so extended tables index fine
            import jax.numpy as jnp2

            extra = jnp2.arange(seq_len, seq_len + pad_len)
            extra = jnp2.broadcast_to(extra, position_ids.shape[:-1] +
                                      (pad_len,))
            position_ids = jnp2.concatenate(
                [jnp2.asarray(position_ids), extra], axis=1)
        if inputs_embeds is not None:
            assert model_embeddings is not None or pad_token_id == 0, \
                "padding embeddings needs the embedding table"
            if model_embeddings is not None:
                pad_embed = jnp.asarray(model_embeddings)[pad_token_id]
                pad_block = jnp.broadcast_to(
                    pad_embed, (inputs_embeds.shape[0], pad_len,
                                inputs_embeds.shape[2]))
            else:
                pad_block = jnp.zeros((inputs_embeds.shape[0], pad_len,
                                       inputs_embeds.shape[2]),
                                      inputs_embeds.dtype)
            inputs_embeds = jnp.concatenate(
                [jnp.asarray(inputs_embeds), pad_block], axis=1)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """Strip the padding added by pad_to_block_size (reference :149-163)."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]
