from deepspeed_tpu.moe.sharded_moe import (MoE, StackedExperts, moe_capacity,
                                           moe_leaf_spec, sum_moe_losses,
                                           top_k_gating)

__all__ = ["MoE", "StackedExperts", "moe_capacity", "moe_leaf_spec",
           "sum_moe_losses", "top_k_gating"]
