"""Mixture-of-Experts with expert parallelism — TPU-native.

The reference snapshot (v0.3.11) predates DeepSpeed-MoE (SURVEY §2.9: "EP:
no — no MoE in this snapshot"), so this subsystem is a forward-looking
extension in the spirit of the later ``deepspeed/moe/sharded_moe.py``,
designed TPU-first rather than ported:

- **Gating** (GShard top-2 / Switch top-1): dense one-hot dispatch and
  combine tensors built from cumulative-sum position assignment — no
  scatter, no dynamic shapes, everything lands on the MXU/VPU.
- **Expert parallelism**: expert weights are stacked ``(E, ...)`` arrays
  sharded over the 'data' mesh axis (ep_size == dp world size, the
  DeepSpeed-MoE default). The token exchange is NOT hand-written: the
  dispatched activations flip from token-sharded ``P('data', ...)`` to
  expert-sharded ``P('data' on E, ...)`` via a sharding constraint, and
  GSPMD inserts the all_to_all over ICI. Single-device meshes degrade to
  plain dense einsums.
- **Static capacity**: ``capacity = ceil(k * tokens * capacity_factor / E)``
  is a Python int, so the jitted program has fixed shapes; overflow tokens
  are dropped (their combine weight is zero) and ride the residual
  connection, exactly like Switch Transformer.

Load-balancing auxiliary loss follows Switch §2.2 / GShard §2.2(3):
``aux = E * sum_e( fraction_tokens_e * mean_router_prob_e )`` — equals 1.0
at perfect balance. Layers report it via flax ``sow('losses', ...)``; model
loss heads add ``aux_loss_coef * aux``.
"""
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import mesh as mesh_lib


def moe_capacity(tokens_per_group: int, num_experts: int, k: int,
                 capacity_factor: float, min_capacity: int = 4) -> int:
    """Static per-expert slot count for one token group."""
    cap = int(math.ceil(k * tokens_per_group * capacity_factor / num_experts))
    return max(min_capacity, min(cap, tokens_per_group * k))


def top_k_gating(logits, k: int = 2, capacity: Optional[int] = None,
                 capacity_factor: float = 1.25, min_capacity: int = 4,
                 normalize: bool = True):
    """Dense top-k gating.

    logits: (G, S, E) router scores (any float dtype; softmax runs fp32).
    Returns (combine, dispatch, aux_loss, metrics):
      combine:  (G, S, E, C) fp32 — weight of token (g,s) in expert e slot c
      dispatch: (G, S, E, C) bool — combine > 0
      aux_loss: scalar fp32 load-balance loss (≈1.0 when balanced)
      metrics:  dict of scalars (expert load entropy, dropped fraction)
    """
    G, S, E = logits.shape
    if capacity is None:
        capacity = moe_capacity(S, E, k, capacity_factor, min_capacity)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    masks, gates = [], []
    rem = probs
    for _ in range(k):
        idx = jnp.argmax(rem, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # (G, S, E)
        gates.append(jnp.sum(rem * m, axis=-1))        # (G, S)
        masks.append(m)
        rem = rem * (1.0 - m)

    # load-balance loss on first-choice routing (Switch §2.2): product of
    # per-expert token fraction and mean router probability
    mean_prob = jnp.mean(probs, axis=(0, 1))           # (E,)
    frac_tokens = jnp.mean(masks[0], axis=(0, 1))      # (E,)
    aux_loss = E * jnp.sum(mean_prob * frac_tokens)

    # normalize across the k chosen gates (GShard top-2). Never for k=1:
    # Switch scales by the RAW router prob — a normalized top-1 gate is the
    # constant 1 and the router would get no gradient through the output
    normalize = normalize and k > 1
    gate_sum = sum(gates)
    combine = jnp.zeros((G, S, E, capacity), jnp.float32)
    # slots an expert already handed out to higher-priority choices: the
    # 2nd-choice positions start after ALL 1st-choice assignments (GShard's
    # locations2 += sum(mask1))
    offset = jnp.zeros((G, 1, E), jnp.float32)
    kept_tokens = jnp.float32(0.0)
    for m, g in zip(masks, gates):
        loc = jnp.cumsum(m, axis=1) - m + offset       # (G, S, E)
        pos = jnp.sum(loc * m, axis=-1)                # (G, S) slot index
        chosen = jnp.sum(m, axis=-1)                   # (G, S) 0/1
        keep = (pos < capacity).astype(jnp.float32) * chosen
        kept_tokens = kept_tokens + jnp.sum(keep)
        gn = g / jnp.maximum(gate_sum, 1e-9) if normalize else g
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32)       # (G, S, C)
        combine = combine + (gn * keep)[..., None, None] \
            * m[..., None] * slot[:, :, None, :]
        offset = offset + jnp.sum(m, axis=1, keepdims=True)

    dispatch = combine > 0
    total = jnp.float32(G * S * k)
    load = frac_tokens + 1e-9
    metrics = {
        "moe_aux_loss": aux_loss,
        "moe_dropped_frac": 1.0 - kept_tokens / total,
        "moe_load_entropy": -jnp.sum(load * jnp.log(load)),
    }
    return combine, dispatch, aux_loss, metrics


class StackedExperts(nn.Module):
    """E parallel FFN experts as stacked weights — one batched einsum per
    projection so every expert's GEMM tiles onto the MXU together.

    Input/output: (E, N, M) with E sharded over the 'data' mesh axis
    (expert parallelism) and the hidden dim optionally sharded over
    'model' (tensor parallelism inside each expert, same layout rule as
    the dense MLP: models/gpt2.py gpt2_tp_leaf_spec)."""
    num_experts: int
    d_model: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        E, M, F = self.num_experts, self.d_model, self.d_ff
        w_in = self.param("w_in", nn.initializers.normal(0.02), (E, M, F),
                          jnp.float32)
        b_in = self.param("b_in", nn.initializers.zeros, (E, F), jnp.float32)
        w_out = self.param("w_out", nn.initializers.normal(0.02), (E, F, M),
                           jnp.float32)
        b_out = self.param("b_out", nn.initializers.zeros, (E, M), jnp.float32)
        h = jnp.einsum("enm,emf->enf", x, w_in.astype(self.dtype))
        h = h + b_in.astype(self.dtype)[:, None, :]
        h = mesh_lib.constrain(h, P(mesh_lib.DATA_AXIS, None,
                                    mesh_lib.MODEL_AXIS))
        h = nn.gelu(h, approximate=True)
        y = jnp.einsum("enf,efm->enm", h, w_out.astype(self.dtype))
        y = y + b_out.astype(self.dtype)[:, None, :]
        return mesh_lib.constrain(y, P(mesh_lib.DATA_AXIS, None, None))


class MoE(nn.Module):
    """Sparsely-gated MoE FFN block (drop-in for a dense MLP).

    x: (B, S, M) with B sharded over 'data'. Each batch row is a routing
    group (static capacity is per row). Returns (B, S, M); the caller adds
    the residual. The load-balance aux loss is sown into the 'losses'
    collection as 'moe_aux_loss' (already scaled by aux_loss_coef) — loss
    heads sum the collection into the objective.
    """
    num_experts: int
    d_ff: int
    k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 0.0   # 0 = same as capacity_factor;
                                        # eval typically uses a larger
                                        # factor so fewer tokens drop
    min_capacity: int = 4
    aux_loss_coef: float = 0.01
    router_z_loss_coef: float = 0.0   # ST-MoE router z-loss: penalizes
                                      # large router logits, stabilizing
                                      # bf16 gating at scale
    router_jitter: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        B, S, M = x.shape
        E = self.num_experts
        # router in fp32: tiny GEMM, and routing decisions are precision
        # sensitive (flipping an argmax moves a whole token)
        xr = x.astype(jnp.float32)
        if train and self.router_jitter > 0:
            xr = xr * jax.random.uniform(
                self.make_rng("dropout"), xr.shape, jnp.float32,
                1.0 - self.router_jitter, 1.0 + self.router_jitter)
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="router")(xr)
        cf = self.capacity_factor if train or not self.eval_capacity_factor \
            else self.eval_capacity_factor
        combine, dispatch, aux, _ = top_k_gating(
            logits, k=self.k, capacity_factor=cf,
            min_capacity=self.min_capacity)
        total_aux = jnp.float32(self.aux_loss_coef) * aux
        if self.router_z_loss_coef:
            z = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
            total_aux = total_aux \
                + jnp.float32(self.router_z_loss_coef) * jnp.mean(z * z)
        self.sow("losses", "moe_aux_loss", total_aux,
                 init_fn=lambda: jnp.float32(0.0),
                 reduce_fn=lambda a, b: a + b)

        # dispatch: token-sharded (B over 'data') -> expert-sharded (E over
        # 'data'); the constraint flip is where GSPMD inserts the all_to_all
        d = jnp.einsum("gsec,gsm->egcm", dispatch.astype(self.dtype), x)
        C = d.shape[2]
        d = mesh_lib.constrain(d, P(mesh_lib.DATA_AXIS, None, None, None))
        y = StackedExperts(E, M, self.d_ff, dtype=self.dtype,
                           name="experts")(d.reshape(E, B * C, M))
        y = y.reshape(E, B, C, M)
        # combine: expert-sharded -> token-sharded (the return all_to_all)
        out = jnp.einsum("egcm,gsec->gsm", y, combine.astype(self.dtype))
        return mesh_lib.constrain(out, P(mesh_lib.DATA_AXIS, None, None))


def moe_leaf_spec(joined: str, leaf):
    """Partition rule for MoE params (compose into a model's partition
    spec walker): expert-stacked weights shard E over 'data' (expert
    parallelism) and the FFN hidden dim over 'model' (TP inside the
    expert); the router is replicated (every token scores every expert).

    Returns None for non-MoE leaves so callers can fall through to their
    dense rules."""
    if "router" in joined:
        return P()
    if "experts" in joined:
        if "w_in" in joined:
            return P(mesh_lib.DATA_AXIS, None, mesh_lib.MODEL_AXIS)
        if "w_out" in joined:
            return P(mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS, None)
        if "b_in" in joined:
            return P(mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS)
        if "b_out" in joined:
            return P(mesh_lib.DATA_AXIS, None)
        return P(mesh_lib.DATA_AXIS)
    return None


def sum_moe_losses(loss_collection) -> jnp.ndarray:
    """Sum every sown 'moe_aux_loss' leaf in a mutable-collection dict."""
    total = jnp.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(loss_collection):
        total = total + jnp.sum(leaf)
    return total
