"""DeepSpeed-style MoE entry point.

Later DeepSpeed exposes ``deepspeed.moe.layer.MoE(hidden_size, expert,
num_experts, k, capacity_factor, ...)``; users coming from there find the
equivalent here. The TPU-native layer is flax (experts are stacked weight
tensors, not wrapped submodules), so ``hidden_size``/``expert`` map onto
the module fields instead of wrapping a torch module.
"""
from deepspeed_tpu.moe.sharded_moe import MoE as _MoE


def MoE(hidden_size: int, num_experts: int = 1, k: int = 1,
        capacity_factor: float = 1.0, eval_capacity_factor: float = 0.0,
        min_capacity: int = 4, expert_intermediate_size: int = 0,
        aux_loss_coef: float = 0.01, noisy_gate_policy: str = None, **kw):
    """Build the flax MoE layer with DeepSpeed-MoE argument names.

    noisy_gate_policy: None or 'Jitter' (maps to router_jitter=0.01;
    DeepSpeed's 'RSample' has no equivalent here).
    """
    if noisy_gate_policy not in (None, "Jitter"):
        # a ported DeepSpeed config expecting RSample noise must not get
        # silently-different gating
        raise ValueError(
            f"noisy_gate_policy={noisy_gate_policy!r} is not supported; "
            "use None or 'Jitter' (DeepSpeed's 'RSample' has no equivalent "
            "in this build)")
    jitter = 0.01 if noisy_gate_policy == "Jitter" else 0.0
    return _MoE(num_experts=num_experts,
                d_ff=expert_intermediate_size or 4 * hidden_size,
                k=k, capacity_factor=capacity_factor,
                eval_capacity_factor=eval_capacity_factor,
                min_capacity=min_capacity, aux_loss_coef=aux_loss_coef,
                router_jitter=jitter, **kw)
