"""`ds_report` — environment and op compatibility report.

Reference behavior: deepspeed/env_report.py:23-109 (op install/compat
table + framework versions). TPU version reports the jax stack, devices,
and which native/Pallas ops are active.
"""
GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
NO = f"{RED}[NO]{END}"


def op_report():
    lines = []
    lines.append("-" * 74)
    lines.append("op name " + "." * 40 + " compatible")
    lines.append("-" * 74)
    from deepspeed_tpu.ops.op_builder import ALL_OPS

    for name, builder_cls in ALL_OPS.items():
        builder = builder_cls()
        status = OKAY if builder.is_compatible() else NO
        lines.append(f"{name} {'.' * (48 - len(name))} {status}")
    # kernel paths
    try:
        import jax

        on_tpu = any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        on_tpu = False
    pallas = OKAY if on_tpu else \
        f"{YELLOW}[interpret-mode (no TPU visible)]{END}"
    lines.append(f"pallas_flash_attention {'.' * 26} {pallas}")
    lines.append("-" * 74)
    return "\n".join(lines)


def version_report():
    import jax

    import deepspeed_tpu

    lines = []
    lines.append("DeepSpeed-TPU general environment info:")
    try:
        import jaxlib

        lines.append(f"jax version ................... {jax.__version__}")
        lines.append(f"jaxlib version ................ {jaxlib.__version__}")
    except ImportError:  # pragma: no cover
        pass
    try:
        import flax

        lines.append(f"flax version .................. {flax.__version__}")
    except ImportError:
        pass
    lines.append(f"deepspeed_tpu version ......... {deepspeed_tpu.__version__}")
    lines.append(
        f"reference API version ......... "
        f"{deepspeed_tpu.__reference_version__}")
    try:
        devices = jax.devices()
        plats = {}
        for d in devices:
            plats[d.platform] = plats.get(d.platform, 0) + 1
        desc = ", ".join(f"{n}x {p}" for p, n in plats.items())
        lines.append(f"devices ....................... {desc}")
    except Exception as e:  # pragma: no cover
        lines.append(f"devices ....................... unavailable ({e})")
    return "\n".join(lines)


def main(args=None):
    print(op_report())
    print(version_report())
    return 0


cli_main = main

if __name__ == "__main__":
    main()
