"""Ulysses-style sequence parallelism — all-to-all head/sequence resharding.

The second sequence-parallel scheme (complementing ring attention,
parallel/ring_attention.py): activations flow through the network sharded
over the SEQUENCE dim, and for the attention op an all_to_all over the
sequence axis re-shards them over the HEAD dim instead — each device then
holds H/N heads with the FULL sequence, so any full-sequence attention
kernel (the Pallas flash kernel, block-sparse, or plain jnp) runs unchanged
per shard. A second all_to_all restores sequence sharding afterwards.
Communication is 2 all_to_alls of the QKV/O tensors per attention call —
O(B*S*E/N) per device, riding ICI.

This is the DeepSpeed-Ulysses scheme (announced for the successor of the
reference snapshot; the snapshot itself has NO sequence parallelism —
SURVEY §2.9) built the TPU way: the resharding is expressed as sharding
constraints and GSPMD emits the all_to_alls — no hand-written collective,
and a single-device mesh degrades to a no-op.

Requires num_heads % axis_size == 0 (classic Ulysses constraint; use ring
attention when heads don't divide).
"""
from typing import Callable, Optional

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import mesh as mesh_lib


def ulysses_attention(q, k, v, *, axis_name: str, mesh=None,
                      attention_fn: Optional[Callable] = None, **attn_kw):
    """Sequence-parallel attention over (B, H, S, D) tensors whose S dim is
    sharded over `axis_name` (GSPMD view: pass GLOBAL arrays under jit).

    attention_fn(q, k, v, **attn_kw) -> (B, H, S, D); defaults to
    ops.transformer.functional.scaled_dot_product_attention (which
    dispatches to the Pallas flash kernel on TPU — full-seq kernels work
    because each shard sees the whole sequence after the reshard).

    mesh: pass explicitly to bind the constraints anywhere; omit to use
    the ambient engine mesh (model code inside an engine step).
    """
    if attention_fn is None:
        from deepspeed_tpu.ops.transformer.functional import (
            scaled_dot_product_attention)

        attention_fn = scaled_dot_product_attention

    seq_spec = P(None, None, axis_name, None)
    head_spec = P(None, axis_name, None, None)
    if mesh is not None:
        from jax.sharding import NamedSharding

        def constrain(x, spec):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec))
    else:
        constrain = mesh_lib.constrain
    # seq-sharded -> head-sharded: GSPMD inserts the first all_to_all
    q = constrain(q, head_spec)
    k = constrain(k, head_spec)
    v = constrain(v, head_spec)
    out = attention_fn(q, k, v, **attn_kw)
    # head-sharded -> seq-sharded: the return all_to_all
    return constrain(out, seq_spec)


def make_ulysses_attention(mesh, axis_name: str, causal: bool = True,
                           scale: Optional[float] = None,
                           attention_fn: Optional[Callable] = None):
    """Jit-wrapped Ulysses attention over full (B, H, S, D) arrays with the
    sequence dim sharded over `axis_name` — API twin of
    make_ring_attention. num_heads must be divisible by the axis size."""

    def fn(q, k, v):
        assert q.shape[1] % mesh.shape[axis_name] == 0, (
            f"ulysses needs heads ({q.shape[1]}) divisible by axis "
            f"'{axis_name}' size ({mesh.shape[axis_name]}); use ring "
            f"attention otherwise")
        return ulysses_attention(q, k, v, axis_name=axis_name, mesh=mesh,
                                 attention_fn=attention_fn,
                                 causal=causal, scale=scale)

    return fn
