"""Ring attention — sequence parallelism over the ICI ring.

The reference has NO sequence-dimension parallelism (SURVEY §2.9: long
sequences are handled only by block-sparse attention compute sparsity). On
TPU, sequence parallelism is first-class: activations are sharded over the
sequence dimension across a named mesh axis, and attention runs blockwise
while K/V shards rotate around the ring via `lax.ppermute` — each hop
overlaps with the matmuls of the current block (XLA's latency-hiding
scheduler), so the attention memory per chip is O(S/N) with no materialized
S x S matrix. Algorithm: blockwise online softmax (the flash-attention
recurrence) with cross-device blocks — Liu et al. 2023 "Ring Attention with
Blockwise Transformers" (PAPERS.md).

Differentiable: the ppermute rotations are linear, jax.grad produces the
reverse-ring backward automatically.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name, causal, scale,
                          vary_axes=()):
    """Per-device body: q,k,v are (B, H, S_local, D) shards, sequence
    sharded over `axis_name`. Must run inside shard_map with the axis bound.

    vary_axes: additional manual axes of the enclosing shard_map (e.g.
    'data'/'model' when batch/heads are also mapped) — the loop-carry
    accumulators must declare themselves device-varying over those axes
    too, or the fori_loop carry types mismatch after the first round.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = (D ** -0.5) if scale is None else scale
    q32 = q.astype(jnp.float32) * scale

    q_pos = idx * S + jnp.arange(S)                      # global query positions

    def round_body(r, carry):
        m, l, acc, k_blk, v_blk = carry
        # the block we hold at round r originated from rank (idx - r) mod n
        src = (idx - r) % n
        k_pos = src * S + jnp.arange(S)

        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]      # (S, S) block mask
            s = jnp.where(mask[None, None], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))

        # rotate K/V shards one hop around the ring
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return m_new, l_new, acc_new, k_blk, v_blk

    # pvary: the accumulators become device-varying over the ring axis after
    # the first round; the loop carry type must declare that up front
    axes = (axis_name,) + tuple(vary_axes)
    m0 = lax.pcast(jnp.full((B, H, S, 1), NEG_INF, jnp.float32), axes, to='varying')
    l0 = lax.pcast(jnp.zeros((B, H, S, 1), jnp.float32), axes, to='varying')
    acc0 = lax.pcast(jnp.zeros((B, H, S, D), jnp.float32), axes, to='varying')
    m, l, acc, _, _ = lax.fori_loop(0, n, round_body, (m0, l0, acc0, k, v))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe).astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Sequence-parallel attention. Call inside shard_map/jit where
    `axis_name` is a manual mesh axis and q/k/v are the device-local
    (B, H, S/N, D) shards of sequence-sharded tensors."""
    return _ring_attention_local(q, k, v, axis_name, causal, scale)


def make_ring_attention(mesh, axis_name: str, causal: bool = True,
                        scale: Optional[float] = None):
    """shard_map-wrapped ring attention over full (B, H, S, D) arrays with
    the sequence dim sharded over `axis_name` — drop-in replacement for
    dense attention inside a jitted step (a shard_map island; everything
    around it stays GSPMD-auto)."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = functools.partial(_ring_attention_local, axis_name=axis_name,
                           causal=causal, scale=scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis_name})
