"""Device-mesh construction: the TPU replacement for NCCL process groups.

The reference builds torch.distributed process groups per parallel axis
(reference: deepspeed/runtime/pipe/topology.py:252-364, engine.py:69-85).  On
TPU the equivalent is ONE named-axis ``jax.sharding.Mesh`` over all chips:
collectives become sharding annotations (GSPMD) or explicit ``psum`` /
``ppermute`` over a named axis inside ``shard_map``.

Axis order is ('pipe', 'data', 'model') — model innermost so tensor-parallel
collectives ride the fastest ICI links, matching the reference's
PipeModelDataParallelTopology axis nesting (topology.py:246, model innermost).
"""
from typing import Optional

import numpy as np

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
# seq sits between data and model: sequence-parallel all_to_alls ride
# faster links than data-parallel gradient reductions, TP innermost still
AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)


def resolve_mesh_shape(mesh_shape: dict, n_devices: int,
                       allow_partial: bool = False):
    """Fill in -1 axes; validate product == n_devices.

    A fully-specified mesh that uses only a subset of the devices is an
    error unless ``allow_partial`` — a config typo (stale axis sizes after
    scaling down) must fail at validation, not silently train on fewer
    chips. Tests/partial-pod runs opt in via ``mesh["allow_partial"]`` or
    an explicit devices list to build_mesh.
    """
    shape = {PIPE_AXIS: mesh_shape.get(PIPE_AXIS, 1),
             DATA_AXIS: mesh_shape.get(DATA_AXIS, -1),
             SEQ_AXIS: mesh_shape.get(SEQ_AXIS, 1),
             MODEL_AXIS: mesh_shape.get(MODEL_AXIS, 1)}
    fixed = 1
    free_axes = [a for a, s in shape.items() if s == -1]
    for a, s in shape.items():
        if s != -1:
            fixed *= s
    assert len(free_axes) <= 1, f"at most one mesh axis may be -1, got {shape}"
    if free_axes:
        assert n_devices % fixed == 0, \
            f"{n_devices} devices not divisible by fixed axes product {fixed}"
        shape[free_axes[0]] = n_devices // fixed
    total = shape[PIPE_AXIS] * shape[DATA_AXIS] * shape[SEQ_AXIS] \
        * shape[MODEL_AXIS]
    if allow_partial:
        assert total <= n_devices, \
            f"mesh {shape} needs {total} devices but {n_devices} available"
    else:
        assert total == n_devices, (
            f"mesh {shape} covers {total} of {n_devices} devices; set "
            f'mesh["allow_partial"] = true (or pass an explicit devices '
            f"list) to intentionally train on a subset")
    return shape


def build_mesh(mesh_shape: Optional[dict] = None, devices=None):
    """Build a Mesh with axes ('pipe','data','model').

    mesh_shape: {"pipe": P, "data": D, "model": M}; -1 = fill remaining.
    An explicit devices list always permits a subset mesh (the caller
    already chose the devices); otherwise subset meshes require
    mesh_shape["allow_partial"].
    """
    import jax
    from jax.sharding import Mesh

    mesh_shape = dict(mesh_shape or {})
    allow_partial = bool(mesh_shape.pop("allow_partial", False))
    if devices is None:
        devices = jax.devices()
    else:
        allow_partial = True
    shape = resolve_mesh_shape(mesh_shape, len(devices), allow_partial)
    total = shape[PIPE_AXIS] * shape[DATA_AXIS] * shape[SEQ_AXIS] \
        * shape[MODEL_AXIS]
    if total < len(devices):
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            f"mesh {shape} uses {total} of {len(devices)} devices — "
            f"{len(devices) - total} idle (intended for tests/partial "
            f"slices; check the config's mesh axes if not)")
    dev_array = np.asarray(devices[:total]).reshape(
        shape[PIPE_AXIS], shape[DATA_AXIS], shape[SEQ_AXIS],
        shape[MODEL_AXIS])
    return Mesh(dev_array, AXIS_ORDER)


def constrain(x, spec):
    """with_sharding_constraint that no-ops when no mesh is active or the
    referenced axes are absent/trivial — lets model code carry sharding
    annotations that only bind inside an engine's mesh context. Inside
    shard_map, axes the map handles manually (e.g. 'data' in the 1-bit Adam
    wire step) are dropped: the data is already device-local there, and
    with_sharding_constraint rejects specs naming manual axes."""
    import jax

    from jax.sharding import PartitionSpec as P

    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    # with_sharding_constraint accepts only Auto axes: under shard_map the
    # mapped axes are Manual and the rest become Explicit, so both must be
    # dropped here (checked up front — genuine spec errors like rank
    # mismatch still surface from with_sharding_constraint itself)
    auto = getattr(mesh, "auto_axes", None)
    if auto is None:  # pragma: no cover - older jax
        manual = set(getattr(mesh, "manual_axes", ()) or ())
        auto = tuple(a for a in mesh.shape if a not in manual)
    # old jax's abstract mesh knows nothing about the legacy shard_map
    # wrapping this trace — its manual axes are tracked by the compat shim
    # and must be dropped too (empty set on new jax)
    from deepspeed_tpu.utils.jax_compat import current_manual_axes

    compat_manual = current_manual_axes()

    def keep(axis):
        if axis is None:
            return None
        axes = axis if isinstance(axis, tuple) else (axis,)
        kept = tuple(a for a in axes
                     if a in mesh.shape and a in auto
                     and a not in compat_manual)
        if not kept:
            return None
        return kept if len(kept) > 1 else kept[0]

    cleaned = P(*(keep(a) for a in spec))
    if all(a is None for a in cleaned):
        return x
    return jax.lax.with_sharding_constraint(x, cleaned)


def data_sharding(mesh, *, extra_dims: int = 1):
    """NamedSharding for a batch: dim0 over 'data', rest replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (extra_dims - 1))))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def dp_size(mesh) -> int:
    return mesh.shape[DATA_AXIS]


def mp_size(mesh) -> int:
    return mesh.shape[MODEL_AXIS]


def pp_size(mesh) -> int:
    return mesh.shape[PIPE_AXIS]


def sp_size(mesh) -> int:
    return mesh.shape.get(SEQ_AXIS, 1)


def zero_merge_spec(spec, leaf, dp: int):
    """Merge ZeRO 'data'-axis sharding into an existing (TP) PartitionSpec.

    The reference flattens params and slices 1/N per rank
    (stage1.py:426, stage2.py:223-295).  The TPU-native formulation keeps
    leaves in natural shape and shards the largest dimension not already
    taken by TP that divides the data-parallel size; XLA then
    reduce-scatters grads into the shard and all-gathers updated params —
    same memory footprint, no bucket machinery.  Leaves too small to shard
    stay replicated (the reference's unpartitioned remainder).
    """
    from jax.sharding import PartitionSpec as P

    if dp == 1 or not hasattr(leaf, "shape") or leaf.ndim == 0:
        return spec
    used = set(a for a in spec if a is not None) if spec else set()
    if DATA_AXIS in used:
        return spec
    entries = list(spec) + [None] * (leaf.ndim - len(spec))
    best_dim, best = None, 0
    for d in range(leaf.ndim):
        if entries[d] is None and leaf.shape[d] % dp == 0 and leaf.shape[d] > best:
            best_dim, best = d, leaf.shape[d]
    if best_dim is None:
        return spec
    entries[best_dim] = DATA_AXIS
    return P(*entries)


def zero_partition_spec(pytree, mesh, stage: int, tp_specs=None):
    """Sharding specs implementing ZeRO state partitioning over the data
    axis, layered on top of optional tensor-parallel specs."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = dp_size(mesh)

    if tp_specs is None:
        tp_specs = jax.tree_util.tree_map(lambda _: P(), pytree)

    def spec_for(spec, leaf):
        if stage == 0:
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, zero_merge_spec(spec, leaf, dp))

    return jax.tree_util.tree_map(
        spec_for, tp_specs, pytree, is_leaf=lambda x: isinstance(x, P))
