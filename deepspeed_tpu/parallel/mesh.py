"""Device-mesh construction: the TPU replacement for NCCL process groups.

The reference builds torch.distributed process groups per parallel axis
(reference: deepspeed/runtime/pipe/topology.py:252-364, engine.py:69-85).  On
TPU the equivalent is ONE named-axis ``jax.sharding.Mesh`` over all chips:
collectives become sharding annotations (GSPMD) or explicit ``psum`` /
``ppermute`` over a named axis inside ``shard_map``.

Axis order is ('pipe', 'data', 'model') — model innermost so tensor-parallel
collectives ride the fastest ICI links, matching the reference's
PipeModelDataParallelTopology axis nesting (topology.py:246, model innermost).
"""
from typing import Optional

import numpy as np

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
MODEL_AXIS = "model"
AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, MODEL_AXIS)


def resolve_mesh_shape(mesh_shape: dict, n_devices: int):
    """Fill in -1 axes; validate product == n_devices."""
    shape = {PIPE_AXIS: mesh_shape.get(PIPE_AXIS, 1),
             DATA_AXIS: mesh_shape.get(DATA_AXIS, -1),
             MODEL_AXIS: mesh_shape.get(MODEL_AXIS, 1)}
    fixed = 1
    free_axes = [a for a, s in shape.items() if s == -1]
    for a, s in shape.items():
        if s != -1:
            fixed *= s
    assert len(free_axes) <= 1, f"at most one mesh axis may be -1, got {shape}"
    if free_axes:
        assert n_devices % fixed == 0, \
            f"{n_devices} devices not divisible by fixed axes product {fixed}"
        shape[free_axes[0]] = n_devices // fixed
    total = shape[PIPE_AXIS] * shape[DATA_AXIS] * shape[MODEL_AXIS]
    assert total == n_devices, \
        f"mesh {shape} needs {total} devices but {n_devices} available"
    return shape


def build_mesh(mesh_shape: Optional[dict] = None, devices=None):
    """Build a Mesh with axes ('pipe','data','model').

    mesh_shape: {"pipe": P, "data": D, "model": M}; -1 = fill remaining.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    shape = resolve_mesh_shape(mesh_shape or {}, len(devices))
    dev_array = np.asarray(devices).reshape(
        shape[PIPE_AXIS], shape[DATA_AXIS], shape[MODEL_AXIS])
    return Mesh(dev_array, AXIS_ORDER)


def data_sharding(mesh, *, extra_dims: int = 1):
    """NamedSharding for a batch: dim0 over 'data', rest replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (extra_dims - 1))))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def dp_size(mesh) -> int:
    return mesh.shape[DATA_AXIS]


def mp_size(mesh) -> int:
    return mesh.shape[MODEL_AXIS]


def pp_size(mesh) -> int:
    return mesh.shape[PIPE_AXIS]


def zero_partition_spec(pytree, mesh, stage: int):
    """Sharding specs implementing ZeRO state partitioning over the data axis.

    The reference flattens params and slices 1/N per rank
    (stage1.py:426, stage2.py:223-295).  The TPU-native formulation keeps leaves
    in natural shape and shards the largest dimension divisible by the
    data-parallel size; XLA then reduce-scatters grads into the shard and
    all-gathers updated params — same memory footprint, no bucket machinery.
    Leaves too small to shard stay replicated (same as reference's final
    unpartitioned remainder).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = dp_size(mesh)

    def spec_for(leaf):
        if stage == 0 or dp == 1 or not hasattr(leaf, "shape") or leaf.ndim == 0:
            return NamedSharding(mesh, P())
        # choose the largest dim divisible by dp
        best_dim, best_size = None, 0
        for d, s in enumerate(leaf.shape):
            if s % dp == 0 and s > best_size:
                best_dim, best_size = d, s
        if best_dim is None:
            return NamedSharding(mesh, P())
        spec = [None] * leaf.ndim
        spec[best_dim] = DATA_AXIS
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map(spec_for, pytree)
