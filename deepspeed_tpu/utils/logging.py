"""Central logging for deepspeed_tpu.

Mirrors the reference logger surface (reference: deepspeed/utils/logging.py:1-60):
a module-level ``logger`` plus ``log_dist(message, ranks)`` that only emits on the
listed process ranks (-1 = all).  On TPU the "rank" is the JAX process index.
"""
import logging
import os
import sys

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


def _create_logger(name: str = "deepspeed_tpu", level=logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    if getattr(lg, "_ds_tpu_configured", False):
        return lg
    lg.setLevel(level)
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setFormatter(logging.Formatter(LOG_FORMAT))
    lg.addHandler(handler)
    lg._ds_tpu_configured = True
    return lg


logger = _create_logger()


def _process_index() -> int:
    # Avoid importing jax at module import time for cheap CLI paths.
    if "jax" in sys.modules:
        import jax

        try:
            return jax.process_index()
        except RuntimeError:
            return 0
    return int(os.environ.get("JAX_PROCESS_INDEX", os.environ.get("RANK", "0")))


def log_dist(message: str, ranks=None, level=logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (None/[-1] => all)."""
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")
