"""Wall-clock and throughput timers.

TPU-native analog of the reference timers (reference: deepspeed/utils/timer.py:19-170).
Where the reference calls ``torch.cuda.synchronize()`` before reading the clock, we
block on outstanding device work via a tiny ``jax.block_until_ready`` barrier token —
XLA dispatch is async on TPU exactly like CUDA streams.
"""
import time

from deepspeed_tpu.utils.logging import log_dist


def _device_sync():
    import jax
    import jax.numpy as jnp

    try:
        jnp.zeros(()).block_until_ready()
    except RuntimeError:  # device not initialised yet; wall clock only
        pass


class SynchronizedWallClockTimer:
    """Named timers that synchronize the accelerator before reading the clock."""

    class Timer:
        def __init__(self, name: str):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = 0.0

        def start(self):
            assert not self.started_, f"timer {self.name_} already started"
            _device_sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False):
            assert self.started_, f"timer {self.name_} not started"
            _device_sync()
            if reset:
                self.elapsed_ = time.time() - self.start_time
            else:
                self.elapsed_ += time.time() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started = self.started_
            if started:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed_

    def __init__(self):
        self.timers = {}

    def __call__(self, name: str) -> "SynchronizedWallClockTimer.Timer":
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage() -> str:
        # thin delegate over runtime/memory_accounting.py — THE one
        # normalizer for the per-backend memory_stats() variants
        from deepspeed_tpu.runtime.memory_accounting import \
            device_memory_report

        lines = []
        for entry in device_memory_report():
            if entry["bytes_in_use"] is None:
                continue
            used = entry["bytes_in_use"] / (1024**3)
            peak = (entry["peak_bytes_in_use"] or 0) / (1024**3)
            lines.append(f"{entry['kind']}:{entry['id']}: "
                         f"in_use {used:.2f} GB | peak {peak:.2f} GB")
        return " | ".join(lines)

    def log(self, names, normalizer=1.0, reset=True, ranks=None, memory_breakdown=False):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])


class ThroughputTimer:
    """Samples/sec tracking with warm-up steps skipped (reference: utils/timer.py:97-170)."""

    def __init__(self, batch_size, num_workers=1, start_step=2, steps_per_output=50,
                 monitor_memory=False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.local_step_count = 0
        self.total_step_count = 0
        self.total_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.local_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.total_step_count >= self.start_step:
            _device_sync()
            self.start_time = time.time()

    def stop(self, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.total_step_count += 1
        self.local_step_count += 1
        if self.total_step_count > self.start_step:
            _device_sync()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            if report_speed and self.local_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.local_step_count}/"
                    f"global_step={self.total_step_count}, "
                    f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.6f}, "
                    f"CurrSamplesPerSec={self.batch_size * self.num_workers / duration:.6f}")

    def avg_samples_per_sec(self):
        if self.total_elapsed_time > 0 and self.total_step_count > self.start_step:
            samples = self.batch_size * self.num_workers * (self.total_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("-1")
