"""Forward-compat shims for older jax releases.

The codebase targets the current jax API (``jax.set_mesh``,
``jax.shard_map``, ``jax.sharding.get_abstract_mesh``,
``pallas.tpu.CompilerParams``).  On older installs (<= 0.4.x) those names
are missing but equivalents exist; ``ensure_compat()`` installs aliases so
one source tree runs on both.  Idempotent and cheap after the first call.
"""
_installed = False

# manual axis names of legacy shard_maps currently being traced: old jax's
# abstract mesh has no record of them, so with_sharding_constraint callers
# (parallel/mesh.py constrain) cannot otherwise know which axes to drop.
# A stack because shard_maps can nest (e.g. via scan re-tracing).
_MANUAL_AXES_STACK = []


def current_manual_axes():
    """Axis names manual in the innermost legacy shard_map being traced
    (empty set on new jax, where the real shard_map reports them via the
    abstract mesh)."""
    out = set()
    for names in _MANUAL_AXES_STACK:
        out |= names
    return out


def ensure_compat():
    global _installed
    if _installed:
        return
    _installed = True
    import jax

    if not hasattr(jax, "set_mesh"):
        # ``with jax.set_mesh(m):`` == the classic ``with m:`` resource-env
        # context on old jax; Mesh has always been a context manager
        jax.set_mesh = lambda mesh: mesh

    if not hasattr(jax.sharding, "get_abstract_mesh"):
        def _get_abstract_mesh():
            from jax._src.mesh import thread_resources

            physical = thread_resources.env.physical_mesh
            return physical.abstract_mesh
        jax.sharding.get_abstract_mesh = _get_abstract_mesh

    if not hasattr(jax.lax, "axis_size"):
        # psum of a concrete python scalar over a named axis is
        # constant-folded to the axis size on old jax — no collective runs
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

    if not hasattr(jax.lax, "pcast"):
        # varying-manifest casts predate old jax's shard_map; with
        # replication checking off (check_rep=False below) the cast is a
        # type-system no-op
        jax.lax.pcast = lambda x, axes, to=None: x

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy_sm

        def shard_map(f, *, mesh=None, in_specs, out_specs, axis_names=None,
                      check_rep=False, **_kw):
            def call(*args):
                m = mesh
                if m is None:
                    from jax._src.mesh import thread_resources

                    m = thread_resources.env.physical_mesh
                    assert m is not None and not m.empty, \
                        "jax.shard_map without mesh= needs an active mesh " \
                        "context (with jax.set_mesh(...))"
                auto = frozenset()
                if axis_names is not None:
                    auto = frozenset(a for a in m.axis_names
                                     if a not in axis_names)
                manual = set(m.axis_names) - set(auto)
                _MANUAL_AXES_STACK.append(manual)
                try:
                    # the body traces inside this call, so constrain() sees
                    # the manual axes via current_manual_axes()
                    return _legacy_sm(f, mesh=m, in_specs=in_specs,
                                      out_specs=out_specs,
                                      check_rep=check_rep,
                                      auto=auto)(*args)
                finally:
                    _MANUAL_AXES_STACK.pop()
            return call
        jax.shard_map = shard_map

    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams") and \
                hasattr(pltpu, "TPUCompilerParams"):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:  # pragma: no cover - pallas not built for platform
        pass
