"""Minimal TensorBoard event-file writer (no tensorboard/tensorflow dep).

The reference logs scalars through tensorboardX (reference
engine.py:157-158, 888-899, 1039-1091). This writes the same on-disk
format natively: a TFRecord stream of protobuf ``Event`` messages with
masked-CRC32C framing, readable by stock TensorBoard.

Wire format (both fixed, stable since TF 1.x):
  record  = uint64 len (LE) | masked_crc32c(len) | data | masked_crc32c(data)
  Event   = { double wall_time = 1; int64 step = 2;
              string file_version = 3; Summary summary = 5; }
  Summary = { repeated Value value = 1 }  with
  Value   = { string tag = 1; float simple_value = 2; }
"""
import os
import socket
import struct
import time

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven; TFRecord uses the masked variant
# ---------------------------------------------------------------------------
_CRC_TABLE = []


def _build_table():
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# tiny protobuf encoder (just the fields Event/Summary need)
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _key(field, 2) + _varint(len(v)) + v


def _summary_value(tag: str, value: float) -> bytes:
    return _pb_bytes(1, _pb_bytes(1, tag.encode()) + _pb_float(2, float(value)))


def _event(step: int, summary: bytes = b"", file_version: str = None) -> bytes:
    msg = _pb_double(1, time.time())
    if file_version is not None:
        msg += _pb_bytes(3, file_version.encode())
    else:
        msg += _pb_int64(2, int(step))
        msg += _pb_bytes(5, summary)
    return msg


class SummaryWriter:
    """tensorboardX-shaped scalar writer producing real TB event files."""

    def __init__(self, log_dir: str, job_name: str = None):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}"
                 + (f".{job_name}" if job_name else ""))
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._write_record(_event(0, file_version="brain.Event:2"))

    def _write_record(self, data: bytes):
        hdr = struct.pack("<Q", len(data))
        self._f.write(hdr)
        self._f.write(struct.pack("<I", _masked_crc(hdr)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))

    def add_scalar(self, tag: str, value: float, global_step: int = 0):
        self._write_record(_event(global_step, _summary_value(tag, value)))

    def add_scalars(self, scalars: dict, global_step: int = 0):
        summary = b"".join(_summary_value(t, v) for t, v in scalars.items())
        self._write_record(_event(global_step, summary))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()
