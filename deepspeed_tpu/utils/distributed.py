"""Multi-host initialization — the NCCL-rendezvous replacement.

Reference: deepspeed/utils/distributed.py:12-108 (env-var rendezvous +
mpi4py auto-discovery).  TPU-native: ``jax.distributed.initialize`` with a
coordinator address; per-host ONE process owns all local chips (no
CUDA_VISIBLE_DEVICES analog).  Env contract kept as close as possible:

  RANK / WORLD_SIZE            -> process index / process count
  MASTER_ADDR / MASTER_PORT    -> coordinator address
"""
import os

from deepspeed_tpu.utils.logging import logger

_initialized = False


def init_distributed(dist_backend=None, auto_mpi_discovery=True,
                     distributed_port=29500, verbose=True):
    """Join the multi-host world if env vars are present; no-op otherwise.

    dist_backend accepted for API parity (the backend is always XLA
    collectives over ICI/DCN on TPU).
    """
    global _initialized
    if _initialized:
        return
    ensure_platform()
    import jax

    required = ["MASTER_ADDR", "RANK", "WORLD_SIZE"]
    if all(v in os.environ for v in required):
        coordinator = f"{os.environ['MASTER_ADDR']}:" \
                      f"{os.environ.get('MASTER_PORT', distributed_port)}"
        rank = int(os.environ["RANK"])
        world = int(os.environ["WORLD_SIZE"])
        if world > 1:
            if verbose:
                logger.info(
                    f"Initializing jax.distributed: coordinator={coordinator} "
                    f"process={rank}/{world}")
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=world, process_id=rank)
    elif auto_mpi_discovery and in_mpi_environment():
        rank, world, addr = mpi_discovery()
        if world > 1:
            coordinator = f"{addr}:{distributed_port}"
            if verbose:
                logger.info(f"MPI discovery: coordinator={coordinator} "
                            f"process={rank}/{world}")
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=world, process_id=rank)
    else:
        if verbose:
            logger.info("Single-process run; skipping jax.distributed init")
    _initialized = True


def ensure_platform():
    """Make JAX_PLATFORMS authoritative.  Installed TPU plugins (e.g. the
    axon tunnel) prepend themselves to jax_platforms even when the user
    exported JAX_PLATFORMS=cpu; re-assert the env choice via jax.config
    before the backend initializes (no-op afterwards)."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
        flags = os.environ.get("XLA_FLAGS", "")
        key = "xla_force_host_platform_device_count="
        if want == "cpu" and key in flags:
            n = int(flags.split(key)[1].split()[0])
            jax.config.update("jax_num_cpu_devices", n)
    except Exception as e:  # backend already initialized with another platform
        logger.warning(f"could not apply JAX_PLATFORMS={want}: {e}")


def in_mpi_environment() -> bool:
    return any(v in os.environ for v in
               ["OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID"])


def mpi_discovery():
    """Discover (rank, world, master_addr) from MPI/SLURM env (reference
    mpi_discovery, distributed.py:54-96, without requiring mpi4py)."""
    if "OMPI_COMM_WORLD_RANK" in os.environ:
        rank = int(os.environ["OMPI_COMM_WORLD_RANK"])
        world = int(os.environ["OMPI_COMM_WORLD_SIZE"])
    elif "PMI_RANK" in os.environ:
        rank = int(os.environ["PMI_RANK"])
        world = int(os.environ["PMI_SIZE"])
    else:
        rank = int(os.environ["SLURM_PROCID"])
        world = int(os.environ["SLURM_NTASKS"])
    addr = os.environ.get("MASTER_ADDR")
    if addr is None:
        try:
            from mpi4py import MPI

            comm = MPI.COMM_WORLD
            import socket

            addr = comm.bcast(socket.gethostbyname(socket.gethostname()), root=0)
        except ImportError:
            addr = "127.0.0.1"
    os.environ.setdefault("RANK", str(rank))
    os.environ.setdefault("WORLD_SIZE", str(world))
    os.environ.setdefault("MASTER_ADDR", addr)
    return rank, world, addr


def get_rank() -> int:
    import jax

    return jax.process_index()


def get_world_size() -> int:
    import jax

    return jax.process_count()
