"""Host-side span tracer: preallocated ring buffer, Chrome-trace export.

The hot path is numpy/stdlib only (the graftlint host-sync bar): one
clock read at ``begin()``, one clock read plus a handful of scalar array
writes at ``complete()``/``instant()``.  Nothing here ever touches a
device, forces a transfer, or allocates per event — the event payload is
five preallocated numpy columns (timestamp, duration, interned name id,
lane id, two integer args) written at a wrapping ring index under a
lock (the async checkpoint-commit thread and the training thread share
one tracer).

Disarmed is exactly free: engines hold ``self._tracer = None`` and every
instrumentation site is a single attribute-load-and-``is None`` branch —
no null-object dispatch, no clock reads, no recording, and (since
tracing is purely host-side) bit-identical device programs either way.

Export is the Chrome trace-event JSON format (``chrome://tracing`` /
Perfetto ``ui.perfetto.dev``): one process, one thread ("lane") per
logical actor — the training engine emits on ``train``/``ckpt`` lanes,
the PipelineEngine interpreter on one ``stage<N>`` lane per physical
stage (so an exported trace *renders* the 1F1B/interleaved/ZB schedule),
the serving engine on ``serve``.  Spans export as complete ``"X"``
events by default or as matched ``"B"``/``"E"`` pairs
(``complete_events=False``); instants as ``"i"``.

``lane_utilization(events)`` computes measured per-lane busy/idle
fractions from an event list — the wall-clock side of the
measured-vs-analytic bubble cross-check
(``runtime/pipe/bubble_accounting.replay_trace`` is the cost-model
side).
"""
import json
import os
import threading
import time

import numpy as np

_PH_SPAN = 0
_PH_INSTANT = 1

DEFAULT_CAPACITY = 65536
MIN_CAPACITY = 256


class Tracer:
    """Ring-buffer span/instant recorder (see module docstring).

    ``capacity`` bounds host memory (5 numpy columns, ~34 B/event); once
    exceeded the OLDEST events are overwritten and ``dropped`` counts
    them — the tracer never grows and never throws on overflow.
    """

    def __init__(self, capacity=DEFAULT_CAPACITY, clock=time.perf_counter):
        capacity = max(MIN_CAPACITY, int(capacity))
        self.capacity = capacity
        self.clock = clock
        self._ts = np.zeros(capacity, np.float64)
        self._dur = np.zeros(capacity, np.float64)
        self._name = np.zeros(capacity, np.int32)
        self._lane = np.zeros(capacity, np.int32)
        self._ph = np.zeros(capacity, np.int8)
        self._a0 = np.full(capacity, -1, np.int64)
        self._a1 = np.full(capacity, -1, np.int64)
        self._n = 0                     # total events ever recorded
        self._names = []                # id -> name
        self._name_ids = {}             # name -> id
        self._arg_labels = {}           # name id -> (label0, label1)
        self._lanes = []                # id -> lane name
        self._lane_ids = {}             # lane name -> id
        self._lock = threading.Lock()

    # -- interning ------------------------------------------------------
    def lane(self, name):
        """Intern a lane (exported as a named Chrome thread); returns its
        integer id — cache it at arming time, pass it on the hot path."""
        with self._lock:
            lid = self._lane_ids.get(name)
            if lid is None:
                lid = len(self._lanes)
                self._lanes.append(str(name))
                self._lane_ids[name] = lid
            return lid

    def intern(self, name, args=()):
        """Intern an event name (optionally labelling its two integer
        args for export); returns the integer name id."""
        with self._lock:
            nid = self._name_ids.get(name)
            if nid is None:
                nid = len(self._names)
                self._names.append(str(name))
                self._name_ids[name] = nid
            if args:
                self._arg_labels[nid] = tuple(str(a) for a in args[:2])
            return nid

    # -- hot path -------------------------------------------------------
    def begin(self):
        """Timestamp for a span start; pair with :meth:`complete`."""
        return self.clock()

    def complete(self, name, lane, t0, a0=-1, a1=-1):
        """Record one finished span [t0, now] on ``lane``."""
        self._record(_PH_SPAN, name, lane, t0, self.clock() - t0, a0, a1)

    def instant(self, name, lane, a0=-1, a1=-1):
        """Record a zero-duration marker event."""
        self._record(_PH_INSTANT, name, lane, self.clock(), 0.0, a0, a1)

    def _record(self, ph, name, lane, ts, dur, a0, a1):
        with self._lock:
            nid = self._name_ids.get(name)
            if nid is None:
                nid = len(self._names)
                self._names.append(str(name))
                self._name_ids[name] = nid
            i = self._n % self.capacity
            self._ts[i] = ts
            self._dur[i] = dur
            self._name[i] = nid
            self._lane[i] = lane
            self._ph[i] = ph
            self._a0[i] = a0
            self._a1[i] = a1
            self._n += 1

    # -- read side ------------------------------------------------------
    @property
    def recorded(self):
        """Total events ever recorded (including overwritten ones)."""
        return self._n

    @property
    def dropped(self):
        """Events overwritten by ring wrap-around."""
        return max(0, self._n - self.capacity)

    def events(self):
        """Retained events oldest-first, as plain dicts:
        ``{name, lane, ph ('X'|'i'), ts, dur, a0, a1}`` (times in
        seconds; ``a0``/``a1`` are the caller's integer args, -1 =
        unset)."""
        with self._lock:
            n = min(self._n, self.capacity)
            start = self._n - n
            idx = [(start + k) % self.capacity for k in range(n)]
            out = []
            for i in idx:
                out.append({
                    "name": self._names[self._name[i]],
                    "lane": self._lanes[self._lane[i]],
                    "ph": "X" if self._ph[i] == _PH_SPAN else "i",
                    "ts": float(self._ts[i]),
                    "dur": float(self._dur[i]),
                    "a0": int(self._a0[i]),
                    "a1": int(self._a1[i]),
                })
            return out

    def reset(self):
        with self._lock:
            self._n = 0

    def summary(self):
        """Small host-side status dict for reports."""
        return {"recorded": self.recorded, "retained": min(self._n,
                                                           self.capacity),
                "dropped": self.dropped, "capacity": self.capacity,
                "lanes": list(self._lanes)}

    # -- export ---------------------------------------------------------
    def _event_args(self, nid, a0, a1):
        labels = self._arg_labels.get(nid, ("a0", "a1"))
        args = {}
        if a0 != -1:
            args[labels[0] if len(labels) > 0 else "a0"] = int(a0)
        if a1 != -1:
            args[labels[1] if len(labels) > 1 else "a1"] = int(a1)
        return args

    def export_chrome_trace(self, path, pid=0, complete_events=True,
                            process_name="deepspeed_tpu"):
        """Write the retained events as Chrome-trace-event JSON (loadable
        in chrome://tracing and Perfetto).  Spans become complete ``X``
        events, or matched ``B``/``E`` pairs with
        ``complete_events=False``; instants become ``i`` with thread
        scope.  The write is atomic (temp file + rename) so a crash
        mid-export never leaves a torn trace.  Returns ``path``."""
        with self._lock:
            n = min(self._n, self.capacity)
            start = self._n - n
            trace_events = [{
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": process_name},
            }]
            for lid, lname in enumerate(self._lanes):
                trace_events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": lid, "args": {"name": lname}})
                trace_events.append({
                    "ph": "M", "name": "thread_sort_index", "pid": pid,
                    "tid": lid, "args": {"sort_index": lid}})
            for k in range(n):
                i = (start + k) % self.capacity
                nid = int(self._name[i])
                ts_us = self._ts[i] * 1e6
                base = {"name": self._names[nid], "cat": "telemetry",
                        "pid": pid, "tid": int(self._lane[i]),
                        "args": self._event_args(nid, int(self._a0[i]),
                                                 int(self._a1[i]))}
                if self._ph[i] == _PH_INSTANT:
                    trace_events.append(dict(base, ph="i", s="t",
                                             ts=round(ts_us, 3)))
                elif complete_events:
                    trace_events.append(dict(
                        base, ph="X", ts=round(ts_us, 3),
                        dur=round(self._dur[i] * 1e6, 3)))
                else:
                    trace_events.append(dict(base, ph="B",
                                             ts=round(ts_us, 3)))
                    trace_events.append({
                        "ph": "E", "pid": pid, "tid": int(self._lane[i]),
                        "ts": round(ts_us + self._dur[i] * 1e6, 3)})
            payload = {"traceEvents": trace_events,
                       "displayTimeUnit": "ms",
                       "otherData": {"dropped_events": self.dropped}}
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path


def lane_utilization(events, lanes=None):
    """Measured wall-clock utilization per lane from an event list (the
    output of :meth:`Tracer.events`): summed span durations over the
    global [first start, last end] window.

    Returns ``{lane: {busy_s, idle_fraction, spans}}`` plus the window
    under ``"_window_s"``.  This is the *measured* half of the bubble
    cross-check; on a host-dispatch-bound CPU mesh the wall numbers are
    dominated by dispatch, so the transferable tier-1 comparison is the
    cost-model replay (``bubble_accounting.replay_trace``) — both are
    reported side by side by ``PipelineEngine.measured_bubble_report``.
    """
    spans = [e for e in events if e["ph"] == "X"
             and (lanes is None or e["lane"] in lanes)]
    if not spans:
        return {"_window_s": 0.0}
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e["dur"] for e in spans)
    window = max(t1 - t0, 1e-12)
    out = {"_window_s": window}
    by_lane = {}
    for e in spans:
        by_lane.setdefault(e["lane"], []).append(e)
    for lane, evs in by_lane.items():
        busy = sum(e["dur"] for e in evs)
        out[lane] = {"busy_s": busy,
                     "idle_fraction": 1.0 - min(busy, window) / window,
                     "spans": len(evs)}
    return out
