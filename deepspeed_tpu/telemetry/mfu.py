"""MFU/HFU accounting from compiled-program cost analysis.

Two FLOP ledgers, reported side by side because they answer different
questions:

- **model FLOPs** (``model_flops_per_step``): the 6ND forward+backward
  formula (2ND forward-only for serving decode) — what the model
  mathematically requires.  ``MFU = model_flops / (step_time × devices
  × peak)``; remat recompute and padding never inflate it (the same
  convention as bench.py's TFLOPS claims).
- **hardware FLOPs**: summed ``compiled.cost_analysis()["flops"]`` over
  every registered jitted program × its calls per step — what XLA
  actually scheduled, including remat recompute, so
  ``HFU >= MFU`` and the gap IS the recompute/padding tax.  Because
  the capture preserves shardings, the compiled program (and so its
  cost) is the PER-DEVICE SPMD executable — ``hfu`` therefore divides
  by ``step_time × peak`` alone, while ``mfu`` divides the global model
  FLOPs by ``step_time × n_devices × peak``.

Registration is capture-by-shape: engines register a zero-arg
``make_compiled`` closure (built from ``jax.ShapeDtypeStruct`` trees of
the real dispatch args, under the engine's mesh) the FIRST time a jit
dispatches, and the closure is only invoked lazily at report time —
``lower().compile()`` on shape structs never touches donated buffers
and never runs device code, but it IS a compile, so it stays off the
hot path and outside any recompile-guard window.

Peak FLOPS resolution: an explicit ``peak_tflops_per_device`` config
wins; otherwise the device-kind table below (the bench.py table, bf16
peaks); unknown kinds (CPU meshes) report achieved FLOPS with
``mfu``/``hfu`` = None rather than a ratio against a guessed peak.
"""
import threading

import numpy as np

# bf16 peak TFLOPS per chip by device-kind substring (bench.py's table —
# kept in sync by tests/unit/test_telemetry.py)
PEAK_TFLOPS_TABLE = [
    ("v6e", 918.0), ("v6", 918.0),
    ("v5p", 459.0), ("v5e", 197.0), ("v5lite", 197.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]


def peak_flops_per_device(device_kind):
    """(peak FLOPS/s per device, known) for a device-kind string."""
    kind = (device_kind or "").lower().replace(" ", "")
    for key, peak in PEAK_TFLOPS_TABLE:
        if key in kind:
            return peak * 1e12, True
    return None, False


def normalize_cost_analysis(compiled):
    """``compiled.cost_analysis()`` → ``{"flops", "bytes_accessed"}``.

    jax has returned the analysis as a dict, a list of one dict, and (on
    some backends) nothing useful; missing keys come back as None so
    callers can report honestly instead of crashing on a backend quirk.
    """
    try:
        ca = compiled.cost_analysis()
    except (AttributeError, NotImplementedError, RuntimeError) as e:
        return {"flops": None, "bytes_accessed": None, "error": str(e)}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {"flops": None, "bytes_accessed": None}
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed", ca.get("bytes_accessed"))
    return {"flops": float(flops) if flops is not None else None,
            "bytes_accessed": float(nbytes) if nbytes is not None else None}


def model_flops_per_step(n_params, tokens_per_step, fwd_only=False):
    """The dense-transformer FLOP formula: 6ND fwd+bwd, 2ND fwd-only."""
    return (2.0 if fwd_only else 6.0) * float(n_params) \
        * float(tokens_per_step)


def shape_structs(args):
    """``jax.ShapeDtypeStruct`` tree of real dispatch args (non-array
    leaves coerced through numpy), PRESERVING each leaf's NamedSharding:
    a sharded program re-lowered from unsharded structs is a different
    program (and donation aliasing can refuse to compile it at all), so
    the structs must carry the placement for the capture to be faithful.
    Shared by the MFU and memory-accounting registrations."""
    import jax
    from jax.sharding import NamedSharding

    def struct(x):
        if not hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        sh = getattr(x, "sharding", None)
        if isinstance(sh, NamedSharding):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree_util.tree_map(struct, args)


def register_by_shape(mfu, name, jit_fn, args, mesh=None,
                      calls_per_step=1.0):
    """THE capture-by-shape registration every engine uses: take a
    ``jax.ShapeDtypeStruct`` tree of the REAL dispatch args NOW (donated
    buffers still alive, shardings preserved) and register a lazy
    ``lower().compile()`` closure — run once, at report time, under
    ``mesh`` when one is given — so the compile never lands on the step
    path or inside a recompile-guard window.  No-op when
    ``mfu``/``jit_fn`` is None or ``name`` is already registered."""
    if mfu is None or jit_fn is None or mfu.has(name):
        return
    import jax

    structs = shape_structs(args)

    def make_compiled():
        if mesh is None:
            return jit_fn.lower(*structs).compile()
        with jax.set_mesh(mesh):
            return jit_fn.lower(*structs).compile()

    mfu.register(name, make_compiled, calls_per_step)


class MfuAccounting:
    """Per-jit FLOPs/bytes registry + MFU/HFU report builder."""

    def __init__(self, peak_tflops_per_device=0.0):
        # explicit peak (TFLOPS) overrides device-kind lookup; 0 = auto
        self.peak_tflops_per_device = float(peak_tflops_per_device or 0.0)
        self._jits = {}        # name -> (make_compiled, calls_per_step)
        self._costs = {}       # name -> normalized cost dict (lazy)
        self._compiled = {}    # name -> compiled object (lazy, shared)
        self._lock = threading.Lock()

    def has(self, name):
        return name in self._jits

    def register(self, name, make_compiled, calls_per_step=1.0):
        """Register one jitted program.  ``make_compiled`` is a zero-arg
        callable returning the compiled object (typically
        ``lambda: jit_fn.lower(*shape_structs).compile()`` under the
        engine's mesh); it runs lazily, once, at report time."""
        with self._lock:
            if name not in self._jits:
                self._jits[name] = (make_compiled, float(calls_per_step))

    def calls_per_step(self, name):
        """Registered calls-per-step factor (None when unregistered)."""
        entry = self._jits.get(name)
        return entry[1] if entry is not None else None

    def compiled(self, name):
        """The lazily-compiled object for one registered program, cached
        so every ledger reading this registry (FLOPs here, bytes in
        runtime/memory_accounting.py) pays ONE ``lower().compile()`` per
        jit between them.  Raises whatever the lowering raised; returns
        None for unregistered names."""
        entry = self._jits.get(name)
        if entry is None:
            return None
        if name not in self._compiled:
            self._compiled[name] = entry[0]()
        return self._compiled[name]

    def costs(self):
        """{name: {flops, bytes_accessed, calls_per_step}} — compiled
        lazily on first call, cached after.  A program whose lowering
        fails reports its error string instead of poisoning the rest."""
        with self._lock:
            jits = dict(self._jits)
        for name, (_make, calls) in jits.items():
            if name in self._costs:
                continue
            try:
                cost = normalize_cost_analysis(self.compiled(name))
            except Exception as e:  # lint: allow-broad-except — one
                # program's lowering quirk must not kill the report
                cost = {"flops": None, "bytes_accessed": None,
                        "error": f"{type(e).__name__}: {e}"}
            cost["calls_per_step"] = calls
            self._costs[name] = cost
        return dict(self._costs)

    def hw_flops_per_step(self):
        total, complete = 0.0, True
        for cost in self.costs().values():
            if cost["flops"] is None:
                complete = False
                continue
            total += cost["flops"] * cost["calls_per_step"]
        return (total if total > 0 else None), complete

    def report(self, *, step_time_s, n_devices, model_flops=None,
               device_kind=None):
        """The ``telemetry_report()["mfu"]`` section.  ``model_flops``
        is per step, all devices; ``step_time_s`` mean seconds per
        optimizer/serving step."""
        hw_flops, complete = self.hw_flops_per_step()
        if self.peak_tflops_per_device > 0:
            peak, peak_known = self.peak_tflops_per_device * 1e12, True
        else:
            peak, peak_known = peak_flops_per_device(device_kind)
        denom = None
        if step_time_s and step_time_s > 0 and n_devices:
            denom = step_time_s * n_devices
        out = {
            "per_jit": self.costs(),
            "hw_flops_per_step": hw_flops,
            "hw_flops_complete": complete,
            "model_flops_per_step": model_flops,
            "step_time_s": step_time_s,
            "n_devices": n_devices,
            "device_kind": device_kind,
            "peak_flops_per_device": peak,
            "peak_known": peak_known,
            "achieved_tflops_per_device":
                (model_flops / denom / 1e12)
                if (denom and model_flops) else None,
            # hw flops are PER-DEVICE (the sharded SPMD executable's own
            # cost_analysis): no n_devices in the hardware denominators
            "achieved_hw_tflops_per_device":
                (hw_flops / step_time_s / 1e12)
                if (step_time_s and hw_flops) else None,
            "mfu": (model_flops / (denom * peak))
            if (denom and model_flops and peak) else None,
            "hfu": (hw_flops / (step_time_s * peak))
            if (step_time_s and hw_flops and peak) else None,
        }
        return out
