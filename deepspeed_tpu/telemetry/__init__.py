"""deepspeed_tpu.telemetry — structured step tracing, unified metrics,
and measured-vs-analytic MFU accounting (see
docs/tutorials/observability.md).

One :class:`Telemetry` session per engine bundles the three channels:

- ``tracer`` (:mod:`.trace`): ring-buffer span/instant recorder with
  Chrome-trace/Perfetto export;
- ``registry`` + ``stream`` (:mod:`.metrics`): counters/gauges/
  histograms and the step-aligned JSONL time series;
- ``mfu`` (:mod:`.mfu`): per-jit FLOPs/bytes from
  ``compiled.cost_analysis()`` → MFU/HFU.

Engines arm it through ``_arm_telemetry`` (config block ``"telemetry"``
for the training engines, the ``telemetry=`` kwarg for the serving
engine); disarmed engines hold ``None`` and pay one attribute check per
instrumentation site.
"""
import time

from deepspeed_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                             MetricsRegistry, MetricsStream,
                                             nearest_rank)
from deepspeed_tpu.telemetry.mfu import (MfuAccounting,
                                         model_flops_per_step,
                                         normalize_cost_analysis,
                                         peak_flops_per_device,
                                         register_by_shape)
from deepspeed_tpu.telemetry.programs import (ProgramRegistry,
                                              register_program)
from deepspeed_tpu.telemetry.trace import (Tracer, lane_utilization)

__all__ = [
    "Telemetry", "Tracer", "lane_utilization",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MetricsStream",
    "nearest_rank",
    "MfuAccounting", "model_flops_per_step", "normalize_cost_analysis",
    "peak_flops_per_device", "register_by_shape",
    "ProgramRegistry", "register_program",
]


class Telemetry:
    """One engine's telemetry session (tracer + metrics + MFU).

    ``on_step(step, payload)`` is the single per-step hook every engine
    calls at its step boundary: it feeds the ``step_time_s`` histogram
    (wall delta between consecutive calls — compile-heavy first steps
    excluded from the mean by construction, they have no predecessor)
    and appends one JSONL record to the metrics stream when one is
    armed.
    """

    def __init__(self, *, trace=True, trace_capacity=None,
                 metrics_jsonl=None, metrics_fsync=False, mfu=True,
                 peak_tflops_per_device=0.0, clock=time.perf_counter):
        from deepspeed_tpu.telemetry import trace as trace_mod

        self.tracer = Tracer(trace_capacity or trace_mod.DEFAULT_CAPACITY,
                             clock=clock) if trace else None
        self.registry = MetricsRegistry()
        self.stream = MetricsStream(metrics_jsonl, fsync=metrics_fsync) \
            if metrics_jsonl else None
        self.mfu = MfuAccounting(peak_tflops_per_device) if mfu else None
        self._clock = clock
        self._last_step_t = None
        self.step_time_hist = self.registry.histogram("step_time_s")

    def on_step(self, step, payload=None):
        now = self._clock()
        if self._last_step_t is not None:
            self.step_time_hist.add(now - self._last_step_t)
        self._last_step_t = now
        self.registry.counter("steps").inc()
        if self.stream is not None:
            self.stream.emit(step, payload)

    def step_time_s(self):
        """Mean seconds per step over the retained window (None before
        two steps)."""
        return self.step_time_hist.mean()

    def close(self):
        if self.stream is not None:
            self.stream.close()
