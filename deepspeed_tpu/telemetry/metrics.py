"""Unified metrics: counters/gauges/histograms + a step-aligned JSONL
time-series stream.

One schema for every producer (training engine, pipeline engine, serving
engine, checkpoint commit path):

- ``Counter`` — monotonically increasing event count;
- ``Gauge`` — last-written value;
- ``Histogram`` — bounded sample reservoir with the repo's single
  nearest-rank percentile implementation (``nearest_rank``), which
  ``serving/metrics._pct`` also routes through: empty input is ``None``
  (never raises), one sample IS every percentile, q clamps to [0, 1].

``MetricsRegistry.snapshot()`` is the dict the engines' unified
``telemetry_report()`` embeds next to the legacy report builders
(``_last_metrics`` / ``pipeline_report`` / ``serving_report`` /
``comm_volume_report``) without replacing them.

``MetricsStream`` is the on-disk time series: append-only JSONL, one
record per optimizer/serving step, flushed at every emit (optionally
fsync'd) — the request-journal idiom from the serving reliability
layer.  A crash can tear at most the final line; :meth:`replay`
tolerates exactly that (a torn tail is skipped, every complete record
is returned), so dead bench rounds still leave a readable step trail.
"""
import json
import os
import threading
import time

from deepspeed_tpu.utils.logging import logger


def nearest_rank(xs, q):
    """Nearest-rank percentile, total over its edge cases: empty input
    is ``None`` (never raises), a single sample IS every percentile,
    and q is clamped to [0, 1] — overload guards read p50/p95 off
    arbitrary slices of a run, including before the first sample."""
    if not xs:
        return None
    s = sorted(xs)
    q = min(1.0, max(0.0, q))
    return s[min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))]


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, value):
        self.value = value


class Histogram:
    """Sample collector with nearest-rank percentiles.

    ``max_samples`` bounds host memory: past it the reservoir keeps the
    most recent window (ring overwrite) — latency distributions are
    about the recent regime, and an unbounded list in a long serving
    run would be its own observability bug.  ``count``/``mean``/``max``
    stay exact over the WHOLE run (running total + running max);
    only the percentiles are windowed."""

    __slots__ = ("values", "count", "_total", "_hi", "_max", "_i")

    def __init__(self, max_samples=4096):
        self.values = []
        self.count = 0
        self._total = 0.0
        self._hi = None
        self._max = int(max_samples)
        self._i = 0

    def add(self, value):
        v = float(value)
        self.count += 1
        self._total += v
        if self._hi is None or v > self._hi:
            self._hi = v
        if len(self.values) < self._max:
            self.values.append(v)
        else:
            self.values[self._i] = v
            self._i = (self._i + 1) % self._max
    # an alias some metric producers read more naturally
    observe = add

    def mean(self):
        return self._total / self.count if self.count else None

    def pct(self, q):
        return nearest_rank(self.values, q)

    def max(self):
        return self._hi

    def summary(self):
        return {"count": self.count, "mean": self.mean(),
                "p50": self.pct(.5), "p95": self.pct(.95),
                "max": self.max()}


class MetricsRegistry:
    """Get-or-create registry; one instance per engine."""

    def __init__(self):
        self._counters = {}
        self._gauges = {}
        self._hists = {}
        self._lock = threading.Lock()

    def counter(self, name) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name, max_samples=4096) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(max_samples)
            return h

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {k: h.summary()
                               for k, h in self._hists.items()},
            }


def _json_safe(x):
    """JSON default: numpy scalars/arrays and other exotics degrade to
    plain numbers/lists/strings instead of failing the step emit."""
    try:
        import numpy as np

        if isinstance(x, np.ndarray):
            return x.tolist()
        if isinstance(x, np.generic):
            return x.item()
    except ImportError:  # pragma: no cover
        pass
    if hasattr(x, "item"):
        try:
            return x.item()
        except (TypeError, ValueError):
            pass
    return str(x)


class MetricsStream:
    """Append-only step-aligned JSONL time series (see module docstring).

    Records are ``{"step": n, "t": unix_seconds, ...payload}``, one per
    line, flushed per emit so the tail is at most ONE torn record deep.
    """

    def __init__(self, path, fsync=False, clock=time.time):
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.path = path
        self._fsync = bool(fsync)
        self._clock = clock
        self._fh = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self.emitted = 0

    def emit(self, step, payload):
        rec = {"step": int(step), "t": self._clock()}
        rec.update(payload or {})
        line = json.dumps(rec, default=_json_safe)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())
            self.emitted += 1

    def close(self):
        """Idempotent: an explicit close followed by the engine's
        GC-time close must not raise on the already-closed handle."""
        with self._lock:
            if self._fh.closed:
                return
            try:
                self._fh.flush()
            finally:
                self._fh.close()

    @staticmethod
    def replay(path):
        """Read every COMPLETE record of a metrics stream; a torn final
        line (crash mid-write) is skipped with a warning, any other
        malformed line raises — silent mid-stream corruption must not
        read as a clean shorter run."""
        out = []
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().split("\n")
        # a trailing "" after the final newline is normal; anything else
        # in the last slot is the torn tail
        body, tail = lines[:-1], lines[-1]
        for i, line in enumerate(body):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i + 1}: corrupt metrics record mid-stream "
                    f"({e}); only the final line may be torn") from e
        if tail.strip():
            try:
                out.append(json.loads(tail))
            except ValueError:
                logger.warning(
                    f"{path}: torn final metrics record skipped "
                    f"({len(tail)} bytes) — crash mid-emit")
        return out
