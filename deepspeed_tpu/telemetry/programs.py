"""Compiled-program registry: every jit an engine builds, with contracts.

This generalizes :mod:`.mfu`'s capture-by-shape registration from "the
jits the FLOP ledger cares about" to "every jit the engine dispatches",
and attaches **declarative contract metadata** to each entry — the
performance/correctness claims the program must keep at the HLO level:

- ``host_transfer_free``: no infeed/outfeed/host callback may survive
  compilation (a stray debug print would stall every dispatch);
- ``collective_free``: the program moves ZERO cross-device bytes
  (0/1 Adam local rounds, batch-sharded serving decode);
- ``wire_dtype``: the collective payload dtype(s) the program declares
  (``"s8"``, ``("u8", "s8")``); any f32/bf16 collective at or above
  ``wire_min_elements`` in such a program means the partitioner
  silently re-widened the wire (the EQuARX failure class);
- ``donates`` / ``donates_argnums``: entry parameters that MUST appear
  in the ``input_output_alias`` / ``buffer_donor`` header tables — a
  declared-donated input missing from both pays a silent copy per call
  and re-arms the allocator at every dispatch; ``donation_min_elements``
  exempts sub-threshold leaves (XLA declines to alias tiny pass-through
  buffers — an rng key threaded through a ``lax.cond`` — and the copy
  cost is nil);
- ``comm_budget_bytes`` (+ ``comm_budget_key``, ``comm_small_op_cutoff``):
  analytic byte ceiling for the program's total collective payload;
- ``boundary_dtypes``: exact entry-output dtype list (pipeline boundary
  activations must leave a bf16 stage in bf16);
- ``forbid_collectives`` / ``expect_op_counts``: op kinds that must not
  appear (a backward that regathers weights) / exact (op, dtype, count)
  expectations (one s8 gather per partitioned stage-3 leaf);
- ``outputs_aliased``: at least this many outputs write into donated
  memory (grad-accumulator handoffs);
- ``uniform_group``: programs sharing a group name are executed at the
  same schedule slot by different callers and must post an IDENTICAL
  collective sequence — a divergence is a static SPMD deadlock.

Contract values may be zero-arg callables: they resolve lazily when the
lint pass reads them (analytic comm budgets depend on
``comm_volume_report()`` state that settles after warmup).

Registration is free on the hot path (a ShapeDtypeStruct capture and a
dict insert, once per program); ``lower().compile()`` runs lazily when
``tools/graftlint/program_lint.py`` walks the registry — never at
dispatch time, never inside a recompile-guard window.  This registry is
the shared program view ROADMAP item 5's unified plan compiler consumes.
"""
import threading

from deepspeed_tpu.telemetry.mfu import shape_structs

# every key a contract dict may carry — program_lint validates against
# this so a typo'd declaration fails loudly instead of never checking
CONTRACT_KEYS = frozenset({
    "host_transfer_free", "collective_free",
    "wire_dtype", "wire_min_elements",
    "donates", "donates_argnums", "donation_min_elements",
    "comm_budget_bytes", "comm_budget_key", "comm_small_op_cutoff",
    "boundary_dtypes", "forbid_collectives", "expect_op_counts",
    "outputs_aliased", "uniform_group",
})


class ProgramEntry:
    """One registered program: a lazy lower/compile closure + contract."""

    __slots__ = ("name", "make_lowered", "contract", "calls_per_step",
                 "_hlo", "_error", "_kept")

    def __init__(self, name, make_lowered, contract, calls_per_step):
        self.name = name
        self.make_lowered = make_lowered
        self.contract = dict(contract or {})
        self.calls_per_step = float(calls_per_step)
        self._hlo = None
        self._error = None
        self._kept = None

    def hlo(self):
        """Optimized HLO text, compiled lazily once and cached.  Raises
        what the lowering raised (also cached, so a broken program costs
        one compile attempt, not one per contract)."""
        if self._error is not None:
            raise self._error
        if self._hlo is None:
            try:
                lowered = self.make_lowered()
                self._kept = self._kept_var_idx(lowered)
                self._hlo = lowered.compile().as_text()
            except Exception as e:  # lint: allow-broad-except — cache
                # the failure whatever it was; the lint pass reports it
                self._error = e
                raise
        return self._hlo

    @property
    def kept_var_idx(self):
        """Sorted FLAT arg indices the lowering kept as entry parameters
        (jit prunes unused args by default, shifting HLO parameter
        numbers against flat indices), or None when unknown.  Populated
        by :meth:`hlo`; the lint's donation scan translates declared
        flat ``donates`` indices through this before reading the alias
        tables."""
        return self._kept

    @staticmethod
    def _kept_var_idx(lowered):
        try:
            kept = lowered._lowering.compile_args.get("kept_var_idx")
            return sorted(kept) if kept is not None else None
        except Exception:  # internal API — absence degrades gracefully
            return None


class ProgramRegistry:
    """Per-engine registry of every jit the engine builds."""

    def __init__(self, engine="engine"):
        self.engine = str(engine)
        self._entries = {}
        self._lock = threading.Lock()

    def has(self, name):
        return name in self._entries

    def names(self):
        return sorted(self._entries)

    def get(self, name):
        return self._entries.get(name)

    def entries(self):
        """Entries in sorted-name order (stable lint reports)."""
        return [self._entries[n] for n in sorted(self._entries)]

    def register(self, name, make_lowered, contract=None,
                 calls_per_step=1.0):
        bad = set(contract or ()) - CONTRACT_KEYS
        if bad:
            raise ValueError(
                f"unknown contract key(s) {sorted(bad)} for program "
                f"{name!r}; known: {sorted(CONTRACT_KEYS)}")
        with self._lock:
            if name not in self._entries:
                self._entries[name] = ProgramEntry(
                    name, make_lowered, contract, calls_per_step)

    def declare(self, name, **contract):
        """Merge contract keys into an already-registered entry (for
        claims only known after registration)."""
        bad = set(contract) - CONTRACT_KEYS
        if bad:
            raise ValueError(f"unknown contract key(s) {sorted(bad)}")
        entry = self._entries[name]
        entry.contract.update(contract)

    def summary(self):
        """JSON-able view: {name: {contract (callables resolved),
        calls_per_step}} — what ``--programs --json`` ships."""
        out = {}
        for entry in self.entries():
            out[entry.name] = {
                "contract": {k: resolve_contract_value(v)
                             for k, v in sorted(entry.contract.items())},
                "calls_per_step": entry.calls_per_step,
            }
        return out


def resolve_contract_value(value):
    """Contract values may be zero-arg callables (lazy analytic budgets);
    resolve to something JSON-able."""
    if callable(value):
        try:
            value = value()
        except Exception as e:  # lint: allow-broad-except — a budget
            # that cannot resolve is itself a reportable fact
            return f"<unresolvable: {type(e).__name__}: {e}>"
    if isinstance(value, (tuple, set, frozenset)):
        return list(value)
    if isinstance(value, range):
        return list(value)
    return value


def _leaf_offsets(args):
    """Flat entry-parameter index offset of each positional arg (a jit
    with no static args flattens its arguments in order)."""
    import jax

    offsets, total = [], 0
    for a in args:
        offsets.append(total)
        total += len(jax.tree_util.tree_leaves(a))
    return offsets, total


def register_program(programs, name, jit_fn, args, mesh=None,
                     contract=None, calls_per_step=1.0):
    """THE capture-by-shape program registration: take a
    ``jax.ShapeDtypeStruct`` tree of the REAL dispatch args NOW (donated
    buffers still alive, shardings preserved) and register a lazy
    ``lower().compile()`` closure plus the program's contract.  A
    ``donates_argnums`` contract key is expanded here — while the real
    args are in hand — into the flat ``donates`` parameter indices the
    HLO header tables speak.  No-op when ``programs``/``jit_fn`` is None
    or ``name`` is already registered."""
    if programs is None or jit_fn is None or programs.has(name):
        return
    import jax

    contract = dict(contract or {})
    if "donates_argnums" in contract:
        offsets, total = _leaf_offsets(args)
        donated = []
        for argnum in contract.pop("donates_argnums"):
            lo = offsets[argnum]
            hi = offsets[argnum + 1] if argnum + 1 < len(offsets) else total
            donated.extend(range(lo, hi))
        existing = list(contract.get("donates", ()))
        contract["donates"] = sorted(set(existing) | set(donated))

    structs = shape_structs(args)

    def make_lowered():
        if mesh is None:
            return jit_fn.lower(*structs)
        with jax.set_mesh(mesh):
            return jit_fn.lower(*structs)

    programs.register(name, make_lowered, contract, calls_per_step)
