"""BERT — encoder model family built on DeepSpeedTransformerLayer.

Reference: the fused-kernel BERT pretraining flow (docs 'fastest BERT
training') and the test-fixture BERT implementations used as kernel ground
truth (tests/unit/modeling.py:1-1578 post-LN, modelingpreln.py pre-LN). This
is the TPU bench model for the BERT-large pretrain baseline (SURVEY §6).
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    # MXU lane alignment for the embedding + tied MLM-head matmuls
    # (30522 -> 30592); logits are sliced back, ids stay < vocab_size
    pad_vocab_multiple: int = 128
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # ops/sparse_attention SparsityConfig: routes every encoder layer's
    # attention through the block-sparse kernel (long-sequence BERT,
    # reference README.md:17); params are identical to the dense model
    sparsity_config: Any = None

    @property
    def padded_vocab_size(self):
        from deepspeed_tpu.models.api import pad_to_multiple

        return pad_to_multiple(self.vocab_size, self.pad_vocab_multiple)


BERT_SIZES = {
    "bert-base": dict(hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072),
    "bert-large": dict(hidden_size=1024, num_hidden_layers=24,
                       num_attention_heads=16, intermediate_size=4096),
}


def bert_config(name: str, **overrides) -> BertConfig:
    base = dict(BERT_SIZES[name])
    base.update(overrides)
    return BertConfig(**base)


def _layer_config(cfg: BertConfig) -> DeepSpeedTransformerConfig:
    return DeepSpeedTransformerConfig(
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        heads=cfg.num_attention_heads,
        attn_dropout_ratio=cfg.attention_probs_dropout_prob,
        hidden_dropout_ratio=cfg.hidden_dropout_prob,
        num_hidden_layers=cfg.num_hidden_layers,
        initializer_range=cfg.initializer_range,
        layer_norm_eps=cfg.layer_norm_eps,
        bf16=cfg.dtype == jnp.bfloat16,
        fp16=cfg.dtype == jnp.float16,
        pre_layer_norm=cfg.pre_layer_norm,
        normalize_invertible=cfg.remat,
        sparsity_config=cfg.sparsity_config)


class BertEmbeddings(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, train: bool):
        cfg = self.config
        S = input_ids.shape[1]
        word = self.param("word_embeddings", nn.initializers.normal(
            cfg.initializer_range), (cfg.padded_vocab_size, cfg.hidden_size),
            jnp.float32)
        pos = self.param("position_embeddings", nn.initializers.normal(
            cfg.initializer_range),
            (cfg.max_position_embeddings, cfg.hidden_size), jnp.float32)
        typ = self.param("token_type_embeddings", nn.initializers.normal(
            cfg.initializer_range), (cfg.type_vocab_size, cfg.hidden_size),
            jnp.float32)
        x = word.astype(cfg.dtype)[input_ids] \
            + pos.astype(cfg.dtype)[None, :S] \
            + typ.astype(cfg.dtype)[token_type_ids]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln")(x)
        if train and cfg.hidden_dropout_prob > 0:
            x = nn.Dropout(cfg.hidden_dropout_prob)(x, deterministic=False)
        return x


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, train: bool):
        layer_cfg = _layer_config(self.config)
        for i in range(self.config.num_hidden_layers):
            x = DeepSpeedTransformerLayer(layer_cfg, name=f"layer_{i}")(
                x, attention_mask, train=train)
        if self.config.pre_layer_norm:
            x = nn.LayerNorm(epsilon=self.config.layer_norm_eps,
                             dtype=self.config.dtype, name="final_ln")(x)
        return x


class BertForPreTrainingModule(nn.Module):
    """Embeddings -> encoder -> MLM head (tied decoder) + NSP head."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 train: bool = False):
        cfg = self.config
        B, S = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        # HF-style extended additive mask: (B, 1, 1, S), 0 keep / -1e30 drop
        ext_mask = None
        if attention_mask is not None:
            ext_mask = (1.0 - attention_mask[:, None, None, :]
                        .astype(jnp.float32)) * -1e30

        emb = BertEmbeddings(cfg, name="embeddings")
        x = emb(input_ids, token_type_ids, train)
        x = BertEncoder(cfg, name="encoder")(x, ext_mask, train)

        # MLM: transform -> LN -> tied decoder over word embeddings
        word = self.variables["params"]["embeddings"]["word_embeddings"]
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     name="mlm_transform")(x)
        h = nn.gelu(h, approximate=False)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlm_ln")(h)
        mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                              (cfg.padded_vocab_size,), jnp.float32)
        logits = jnp.einsum("bse,ve->bsv", h, word.astype(cfg.dtype)) \
            + mlm_bias.astype(cfg.dtype)
        # drop MXU-alignment pad columns before the loss/softmax
        logits = logits[..., :cfg.vocab_size]

        # NSP over the pooled [CLS]
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                                  name="pooler")(x[:, 0]))
        nsp_logits = nn.Dense(2, dtype=cfg.dtype, name="nsp")(pooled)
        return logits, nsp_logits


class BertForPreTraining:
    """Engine model contract: masked-LM (+ optional NSP) pretraining loss.

    batch keys: input_ids, attention_mask (optional), token_type_ids
    (optional), masked_lm_labels (-1 or -100 = unmasked), next_sentence_label
    (optional).
    """

    def __init__(self, config: BertConfig):
        self.config = config
        self.module = BertForPreTrainingModule(config)

    def init(self, rng, batch):
        return self.module.init(
            {"params": rng, "dropout": rng}, batch["input_ids"],
            batch.get("attention_mask"), batch.get("token_type_ids"),
            train=False)["params"]

    def loss(self, params, batch, rng, train=True):
        logits, nsp_logits = self.module.apply(
            {"params": params}, batch["input_ids"],
            batch.get("attention_mask"), batch.get("token_type_ids"),
            train=train, rngs={"dropout": rng})
        labels = batch["masked_lm_labels"]
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        safe = jnp.maximum(labels, 0)
        gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        mlm_loss = jnp.sum((logz - gold) * mask) / jnp.maximum(
            jnp.sum(mask), 1.0)
        total = mlm_loss
        metrics = {"mlm_loss": mlm_loss}
        if "next_sentence_label" in batch:
            nsp_logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32))
            nsp_loss = -jnp.mean(jnp.take_along_axis(
                nsp_logp, batch["next_sentence_label"][:, None], axis=1))
            total = total + nsp_loss
            metrics["nsp_loss"] = nsp_loss
        metrics["loss"] = total
        return total, metrics

    def param_partition_spec(self, params):
        """TP over 'model': QKV/intermediate out-dim, attn-out/ffn-out
        in-dim, embeddings vocab dim."""
        def spec(path, leaf):
            joined = "/".join(str(getattr(p, "key", p)) for p in path)
            if leaf.ndim == 0:
                return P()
            if "word_embeddings" in joined:
                return P("model", None)
            if ("qkv" in joined or "ffn_inter" in joined) and leaf.ndim == 2:
                return P(None, "model")
            if ("attn_out" in joined or "ffn_out" in joined) and leaf.ndim == 2:
                return P("model", None)
            return P()

        return jax.tree_util.tree_map_with_path(spec, params)

    def num_params(self, params):
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
