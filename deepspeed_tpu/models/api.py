"""Model contract consumed by the engine.

The reference engine wraps a torch ``nn.Module`` (reference: runtime/engine.py:101).
The TPU engine is functional: a model is anything exposing

  - ``init(rng, batch) -> params``                (parameter pytree, fp32)
  - ``loss(params, batch, rng, train) -> (loss, metrics_dict)``
  - ``param_partition_spec(params) -> pytree of PartitionSpec``  (optional;
    tensor-parallel layout over the 'model' mesh axis — this build implements
    TP natively, unlike the reference which delegates to an external Megatron
    mpu, SURVEY §2.5)

``FlaxModel`` adapts a flax linen module + loss head to this contract.
"""
from typing import Any, Callable, Optional


class FlaxModel:
    """Adapter: flax linen module -> engine model contract.

    module.__call__(batch_inputs, train=...) must return model outputs;
    ``loss_head(outputs, batch) -> (scalar_loss, metrics)``.
    """

    def __init__(self, module, loss_head: Callable, input_key: str = "input",
                 partition_rules: Optional[Callable] = None):
        self.module = module
        self.loss_head = loss_head
        self.input_key = input_key
        self.partition_rules = partition_rules

    def init(self, rng, batch):
        variables = self.module.init(
            {"params": rng, "dropout": rng}, batch[self.input_key], train=False)
        return variables["params"]

    def loss(self, params, batch, rng, train=True):
        outputs = self.module.apply({"params": params}, batch[self.input_key],
                                    train=train, rngs={"dropout": rng})
        return self.loss_head(outputs, batch)

    def param_partition_spec(self, params):
        import jax
        from jax.sharding import PartitionSpec as P

        if self.partition_rules is None:
            return jax.tree_util.tree_map(lambda _: P(), params)
        return self.partition_rules(params)


def replicated_spec(params):
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(lambda _: P(), params)


def cross_entropy_loss(logits, labels, ignore_index: Optional[int] = None):
    """Token-level softmax cross entropy; returns (mean_loss, metrics)."""
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    logz = jnp.log(jnp.sum(jnp.exp(logits - jnp.max(logits, -1, keepdims=True)),
                           -1)) + jnp.max(logits, -1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if ignore_index is not None:
        mask = (labels != ignore_index).astype(jnp.float32)
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    else:
        loss = jnp.mean(nll)
    return loss, {"loss": loss}
