"""Model contract consumed by the engine.

The reference engine wraps a torch ``nn.Module`` (reference: runtime/engine.py:101).
The TPU engine is functional: a model is anything exposing

  - ``init(rng, batch) -> params``                (parameter pytree, fp32)
  - ``loss(params, batch, rng, train) -> (loss, metrics_dict)``
  - ``param_partition_spec(params) -> pytree of PartitionSpec``  (optional;
    tensor-parallel layout over the 'model' mesh axis — this build implements
    TP natively, unlike the reference which delegates to an external Megatron
    mpu, SURVEY §2.5)

``FlaxModel`` adapts a flax linen module + loss head to this contract.
"""
from typing import Any, Callable, Optional


def pad_to_multiple(n: int, multiple: int) -> int:
    """Ceil `n` to a multiple (MXU lane alignment for vocab dims); 0/None
    multiple returns n unchanged. Single source of truth for GPT2Config,
    BertConfig and the HF weight loader."""
    return -(-n // multiple) * multiple if multiple else n


class FlaxModel:
    """Adapter: flax linen module -> engine model contract.

    module.__call__(batch_inputs, train=...) must return model outputs;
    ``loss_head(outputs, batch) -> (scalar_loss, metrics)``.
    """

    def __init__(self, module, loss_head: Callable, input_key: str = "input",
                 partition_rules: Optional[Callable] = None):
        self.module = module
        self.loss_head = loss_head
        self.input_key = input_key
        self.partition_rules = partition_rules

    def init(self, rng, batch):
        variables = self.module.init(
            {"params": rng, "dropout": rng}, batch[self.input_key], train=False)
        return variables["params"]

    def loss(self, params, batch, rng, train=True):
        outputs = self.module.apply({"params": params}, batch[self.input_key],
                                    train=train, rngs={"dropout": rng})
        return self.loss_head(outputs, batch)

    def param_partition_spec(self, params):
        import jax
        from jax.sharding import PartitionSpec as P

        if self.partition_rules is None:
            return jax.tree_util.tree_map(lambda _: P(), params)
        return self.partition_rules(params)


def replicated_spec(params):
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(lambda _: P(), params)


def cross_entropy_loss(logits, labels, ignore_index: Optional[int] = None):
    """Token-level softmax cross entropy; returns (mean_loss, metrics)."""
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    logz = jnp.log(jnp.sum(jnp.exp(logits - jnp.max(logits, -1, keepdims=True)),
                           -1)) + jnp.max(logits, -1)
    # ignored labels (e.g. -100) are out of range: gather them at 0 and mask
    # (out-of-bounds take_along_axis fills NaN, and NaN*0 stays NaN)
    safe_labels = labels if ignore_index is None else \
        jnp.where(labels == ignore_index, 0, labels)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if ignore_index is not None:
        mask = (labels != ignore_index).astype(jnp.float32)
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    else:
        loss = jnp.mean(nll)
    return loss, {"loss": loss}


def chunked_lm_cross_entropy(hidden, wte, labels, chunk_tokens: int = 2048,
                             ignore_index: Optional[int] = -100,
                             valid_vocab: Optional[int] = None):
    """Memory-efficient LM head + softmax cross entropy.

    Computes mean(-log softmax(hidden @ wte.T)[labels]) WITHOUT materializing
    the full (tokens, vocab) logits tensor: a lax.scan walks token chunks,
    and jax.checkpoint on the body makes the backward recompute each chunk's
    logits instead of saving them. Peak extra memory is O(chunk_tokens *
    vocab) instead of O(batch * seq * vocab) — the fp32 logits residual was
    the allocation that kept gpt2-350m from fitting batch 32 on one v5e chip
    (round-4 profile; the reference leans on fused CUDA softmax-xent kernels
    for the same reason, csrc/transformer/softmax_kernels.cu).

    hidden: (..., E) activations entering the LM head (already shifted);
    wte: (V, E) tied embedding; labels: (...) int targets aligned to hidden;
    valid_vocab: when wte carries MXU-alignment pad rows (V > true vocab),
    columns >= valid_vocab are masked out of the softmax so padding stays an
    invisible layout detail.
    """
    import jax
    import jax.numpy as jnp

    E = hidden.shape[-1]
    x = hidden.reshape(-1, E)
    y = labels.reshape(-1)
    n = x.shape[0]
    chunk = max(1, min(chunk_tokens, n))
    pad = (-n) % chunk
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        fill = ignore_index if ignore_index is not None else 0
        y = jnp.pad(y, (0, pad), constant_values=fill)
        if ignore_index is None:
            # no ignore label available: mask pad rows explicitly
            valid = jnp.pad(jnp.ones((n,), jnp.float32), (0, pad))
    xs = x.reshape(-1, chunk, E)
    ys = y.reshape(-1, chunk)
    if ignore_index is not None:
        valids = (ys != ignore_index).astype(jnp.float32)
    else:
        valids = (valid if pad else jnp.ones_like(y, jnp.float32)).reshape(
            -1, chunk)

    def body(carry, inputs):
        nll_sum, cnt = carry
        xc, yc, mc = inputs
        logits = jax.lax.dot_general(
            xc, wte.astype(xc.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (chunk, V) f32
        if valid_vocab is not None and valid_vocab < wte.shape[0]:
            cols = jax.lax.iota(jnp.int32, wte.shape[0])
            logits = jnp.where(cols[None, :] < valid_vocab, logits, -1e9)
        m = jnp.max(logits, axis=-1)
        logz = jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1)) + m
        safe = jnp.where(mc > 0, yc, 0)
        gold = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        nll = (logz - gold) * mc
        return (nll_sum + jnp.sum(nll), cnt + jnp.sum(mc)), None

    (nll_sum, cnt), _ = jax.lax.scan(
        jax.checkpoint(body), (jnp.float32(0.0), jnp.float32(0.0)),
        (xs, ys, valids))
    loss = nll_sum / jnp.maximum(cnt, 1.0)
    return loss, {"loss": loss}
