"""GPT-2 family — the flagship LM for benchmarks.

TPU-first design: flax linen decoder with
- bf16 compute / fp32 master params (engine-managed),
- Megatron-style tensor parallelism expressed as PartitionSpecs over the
  'model' mesh axis (this build owns TP natively; the reference only consumed
  an external Megatron mpu, SURVEY §2.5),
- jax.checkpoint (remat) per block for activation checkpointing,
- attention through ops.transformer.functional (Pallas flash path on TPU).

Size table mirrors the reference perf harness configs
(tests/model/Megatron_GPT2/run_perf_test.py:18-84: 1.5B = 48L x 1600h etc.).
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.api import (chunked_lm_cross_entropy,
                                      cross_entropy_loss)
from deepspeed_tpu.ops.transformer.functional import scaled_dot_product_attention
from deepspeed_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    # Pad the embedding/LM-head vocab dim to a multiple of this so the two
    # biggest matmuls in the model tile cleanly onto the MXU's 128 lanes
    # (50257 -> 50304). Purely an internal layout: ids stay < vocab_size,
    # logits are sliced/masked back to vocab_size everywhere. 0 disables.
    pad_vocab_multiple: int = 128
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16      # compute dtype
    remat: bool = False            # activation checkpointing per block
    # remat policy: what the per-block checkpoint SAVES (everything else is
    # recomputed in the backward). 'nothing' = full remat (max memory
    # saving, max recompute); 'attn_out' = save the flash-attention outputs
    # (skips recomputing the attention kernel — the most expensive fwd op —
    # while still freeing the big QK/PV intermediates); 'dots' = save every
    # matmul output (least recompute, most memory)
    remat_policy: str = "nothing"
    scan_layers: bool = False      # lax.scan over blocks: compile time O(1)
                                   # in depth, params stacked (L, ...)
    use_pallas_attention: Optional[bool] = None  # None = auto
    loss_chunk_tokens: int = 8192  # chunked LM-head xent (0 = dense logits);
                                   # keeps peak memory O(chunk*V) not O(B*S*V).
                                   # 8192 on v5e: scan overhead amortized to
                                   # parity with the dense head (round-4 sweep)
    # attention under a nontrivial 'seq' mesh axis: 'ulysses' = all_to_all
    # head/seq reshard around a full-sequence kernel (parallel/ulysses.py);
    # 'ring' = K/V rotation with O(S/N) attention memory
    # (parallel/ring_attention.py; no dropout path)
    attention_sp_mode: str = "ulysses"
    # Mixture-of-Experts (expert parallelism over the 'data' mesh axis;
    # moe/sharded_moe.py). 0 experts = dense model. Every moe_layer_freq-th
    # block (the odd ones, GShard-style alternation) swaps its MLP for MoE.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_layer_freq: int = 2
    moe_aux_loss_coef: float = 0.01

    @property
    def head_dim(self):
        return self.n_embd // self.n_head

    @property
    def padded_vocab_size(self):
        from deepspeed_tpu.models.api import pad_to_multiple

        return pad_to_multiple(self.vocab_size, self.pad_vocab_multiple)


# named configs; 1.5B mirrors the reference's 48L/1600h perf config
GPT2_SIZES = {
    "gpt2-125m": dict(n_layer=12, n_embd=768, n_head=12),
    "gpt2-350m": dict(n_layer=24, n_embd=1024, n_head=16),
    "gpt2-760m": dict(n_layer=24, n_embd=1536, n_head=16),
    "gpt2-1.5b": dict(n_layer=48, n_embd=1600, n_head=25),
    "gpt2-4b": dict(n_layer=64, n_embd=2304, n_head=24),
    "gpt2-8b": dict(n_layer=72, n_embd=3072, n_head=24),
    "gpt2-10b": dict(n_layer=50, n_embd=4096, n_head=32),
}


def gpt2_config(name: str, **overrides) -> GPT2Config:
    base = dict(GPT2_SIZES[name])
    base.update(overrides)
    return GPT2Config(**base)


def remat_policy(name: str):
    """Map a GPT2Config.remat_policy name to a jax.checkpoint policy
    (None = save nothing, i.e. classic full remat)."""
    if name in ("nothing", "", None):
        return None
    if name == "attn_out":
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    if name == "dots":
        return jax.checkpoint_policies.dots_saveable
    raise ValueError(f"unknown remat_policy {name!r} "
                     "(expected nothing|attn_out|dots)")


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.config
        B, S, E = x.shape
        # fused QKV projection: one big MXU matmul, sharded over 'model'
        qkv = nn.Dense(3 * E, dtype=cfg.dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, cfg.n_head, cfg.head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        drop_rng = self.make_rng("dropout") if (train and cfg.dropout > 0) else None
        amesh = jax.sharding.get_abstract_mesh()
        ring = (cfg.attention_sp_mode == "ring" and amesh is not None
                and not amesh.empty and amesh.shape.get("seq", 1) > 1)
        if ring:
            # ring sequence parallelism: K/V shards rotate over the 'seq'
            # axis, attention memory stays O(S/N) per device
            # (parallel/ring_attention.py)
            assert drop_rng is None, \
                "attention_sp_mode='ring' has no dropout path"
            from deepspeed_tpu.parallel.ring_attention import (
                _ring_attention_local)

            spec = P("data", "model", "seq", None)
            y = jax.shard_map(
                lambda qq, kk, vv: _ring_attention_local(
                    qq, kk, vv, axis_name="seq", causal=True, scale=None,
                    vary_axes=("data", "model")),
                in_specs=(spec, spec, spec), out_specs=spec,
                axis_names={"data", "model", "seq"})(q, k, v)
        else:
            # Ulysses sequence parallelism (parallel/ulysses.py): with a
            # nontrivial 'seq' axis these constraints flip the sequence dim
            # to full and shard heads over ('model','seq') instead (GSPMD
            # all_to_all) so the attention kernel sees the whole sequence.
            # Every dim names its axes — a partial spec would pin the
            # batch's 'data' and the heads' 'model' sharding to replicated.
            head_sp = P("data", ("model", "seq"), None, None)
            q = mesh_lib.constrain(q, head_sp)
            k = mesh_lib.constrain(k, head_sp)
            v = mesh_lib.constrain(v, head_sp)
            y = scaled_dot_product_attention(
                q, k, v, causal=True, dropout_rng=drop_rng,
                dropout_rate=cfg.dropout if train else 0.0,
                use_pallas=cfg.use_pallas_attention)
            y = mesh_lib.constrain(y, P("data", "model", "seq", None))
        y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
        # marker for remat_policy='attn_out': saving here means the backward
        # re-runs only the (cheap) projections/LN/GeLU, not the attention
        y = checkpoint_name(y, "attn_out")
        y = nn.Dense(E, dtype=cfg.dtype, name="c_proj")(y)
        if train and cfg.dropout > 0:
            y = nn.Dropout(cfg.dropout)(y, deterministic=False)
        return y


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.config
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(h)
        if train and cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=False)
        return h


class Block(nn.Module):
    config: GPT2Config
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.config
        # pre-LN
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_1")(x), train)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_2")(x)
        if self.use_moe:
            from deepspeed_tpu.moe import MoE

            ffn = MoE(num_experts=cfg.moe_num_experts, d_ff=4 * cfg.n_embd,
                      k=cfg.moe_top_k,
                      capacity_factor=cfg.moe_capacity_factor,
                      aux_loss_coef=cfg.moe_aux_loss_coef,
                      dtype=cfg.dtype, name="moe")
        else:
            ffn = MLP(cfg, name="mlp")
        x = x + ffn(h, train)
        # keep activations sharded batch-over-data (and sequence-over-seq
        # under sequence parallelism) as blocks stack
        x = mesh_lib.constrain(x, P("data", "seq", None))
        return x


class GPT2LMHead(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, train: bool = False,
                 return_hidden: bool = False):
        cfg = self.config
        B, S = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab_size, cfg.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd), jnp.float32)
        x = wte.astype(cfg.dtype)[input_ids] + wpe.astype(cfg.dtype)[None, :S]
        if train and cfg.dropout > 0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=False)
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=(2,),
                             policy=remat_policy(cfg.remat_policy))
        if cfg.moe_num_experts:
            # heterogeneous layers (dense/MoE alternation) can't share one
            # scanned body; unrolled loop only
            assert not cfg.scan_layers, \
                "moe_num_experts > 0 requires scan_layers=False"
            for i in range(cfg.n_layer):
                x = block(cfg, name=f"h_{i}",
                          use_moe=(i % cfg.moe_layer_freq
                                   == cfg.moe_layer_freq - 1))(x, train)
        elif cfg.scan_layers:
            # ONE traced block scanned over stacked (L, ...) params: the
            # compiled program is depth-independent (big HLOs from unrolled
            # deep stacks are the main TPU compile-time cost)
            class _Body(nn.Module):
                config: GPT2Config

                @nn.compact
                def __call__(self, carry, _):
                    return block(self.config, name="block")(carry, train), None

            stack = nn.scan(_Body, variable_axes={"params": 0},
                            split_rngs={"params": True, "dropout": True},
                            length=cfg.n_layer)
            x, _ = stack(cfg, name="h")(x, None)
        else:
            for i in range(cfg.n_layer):
                x = block(cfg, name=f"h_{i}")(x, train)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_f")(x)
        if return_hidden:
            # training loss path: the chunked xent applies the tied head
            # itself without materializing full logits
            return x, wte
        # tied LM head: logits against the embedding matrix; the matmul runs
        # at the padded (MXU-aligned) width, then the pad columns drop out
        logits = jnp.einsum("bse,ve->bsv", x, wte.astype(cfg.dtype))
        return logits[..., :cfg.vocab_size]


def gpt2_tp_leaf_spec(joined: str, leaf, stacked: bool = False):
    """Megatron-style TP rule for one GPT-2 param leaf — the single source
    of truth shared by GPT2Model.param_partition_spec and the pipeline
    LayerSpecs (models/gpt2_pipe.py):
    - QKV (c_attn) and MLP-in (c_fc) kernels: shard output dim,
    - attn-out / MLP-out (c_proj) kernels: shard input dim,
    - token embedding (wte): shard vocab dim,
    - everything else replicated.

    joined: '/'-joined param path; stacked: leaf carries a leading (L,)
    scan dim.
    """
    if leaf.ndim == 0:
        return P()
    if "moe" in joined:
        from deepspeed_tpu.moe import moe_leaf_spec

        spec = moe_leaf_spec(joined, leaf)
        if spec is not None:
            return spec
    lead = (None,) if stacked else ()
    if "wte" in joined:
        return P("model", None)
    if "wpe" in joined:
        return P()
    kernel_ndim = leaf.ndim - (1 if stacked else 0)
    if "c_attn" in joined or "c_fc" in joined:
        return P(*lead, None, "model") if kernel_ndim == 2 \
            else P(*lead, "model")
    if "c_proj" in joined:
        return P(*lead, "model", None) if kernel_ndim == 2 \
            else P(*lead)
    return P(*lead) if stacked else P()


class GPT2Model:
    """Engine model contract for GPT-2 (see models/api.py)."""

    def __init__(self, config: GPT2Config):
        self.config = config
        self.module = GPT2LMHead(config)

    def init(self, rng, batch):
        return self.module.init({"params": rng, "dropout": rng},
                                batch["input_ids"], train=False)["params"]

    def loss(self, params, batch, rng, train=True):
        cfg = self.config
        chunk = cfg.loss_chunk_tokens

        def apply(**kw):
            if cfg.moe_num_experts:
                out, col = self.module.apply(
                    {"params": params}, batch["input_ids"], train=train,
                    rngs={"dropout": rng}, mutable=["losses"], **kw)
                from deepspeed_tpu.moe import sum_moe_losses

                return out, sum_moe_losses(col.get("losses", {}))
            return self.module.apply(
                {"params": params}, batch["input_ids"], train=train,
                rngs={"dropout": rng}, **kw), None

        if chunk:
            (hidden, wte), aux = apply(return_hidden=True)
            # next-token LM loss, chunked head (no full-logits residual)
            loss, metrics = chunked_lm_cross_entropy(
                hidden[:, :-1], wte, batch["labels"][:, 1:],
                chunk_tokens=chunk, ignore_index=-100,
                valid_vocab=cfg.vocab_size)
        else:
            logits, aux = apply()
            # next-token LM loss
            loss, metrics = cross_entropy_loss(
                logits[:, :-1], batch["labels"][:, 1:], ignore_index=-100)
        if aux is not None and train:
            # the load-balance regularizer only exists to shape routing
            # gradients; eval loss must stay comparable to dense models
            loss = loss + aux
            metrics = dict(metrics, moe_aux_loss=aux, loss=loss)
        return loss, metrics

    def param_partition_spec(self, params):
        """Megatron-style TP layout over the 'model' axis:
        - QKV and MLP-in kernels: shard output dim,
        - attn-out and MLP-out kernels: shard input dim,
        - token embedding: shard vocab dim,
        - LayerNorms/biases on sharded-output layers: shard to match.
        """
        scanned = self.config.scan_layers

        def spec(path, leaf):
            names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
            joined = "/".join(str(n) for n in names)
            # scan-stacked block params carry a leading (L,) dim
            stacked = scanned and joined.startswith("h/")
            return gpt2_tp_leaf_spec(joined, leaf, stacked)

        return jax.tree_util.tree_map_with_path(spec, params)

    def num_params(self, params):
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    def generate(self, params, input_ids, max_new_tokens, **kw):
        """KV-cache autoregressive decoding (models/generation.py)."""
        from deepspeed_tpu.models.generation import generate

        return generate(self, params, input_ids, max_new_tokens, **kw)
