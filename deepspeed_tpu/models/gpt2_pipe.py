"""GPT-2 as a PipelineModule — the flagship model in pipeline form.

Mirrors the reference's Megatron-GPT2 pipeline configs
(tests/model/Megatron_GPT2/run_perf_test.py:18-84: e.g. 1.5B = 48L/1600h on
16 GPUs with mp2/mp4) expressed as LayerSpecs: embedding -> n_layer blocks ->
final LN -> tied LM head (TiedLayerSpec reusing the embedding matrix, the
reference's canonical tied-weight example, module.py:71-83).
"""
import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.api import cross_entropy_loss
from deepspeed_tpu.models.gpt2 import Block, GPT2Config
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec)


class GPT2Embed(nn.Module):
    """Token + position embeddings; owns the tied wte matrix."""
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        cfg = self.config
        S = input_ids.shape[1]
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab_size, cfg.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd), jnp.float32)
        x = wte.astype(cfg.dtype)[input_ids] + wpe.astype(cfg.dtype)[None, :S]
        if train and cfg.dropout > 0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=False)
        return x


class GPT2LMHead(nn.Module):
    """UNTIED LM head: its own vocab projection matrix (named wte so the
    TP spec and tied-head checkpoints line up shape-wise). The default
    pipeline ties the head to GPT2Embed's wte; this variant exists for
    schedules that cannot host tied weights (zb-h1)."""
    config: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab_size, cfg.n_embd), jnp.float32)
        logits = jnp.einsum("bse,ve->bsv", x, wte.astype(x.dtype))
        return logits[..., :cfg.vocab_size]


class GPT2BlockLayer(nn.Module):
    config: GPT2Config
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        return Block(self.config, use_moe=self.use_moe, name="block")(x, train)


class GPT2FinalNorm(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.LayerNorm(epsilon=self.config.layer_norm_epsilon,
                            dtype=self.config.dtype, name="ln_f")(x)


def _tied_lm_head(module, params, x):
    """forward_fn for the tied head: logits against the shared wte (run at
    the MXU-padded width, pad columns sliced off)."""
    wte = params["wte"]
    logits = jnp.einsum("bse,ve->bsv", x, wte.astype(x.dtype))
    return logits[..., :module.config.vocab_size]


def _tp_spec(params):
    """Per-layer TP layout: defer every leaf to the shared Megatron rule
    (models/gpt2.py:gpt2_tp_leaf_spec — single source of truth for both
    the monolithic and pipeline GPT-2)."""
    from deepspeed_tpu.models.gpt2 import gpt2_tp_leaf_spec

    def spec(path, leaf):
        joined = "/".join(str(getattr(p, "key", p)) for p in path)
        return gpt2_tp_leaf_spec(joined, leaf)

    return jax.tree_util.tree_map_with_path(spec, params)


def gpt2_pipeline_module(config: GPT2Config, partition_method="parameters",
                         activation_checkpoint_interval=0,
                         untied_head=False):
    """Build the LayerSpec pipeline for a GPT-2 config (TP specs included —
    with mesh model>1 this is the 3D PP x TP x DP configuration). MoE
    configs (moe_num_experts > 0) alternate dense/MoE blocks exactly like
    the monolithic GPT2Model; each MoE block's load-balance loss is sown
    stage-locally and the PipelineEngine folds it into the objective.

    untied_head: give the LM head its OWN embedding matrix instead of
    tying it to the input embedding — tied weights block the zb-h1
    pipeline schedule (deferred wgrads vs the cross-stage tied-grad
    reduction), so zero-bubble runs use this variant."""
    if untied_head:
        specs = [LayerSpec(GPT2Embed, config, partition_spec=_tp_spec)]
    else:
        specs = [TiedLayerSpec("embed", GPT2Embed, config,
                               partition_spec=_tp_spec)]
    for i in range(config.n_layer):
        use_moe = bool(config.moe_num_experts) \
            and i % config.moe_layer_freq == config.moe_layer_freq - 1
        specs.append(LayerSpec(GPT2BlockLayer, config, use_moe=use_moe,
                               partition_spec=_tp_spec))
    specs.append(LayerSpec(GPT2FinalNorm, config))
    if untied_head:
        specs.append(LayerSpec(GPT2LMHead, config,
                               partition_spec=_tp_spec))
    else:
        specs.append(TiedLayerSpec("embed", GPT2Embed, config,
                                   forward_fn=_tied_lm_head,
                                   partition_spec=_tp_spec))

    def loss_fn(logits, batch):
        return cross_entropy_loss(logits[:, :-1], batch["labels"][:, 1:],
                                  ignore_index=-100)

    return PipelineModule(
        specs, loss_fn=loss_fn, partition_method=partition_method,
        input_fn=lambda batch: batch["input_ids"],
        activation_checkpoint_interval=activation_checkpoint_interval)
