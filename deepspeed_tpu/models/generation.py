"""Autoregressive generation for GPT-2 with a KV cache.

The reference snapshot has no generation utility (inference arrived in
later DeepSpeed); this is a TPU-first extension: the whole decode loop is
ONE `lax.scan` inside jit (static token count, no host round-trips), the
KV cache is a preallocated (L, B, H, S_max, D) pair updated with
`dynamic_update_slice`, and sampling is counter-based (one PRNG key per
step, folded from a base key).

The decode math consumes the SAME params pytree as GPT2LMHead — stacked
(scan_layers=True) or per-layer — and a parity test pins it to the
training forward (tests/unit/test_generation.py).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _ln(x, p, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _dense(x, p):
    return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)


def _block_params(params, cfg):
    """Yield per-layer param trees; handles scan-stacked layouts."""
    if cfg.scan_layers:
        stacked = params["h"]["block"]
        return [jax.tree_util.tree_map(lambda l, i=i: l[i], stacked)
                for i in range(cfg.n_layer)]
    return [params[f"h_{i}"] for i in range(cfg.n_layer)]


def _attn_decode(x, p, cache_k, cache_v, pos, cfg):
    """One-token attention against the cache. x: (B, 1, E); cache_k/v:
    (B, H, S_max, D); pos: scalar int32 current position."""
    B = x.shape[0]
    H, D = cfg.n_head, cfg.head_dim
    qkv = _dense(x, p["c_attn"])                       # (B, 1, 3E)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(B, 1, H, D).transpose(0, 2, 1, 3)  # (B, H, 1, D)

    q, k, v = heads(q), heads(k), heads(v)
    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, 0, pos, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, 0, pos, 0))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, cache_k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    # mask out the not-yet-written tail of the cache
    valid = jnp.arange(cache_k.shape[2]) <= pos        # (S_max,)
    s = jnp.where(valid[None, None, None, :], s, -1e30)
    probs = jax.nn.softmax(s, axis=-1).astype(x.dtype)
    y = jnp.einsum("bhqk,bhkd->bhqd", probs, cache_v)  # (B, H, 1, D)
    y = y.transpose(0, 2, 1, 3).reshape(B, 1, cfg.n_embd)
    return _dense(y, p["c_proj"]), cache_k, cache_v


def _block_decode(x, bp, ck, cv, pos, cfg):
    a, ck, cv = _attn_decode(
        _ln(x, bp["ln_1"], cfg.layer_norm_epsilon), bp["attn"], ck, cv,
        pos, cfg)
    x = x + a
    h = _ln(x, bp["ln_2"], cfg.layer_norm_epsilon)
    mp = bp["mlp"]
    h = jax.nn.gelu(_dense(h, mp["c_fc"]), approximate=True)
    x = x + _dense(h, mp["c_proj"])
    return x, ck, cv


def _forward_token(params, cfg, token, pos, caches_k, caches_v):
    """Embed one token, run all blocks against the cache, return logits.
    token: (B,) int32; caches: (L, B, H, S_max, D)."""
    wte = params["wte"]
    wpe = params["wpe"]
    x = wte.astype(cfg.dtype)[token][:, None, :] \
        + wpe.astype(cfg.dtype)[pos][None, None, :]    # (B, 1, E)
    blocks = _block_params(params, cfg)
    new_k, new_v = [], []
    for i, bp in enumerate(blocks):
        x, ck, cv = _block_decode(x, bp, caches_k[i], caches_v[i], pos, cfg)
        new_k.append(ck)
        new_v.append(cv)
    x = _ln(x, params["ln_f"], cfg.layer_norm_epsilon)
    logits = jnp.einsum("bse,ve->bsv", x, wte.astype(cfg.dtype))
    return logits[:, 0].astype(jnp.float32), \
        jnp.stack(new_k), jnp.stack(new_v)


def _sample(logits, key, temperature, top_k):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k and top_k < logits.shape[-1]:
        # top_k >= vocab filters nothing; clamping keeps the arg safe
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(model, params, input_ids, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             rng=None):
    """Generate `max_new_tokens` continuations. input_ids: (B, S0) int.
    temperature 0 = greedy. Returns (B, S0 + max_new_tokens) int32.

    Prefill runs positions one at a time through the same jitted scan as
    decode (simple and cache-exact; for long prompts a batched prefill is
    the obvious optimization).
    """
    cfg = model.config
    assert not cfg.moe_num_experts, \
        "generate() does not support MoE configs yet (dense blocks only)"
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S0 = input_ids.shape
    S_max = S0 + max_new_tokens
    assert S_max <= cfg.n_positions, \
        f"{S_max} exceeds n_positions={cfg.n_positions}"
    L, H, D = cfg.n_layer, cfg.n_head, cfg.head_dim
    caches_k = jnp.zeros((L, B, H, S_max, D), cfg.dtype)
    caches_v = jnp.zeros((L, B, H, S_max, D), cfg.dtype)
    key = rng if rng is not None else jax.random.PRNGKey(0)

    # cfg is a frozen (hashable) dataclass, so the decode program caches
    # per (config, shapes, sampling) — repeat generate() calls reuse the
    # compiled scan instead of re-tracing a fresh closure
    run = _decode_fn(cfg, S0, S_max, float(temperature), int(top_k or 0))
    out = run(params, input_ids, caches_k, caches_v, key)
    seq = jnp.concatenate([input_ids[:, :1], jnp.transpose(out)], axis=1)
    return np.asarray(seq)


@functools.lru_cache(maxsize=32)
def _decode_fn(cfg, S0, S_max, temperature, top_k):
    def run(params, tokens_in, caches_k, caches_v, key):
        def step(carry, pos):
            tok, ck, cv = carry
            logits, ck, cv = _forward_token(params, cfg, tok, pos, ck, cv)
            nxt = _sample(logits, jax.random.fold_in(key, pos),
                          temperature, top_k)
            # while still inside the prompt, emit the prompt token
            in_prompt = pos + 1 < S0
            nxt = jnp.where(in_prompt,
                            tokens_in[:, jnp.minimum(pos + 1, S0 - 1)], nxt)
            return (nxt, ck, cv), nxt

        (_, _, _), out = jax.lax.scan(
            step, (tokens_in[:, 0], caches_k, caches_v),
            jnp.arange(S_max - 1))
        return out  # (S_max-1, B)

    return jax.jit(run)
