"""Autoregressive generation for GPT-2 with a KV cache.

The reference snapshot has no generation utility (inference arrived in
later DeepSpeed); this is a TPU-first extension: the whole decode loop is
ONE `lax.scan` inside jit (static token count, no host round-trips), the
KV cache is a preallocated (L, B, H, S_max, D) pair updated with
`dynamic_update_slice`, and sampling is counter-based (one PRNG key per
step, folded from a base key).

The decode math consumes the SAME params pytree as GPT2LMHead — stacked
(scan_layers=True) or per-layer — and a parity test pins it to the
training forward (tests/unit/test_generation.py).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _ln(x, p, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _dense(x, p):
    return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)


def _block_params(params, cfg):
    """Yield per-layer param trees; handles scan-stacked layouts."""
    if cfg.scan_layers:
        stacked = params["h"]["block"]
        return [jax.tree_util.tree_map(lambda l, i=i: l[i], stacked)
                for i in range(cfg.n_layer)]
    return [params[f"h_{i}"] for i in range(cfg.n_layer)]


def _split_heads(t, B, T, H, D):
    return t.reshape(B, T, H, D).transpose(0, 2, 1, 3)  # (B, H, T, D)


def _attn_core(q, keys, values, valid, p, out_dtype):
    """Masked attention shared by every decode surface: the contiguous
    KV cache here, the causal prefill, and the serving engine's paged
    pool (deepspeed_tpu/serving/engine.py).  q/keys/values: (B, H, Q, D)
    and (B, H, K, D); ``valid`` broadcasts against the (B, H, Q, K)
    score tensor.  Scores accumulate in f32 and masked positions score
    -1e30, which softmax turns into EXACT zeros — so a path that gathers
    a wider, padded key view (the paged pool) produces bit-identical
    outputs to one that attends a tight contiguous cache."""
    B, H, Q, D = q.shape
    s = jnp.einsum("bhqd,bhkd->bhqk", q, keys,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    s = jnp.where(valid, s, -1e30)
    probs = jax.nn.softmax(s, axis=-1).astype(out_dtype)
    y = jnp.einsum("bhqk,bhkd->bhqd", probs, values)   # (B, H, Q, D)
    y = y.transpose(0, 2, 1, 3).reshape(B, Q, H * D)
    return _dense(y, p["c_proj"])


def _attn_decode(x, p, cache_k, cache_v, pos, cfg):
    """One-token attention against the cache. x: (B, 1, E); cache_k/v:
    (B, H, S_max, D); pos: scalar int32 current position."""
    B = x.shape[0]
    H, D = cfg.n_head, cfg.head_dim
    qkv = _dense(x, p["c_attn"])                       # (B, 1, 3E)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q, k, v = (_split_heads(t, B, 1, H, D) for t in (q, k, v))
    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, 0, pos, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, 0, pos, 0))
    # mask out the not-yet-written tail of the cache
    valid = jnp.arange(cache_k.shape[2]) <= pos        # (S_max,)
    out = _attn_core(q, cache_k, cache_v, valid[None, None, None, :], p,
                     x.dtype)
    return out, cache_k, cache_v


def _moe_ffn(x, mp, cfg):
    """Params-level MoE FFN for generation — the same dense top-k gating +
    stacked-expert einsums the training layer runs (moe/sharded_moe.py),
    deterministic (no jitter), gated with cfg.moe_capacity_factor exactly
    like the train=False forward (GPT-2's blocks do not set an eval
    capacity factor). x: (B, T, M).

    Capacity semantics: prefill gates the whole prompt per batch row
    exactly like the training forward; decode gates ONE token per step, so
    a decoded token never competes with its predecessors for expert slots
    (the min_capacity floor guarantees it a slot). Identical to the
    training forward whenever nothing drops; under capacity pressure
    decode keeps tokens the training pass would drop."""
    from deepspeed_tpu.moe.sharded_moe import top_k_gating

    dtype = x.dtype
    logits = x.astype(jnp.float32) @ mp["router"]["kernel"]    # (B, T, E)
    # single-token decode groups occupy at most one slot per chosen expert:
    # capacity=k is exact, and skips the min_capacity=4 floor that would
    # oversize the expert GEMMs 2-4x per generated token
    cap = cfg.moe_top_k if x.shape[1] == 1 else None
    combine, dispatch, _, _ = top_k_gating(
        logits, k=cfg.moe_top_k, capacity=cap,
        capacity_factor=cfg.moe_capacity_factor)
    ex = mp["experts"]
    E = cfg.moe_num_experts
    d = jnp.einsum("gsec,gsm->egcm", dispatch.astype(dtype), x)
    B, C = d.shape[1], d.shape[2]
    d = d.reshape(E, B * C, -1)
    h = jnp.einsum("enm,emf->enf", d, ex["w_in"].astype(dtype)) \
        + ex["b_in"].astype(dtype)[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    y = jnp.einsum("enf,efm->enm", h, ex["w_out"].astype(dtype)) \
        + ex["b_out"].astype(dtype)[:, None, :]
    y = y.reshape(E, B, C, -1)
    # dropped tokens get zero here and ride the residual, like training
    return jnp.einsum("egcm,gsec->gsm", y, combine.astype(dtype))


def _ffn(x, bp, cfg):
    """Dense-MLP or MoE feed-forward, keyed on the block's param names."""
    if "moe" in bp:
        return _moe_ffn(x, bp["moe"], cfg)
    mp = bp["mlp"]
    h = jax.nn.gelu(_dense(x, mp["c_fc"]), approximate=True)
    return _dense(h, mp["c_proj"])


def _block_decode(x, bp, ck, cv, pos, cfg):
    a, ck, cv = _attn_decode(
        _ln(x, bp["ln_1"], cfg.layer_norm_epsilon), bp["attn"], ck, cv,
        pos, cfg)
    x = x + a
    h = _ln(x, bp["ln_2"], cfg.layer_norm_epsilon)
    x = x + _ffn(h, bp, cfg)
    return x, ck, cv


def _attn_prefill(x, p, cfg):
    """Causal attention over the whole prompt; returns (out, k, v) with
    k/v shaped (B, H, S0, D) for cache seeding."""
    B, S, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    qkv = _dense(x, p["c_attn"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q, k, v = (_split_heads(t, B, S, H, D) for t in (q, k, v))
    mask = jnp.tril(jnp.ones((S, S), bool))
    return _attn_core(q, k, v, mask[None, None], p, x.dtype), k, v


def _lm_logits(params, cfg, xe):
    """Tied LM head over (B, E) final hidden states, MXU-alignment pad
    columns dropped so sampling never picks a pad id.  Shared by the
    prompt prefill, single-token decode, and the serving engine's paged
    decode/prefill (deepspeed_tpu/serving/engine.py) — one head, one
    dtype policy, bit-identical logits across every decode surface."""
    logits = jnp.einsum("be,ve->bv", xe, params["wte"].astype(cfg.dtype))
    return logits[:, :cfg.vocab_size].astype(jnp.float32)


def _prefill(params, cfg, tokens):
    """One batched forward over the (B, S0) prompt: returns the logits at
    the last prompt position and per-layer K/V for cache seeding."""
    S0 = tokens.shape[1]
    x = params["wte"].astype(cfg.dtype)[tokens] \
        + params["wpe"].astype(cfg.dtype)[None, :S0]
    ks, vs = [], []
    for bp in _block_params(params, cfg):
        a, k, v = _attn_prefill(
            _ln(x, bp["ln_1"], cfg.layer_norm_epsilon), bp["attn"], cfg)
        x = x + a
        h = _ln(x, bp["ln_2"], cfg.layer_norm_epsilon)
        x = x + _ffn(h, bp, cfg)
        ks.append(k)
        vs.append(v)
    x = _ln(x, params["ln_f"], cfg.layer_norm_epsilon)
    return _lm_logits(params, cfg, x[:, -1]), jnp.stack(ks), jnp.stack(vs)


def _forward_token(params, cfg, token, pos, caches_k, caches_v):
    """Embed one token, run all blocks against the cache, return logits.
    token: (B,) int32; caches: (L, B, H, S_max, D)."""
    wte = params["wte"]
    wpe = params["wpe"]
    x = wte.astype(cfg.dtype)[token][:, None, :] \
        + wpe.astype(cfg.dtype)[pos][None, None, :]    # (B, 1, E)
    blocks = _block_params(params, cfg)
    new_k, new_v = [], []
    for i, bp in enumerate(blocks):
        x, ck, cv = _block_decode(x, bp, caches_k[i], caches_v[i], pos, cfg)
        new_k.append(ck)
        new_v.append(cv)
    x = _ln(x, params["ln_f"], cfg.layer_norm_epsilon)
    return _lm_logits(params, cfg, x[:, 0]), \
        jnp.stack(new_k), jnp.stack(new_v)


def _sample(logits, key, temperature, top_k, top_p=0.0):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    use_k = top_k and top_k < logits.shape[-1]
    use_p = top_p and top_p < 1.0
    if use_k or use_p:
        # ONE descending sort serves both filters (this runs per decode
        # step inside the scan — no reason to sort twice)
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        if use_k:
            # top_k >= vocab filters nothing; clamping keeps the arg safe
            logits = jnp.where(
                logits < sorted_desc[:, top_k - 1][:, None], -1e30, logits)
        if use_p:
            # nucleus sampling: keep the smallest prefix of the sorted
            # distribution whose mass reaches top_p (the top token always
            # survives — its EXCLUSIVE cumulative mass is 0 < top_p).
            # With top_k also active, masked tokens carry ~0 probability
            # here, so the nucleus is computed within the top-k set.
            if use_k:
                sorted_desc = jnp.where(
                    sorted_desc < sorted_desc[:, top_k - 1][:, None],
                    -1e30, sorted_desc)
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            exclusive = jnp.cumsum(probs, axis=-1) - probs
            keep = exclusive < top_p
            cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                             axis=-1, keepdims=True)
            logits = jnp.where(logits >= cutoff, logits, -1e30)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(model, params, input_ids, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: float = 0.0, rng=None, num_beams: int = 1,
             eos_token_id: Optional[int] = None):
    """Generate `max_new_tokens` continuations. input_ids: (B, S0) int.
    temperature 0 = greedy; top_k / top_p (nucleus) filter the sampling
    distribution and compose (top_k first); num_beams > 1 switches to
    beam search (deterministic — incompatible with sampling). Returns
    (B, S0 + max_new_tokens) int32.

    eos_token_id: rows that emit it stop — every later position repeats
    the eos id. The program stays fixed-shape (the scan always runs
    max_new_tokens steps; finished rows just carry eos), which is the
    TPU-friendly formulation of early stopping.

    The prompt is consumed by ONE batched causal forward (prefill) that
    seeds the KV cache; decode then scans one token at a time.
    """
    cfg = model.config
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if max_new_tokens <= 0:
        return np.asarray(input_ids)
    # a sign/range bug here would otherwise mask EVERY logit and emit
    # plausible-shaped garbage (token 0 forever) with no error
    assert 0.0 <= (top_p or 0.0) <= 1.0, f"top_p must be in [0, 1]: {top_p}"
    assert top_k is None or top_k >= 0, f"top_k must be >= 0: {top_k}"
    assert temperature >= 0.0, f"temperature must be >= 0: {temperature}"
    if num_beams > 1:
        assert temperature == 0.0 and not top_k and not top_p \
            and rng is None, \
            "beam search is deterministic; drop temperature/top_k/top_p/rng"
        assert eos_token_id is None, \
            "beam search is fixed-length; eos_token_id is not supported " \
            "with num_beams > 1 (length-normalized eos-aware scoring is a " \
            "different search)"
        return generate_beam(model, params, input_ids, max_new_tokens,
                             num_beams=num_beams)
    B, S0 = input_ids.shape
    S_max = S0 + max_new_tokens
    assert S_max <= cfg.n_positions, \
        f"{S_max} exceeds n_positions={cfg.n_positions}"
    L, H, D = cfg.n_layer, cfg.n_head, cfg.head_dim
    caches_k = jnp.zeros((L, B, H, S_max, D), cfg.dtype)
    caches_v = jnp.zeros((L, B, H, S_max, D), cfg.dtype)
    key = rng if rng is not None else jax.random.PRNGKey(0)

    # cfg is a frozen (hashable) dataclass, so the decode program caches
    # per (config, shapes, sampling) — repeat generate() calls reuse the
    # compiled scan instead of re-tracing a fresh closure
    run = _decode_fn(cfg, S0, S_max, float(temperature), int(top_k or 0),
                     float(top_p or 0.0),
                     int(eos_token_id) if eos_token_id is not None else -1)
    out = run(params, input_ids, caches_k, caches_v, key)
    seq = jnp.concatenate([input_ids, jnp.transpose(out)], axis=1)
    return np.asarray(seq)


def generate_beam(model, params, input_ids, max_new_tokens: int,
                  num_beams: int = 4):
    """Beam-search decode: return the highest-log-probability continuation
    among `num_beams` beams per batch row. input_ids: (B, S0) int; returns
    (B, S0 + max_new_tokens) int32.

    Fixed-length search (no EOS concept in this API), whole loop in ONE
    jitted lax.scan: beams live as a (B*W) batch sharing the KV-cache
    machinery of greedy decode, and each step's top-W reselection reorders
    the caches by gathering along the beam dim. num_beams=1 is exactly
    greedy decode."""
    cfg = model.config
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if max_new_tokens <= 0:
        return np.asarray(input_ids)
    B, S0 = input_ids.shape
    W = int(num_beams)
    assert W >= 1
    assert W <= model.config.vocab_size, \
        f"num_beams={W} exceeds vocab_size={model.config.vocab_size}; " \
        f"top-k reselection cannot produce more beams than tokens"
    S_max = S0 + max_new_tokens
    assert S_max <= cfg.n_positions, \
        f"{S_max} exceeds n_positions={cfg.n_positions}"
    run = _beam_fn(cfg, S0, S_max, W)
    seq = run(params, input_ids)
    return np.asarray(seq)


@functools.lru_cache(maxsize=32)
def _beam_fn(cfg, S0, S_max, W):
    T = S_max - S0

    def run(params, tokens_in):
        B = tokens_in.shape[0]
        logits0, pk, pv = _prefill(params, cfg, tokens_in)   # (B,V), (L,B,H,S0,D)
        logp0 = jax.nn.log_softmax(logits0, axis=-1)         # (B, V)
        V = logp0.shape[-1]
        # seed beams with the prompt's top-W continuations
        scores, first = jax.lax.top_k(logp0, W)              # (B, W)
        # tile caches to (L, B*W, H, S_max, D), beam-major within batch
        def tile(c):
            c = jnp.pad(c, ((0, 0), (0, 0), (0, 0), (0, S_max - S0), (0, 0)))
            c = jnp.repeat(c, W, axis=1)
            return c
        ck, cv = tile(pk), tile(pv)
        toks = jnp.zeros((B, W, T), jnp.int32)
        toks = toks.at[:, :, 0].set(first)
        flat = lambda x: x.reshape(B * W)

        def step(carry, pos):
            toks, scores, ck, cv, prev = carry
            logits, ck, cv = _forward_token(params, cfg, flat(prev), pos,
                                            ck, cv)          # (B*W, V)
            logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, W, V)
            cand = scores[:, :, None] + logp                 # (B, W, V)
            scores, idx = jax.lax.top_k(cand.reshape(B, W * V), W)
            parent = idx // V                                # (B, W)
            nxt = (idx % V).astype(jnp.int32)
            # reorder beam state by parent: tokens-so-far and KV caches
            toks = jnp.take_along_axis(toks, parent[:, :, None], axis=1)
            toks = toks.at[:, :, pos - S0 + 1].set(nxt)
            gather = (jnp.arange(B)[:, None] * W + parent).reshape(-1)
            ck = jnp.take(ck, gather, axis=1)
            cv = jnp.take(cv, gather, axis=1)
            return (toks, scores, ck, cv, nxt), None

        if T > 1:
            (toks, scores, _, _, _), _ = jax.lax.scan(
                step, (toks, scores, ck, cv, first),
                jnp.arange(S0, S_max - 1))
        best = jnp.argmax(scores, axis=-1)                   # (B,)
        out = jnp.take_along_axis(
            toks, best[:, None, None], axis=1)[:, 0]         # (B, T)
        return jnp.concatenate([tokens_in, out], axis=1)

    return jax.jit(run)


@functools.lru_cache(maxsize=32)
def _decode_fn(cfg, S0, S_max, temperature, top_k, top_p=0.0, eos=-1):
    def run(params, tokens_in, caches_k, caches_v, key):
        # batched prefill over the prompt seeds positions [0, S0)
        logits0, pk, pv = _prefill(params, cfg, tokens_in)
        caches_k = jax.lax.dynamic_update_slice(
            caches_k, pk, (0, 0, 0, 0, 0))
        caches_v = jax.lax.dynamic_update_slice(
            caches_v, pv, (0, 0, 0, 0, 0))
        first = _sample(logits0, jax.random.fold_in(key, S0 - 1),
                        temperature, top_k, top_p)
        done0 = first == eos if eos >= 0 else jnp.zeros_like(first, bool)

        def step(carry, pos):
            tok, done, ck, cv = carry
            logits, ck, cv = _forward_token(params, cfg, tok, pos, ck, cv)
            nxt = _sample(logits, jax.random.fold_in(key, pos),
                          temperature, top_k, top_p)
            if eos >= 0:
                # finished rows keep emitting eos; the cache still advances
                # (harmless — nothing attends past a row's eos in the
                # returned sequence)
                nxt = jnp.where(done, jnp.int32(eos), nxt)
                done = done | (nxt == eos)
            return (nxt, done, ck, cv), nxt

        # decode steps consume tokens at positions S0 .. S_max-2
        (_, _, _, _), rest = jax.lax.scan(
            step, (first, done0, caches_k, caches_v),
            jnp.arange(S0, S_max - 1))
        return jnp.concatenate([first[None], rest], axis=0)  # (new, B)

    return jax.jit(run)
