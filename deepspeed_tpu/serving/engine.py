"""Continuous-batching inference engine over the paged KV pool.

The compiled surface is deliberately tiny and FIXED-SHAPE:

- ONE decode jit over all ``max_slots`` lanes, with per-slot page
  tables, positions and an active mask — requests joining, leaving,
  finishing or being evicted only change ARRAY CONTENTS, never shapes,
  so steady-state serving triggers zero recompilations (pinned by the
  CompilationCounter acceptance test);
- a small family of length-bucketed chunked-prefill jits (one per
  power-of-two bucket x final/non-final), so a long prompt is absorbed
  ``prefill_chunk`` tokens per step between decode steps and never
  stalls running decodes.

Both programs DONATE the pool tensors (kv_cache.PoolTensors) and update
them in place: steady-state decode is allocation-free, and the HLO
contracts in tests/unit/test_hlo_contracts.py pin the decode jit to
"host-transfer-free + pool donated + (sharded) zero collective bytes".

The decode math reuses models/generation.py internals (``_attn_core``,
``_ln``, ``_ffn``, ``_sample``) over a gathered page view, and the exact
-1e30 masking makes greedy tokens bit-identical to single-sequence
``generate()`` — under staggered arrivals, eviction and cancellation
churn (the parity acceptance test).

Sharding: with ``shards > 1`` the decode program runs under a shard_map
over the slot axis — slots, page tables and the block pool are all split
on the same mesh axis, params replicated.  Every decode operator is
batch-uniform in the slot dimension, so the compiled program contains NO
collectives (runtime/comm_accounting.serving_decode_collectives prices
this placement against the tensor-parallel alternative).

Reliability (serving/reliability.py): per-request deadlines and work
budgets enforced at step boundaries, an SLO-aware predicted-TTFT
admission gate with lowest-priority-first load shedding, graceful
``drain()`` (SIGTERM via ``install_preemption_handler``), a per-step
request journal driving ``recover()`` (bit-identical greedy
continuations after a host crash), and per-request poison quarantine —
non-finite logits abort only the offending lane, detected on the same
batched fetch as the sampled tokens.  None of it touches the compiled
surface's contracts: still ONE decode jit, zero recompiles, zero
collectives.
"""
import functools
import itertools
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.generation import (_attn_core, _block_params,
                                             _dense, _ffn, _lm_logits,
                                             _ln, _sample, _split_heads)
from deepspeed_tpu.runtime.quantization import (dequantize_rows,
                                                quantize_rows)
from deepspeed_tpu.runtime.resilience import chaos
from deepspeed_tpu.serving.kv_cache import (TRASH_BLOCK, PagedKVPool,
                                            PoolTensors)
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.reliability import (ABORT_BUDGET, ABORT_EXPIRED,
                                               ABORT_POISONED, ABORT_SHED,
                                               Reliability, ReliabilityConfig,
                                               RequestJournal)
from deepspeed_tpu.serving.scheduler import (Request, RequestState,
                                             Scheduler)
from deepspeed_tpu.serving.sparse_context import (SparseContext,
                                                  _policy_layout)
from deepspeed_tpu.utils.jax_compat import ensure_compat
from deepspeed_tpu.utils.logging import logger

ensure_compat()

_MIN_BUCKET = 4


def _slot_key(seed, pos):
    """Per-request sampling key: a function of (request seed, absolute
    position) only — the token stream of a sampled request does not
    depend on which slot or step it lands in."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), pos)


def _pool_write(pool, scales, l, blk, off, rows, quantized):
    """Scatter one K or V row per (lane, head) into the block pool.
    rows: (N, H, D); blk/off: (N,) local block id / in-block offset.
    Masked lanes arrive with blk == TRASH_BLOCK and land in the trash
    block — the scatter itself is always dense."""
    N, H, D = rows.shape
    if quantized:
        q, s = quantize_rows(rows.reshape(N * H, D), block_size=D)
        pool = pool.at[l, blk, :, off, :].set(q.reshape(N, H, D))
        scales = scales.at[l, blk, :, off].set(
            s.reshape(N, H).astype(jnp.float32))
    else:
        pool = pool.at[l, blk, :, off, :].set(rows.astype(pool.dtype))
    return pool, scales


def _pool_view(pool, scales, l, tables, quantized, out_dtype):
    """Gather per-sequence page views back to contiguous position order:
    (B, W) tables over (L, NB, H, bs, D) pool -> (B, H, W*bs, D).  View
    position j IS absolute sequence position j, so the attention mask of
    the contiguous cache applies unchanged."""
    B, W = tables.shape
    _, _, H, bs, D = pool.shape
    g = pool[l][tables.reshape(-1)]
    g = g.reshape(B, W, H, bs, D).transpose(0, 2, 1, 3, 4) \
         .reshape(B, H, W * bs, D)
    if not quantized:
        return g
    s = scales[l][tables.reshape(-1)].reshape(B, W, H, bs) \
        .transpose(0, 2, 1, 3).reshape(B * H * W * bs, 1)
    return dequantize_rows(g.reshape(B * H * W * bs, D), s, D,
                           out_dtype).reshape(B, H, W * bs, D)


def _paged_forward(params, cfg, pools, tables, pos, maxpos, blk, off, x,
                   quantized, sparse=None, allowed=None):
    """Shared transformer pass of decode and chunked prefill: per layer,
    write this step's K/V rows into the pool, gather the page view, and
    run the SAME attention core the contiguous cache uses.  x: (B, T, E)
    with T == number of query tokens per lane; pos: (B*T?,) absolute
    positions of the query tokens, flattened to match blk/off.

    ``maxpos``: (B,) last VALID absolute position per lane.  View
    positions beyond it have their VALUES zeroed before the attention
    einsum: their softmax weight is already exactly 0 (the -1e30 score
    mask), but ``0 * NaN = NaN`` — without the value mask, stale
    non-finite garbage in a reused/trash block (a quarantined request's
    poisoned writes) would leak into every lane that merely gathers the
    block at a masked position.  For finite garbage the zeroing is
    bit-neutral (0 * garbage was already exactly +/-0), so the parity
    contract is untouched while per-request fault ISOLATION becomes
    unconditional.

    ``sparse`` (serving/sparse_context.py): ``(stables, sbase)`` — a
    (B, K) physical-page gather table plus the absolute view position of
    each page's first token.  The GATHER then reads only K active pages
    per lane while WRITES keep addressing the full page table through
    ``blk``/``off``; padded/expired entries carry the sentinel position
    (>= every valid pos/maxpos), so both masks reject them exactly like
    dense trash padding.  Attention is permutation-invariant over keys,
    so view order no longer being position order changes nothing — the
    masks are built from the TRUE absolute positions.  ``allowed``
    (B?, T, K*bs) further restricts each query to its OWN policy blocks
    (chunked prefill gathers the chunk's union set)."""
    pk, pv, ksc, vsc = pools
    B, T, _ = x.shape
    H, D = cfg.n_head, cfg.head_dim
    W = tables.shape[1]
    bs = pk.shape[3]
    if sparse is None:
        gtables = tables
        validj = (jnp.arange(W * bs)[None, :]
                  <= pos.reshape(B, T)[:, :, None]) \
            .reshape(B, T, W * bs)[:, None]              # (B, 1, T, K)
        validk = (jnp.arange(W * bs)[None, :] <= maxpos[:, None]) \
            [:, None, :, None]                           # (B, 1, K, 1)
    else:
        gtables, sbase = sparse
        K = gtables.shape[1]
        view_pos = (sbase[:, :, None] + jnp.arange(bs)[None, None, :]) \
            .reshape(B, K * bs)                          # (B, K*bs)
        validj = view_pos[:, None, :] <= pos.reshape(B, T)[:, :, None]
        if allowed is not None:
            validj = validj & allowed
        validj = validj[:, None]                         # (B, 1, T, K*bs)
        validk = (view_pos <= maxpos[:, None])[:, None, :, None]
    for l, bp in enumerate(_block_params(params, cfg)):
        h = _ln(x, bp["ln_1"], cfg.layer_norm_epsilon)
        qkv = _dense(h, bp["attn"]["c_attn"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = _split_heads(q, B, T, H, D)                  # (B, H, T, D)
        kt = k.reshape(B * T, H, D)
        vt = v.reshape(B * T, H, D)
        pk, ksc = _pool_write(pk, ksc, l, blk, off, kt, quantized)
        pv, vsc = _pool_write(pv, vsc, l, blk, off, vt, quantized)
        kview = _pool_view(pk, ksc, l, gtables, quantized, x.dtype)
        vview = _pool_view(pv, vsc, l, gtables, quantized, x.dtype)
        kview = jnp.where(validk, kview, 0)
        vview = jnp.where(validk, vview, 0)
        a = _attn_core(q, kview, vview, validj, bp["attn"], x.dtype)
        x = x + a
        x = x + _ffn(_ln(x, bp["ln_2"], cfg.layer_norm_epsilon), bp, cfg)
    x = _ln(x, params["ln_f"], cfg.layer_norm_epsilon)
    return x, (pk, pv, ksc, vsc)


def _pick_next(logits, seeds, pos, temperature, top_k, top_p):
    """Greedy argmax (the bit-parity path) or per-lane sampled token."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    keys = jax.vmap(_slot_key)(seeds, pos)
    return jax.vmap(
        lambda lg, k: _sample(lg[None], k, temperature, top_k, top_p)[0]
    )(logits, keys).astype(jnp.int32)


def _shard_wrap(core, mesh, axis_name, n_pool, in_streams, n_out_streams):
    """jit(shard_map(core)) with pool tensors split on the block axis,
    per-slot streams split on the slot axis and params replicated; plain
    jit when mesh is None.  ``in_streams``/``n_out_streams`` mark which
    trailing args / leading-after-pool outputs carry the slot axis."""
    donate = tuple(range(1, 1 + n_pool))
    if mesh is None:
        return jax.jit(core, donate_argnums=donate)
    from jax.sharding import PartitionSpec as P

    pool_spec = P(None, axis_name)
    in_specs = (P(),) + (pool_spec,) * n_pool + tuple(
        P(axis_name) if s else P() for s in in_streams)
    out_specs = (pool_spec,) * n_pool + (P(axis_name),) * n_out_streams
    sm = jax.shard_map(core, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)
    return jax.jit(sm, donate_argnums=donate)


@functools.lru_cache(maxsize=64)
def _make_decode_step(cfg, W, bs, quantized, temperature, top_k, top_p,
                      mesh, axis_name):
    """ONE fixed-shape decode program over every (local) slot lane.

    ``poison`` is a per-lane additive fault-injection stream (0.0 in
    production — bit-neutral on the embedding sum): chaos writes NaN
    into one lane to model a numeric blow-up, and the per-lane
    ``finite`` output (non-finite logits detector) rides the same
    batched fetch as the sampled tokens — per-request quarantine costs
    zero extra host syncs and zero recompiles."""
    def run(params, *args):
        pools, (tables, pos, tok, active, seeds, poison) = \
            (args[:4] if quantized else args[:2] + (None, None)), args[-6:]
        S = tok.shape[0]
        x = params["wte"].astype(cfg.dtype)[tok][:, None, :] \
            + params["wpe"].astype(cfg.dtype)[pos][:, None, :]   # (S, 1, E)
        x = x + poison.astype(cfg.dtype)[:, None, None]
        blk = jnp.where(active, tables[jnp.arange(S), pos // bs],
                        TRASH_BLOCK)
        off = pos % bs
        x, pools = _paged_forward(params, cfg, pools, tables, pos, pos,
                                  blk, off, x, quantized)
        logits = _lm_logits(params, cfg, x[:, 0])
        finite = jnp.isfinite(logits).all(axis=-1)
        nxt = _pick_next(logits, seeds, pos, temperature, top_k, top_p)
        nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
        out = pools[:4] if quantized else pools[:2]
        return (*out, nxt, finite)

    n_pool = 4 if quantized else 2
    return _shard_wrap(run, mesh, axis_name, n_pool,
                       in_streams=(True,) * 6, n_out_streams=2)


@functools.lru_cache(maxsize=64)
def _make_spec_verify(cfg, K, W, bs, quantized, mesh, axis_name):
    """Self-speculative draft-verify: K+1 query tokens per lane — the
    current token plus K drafted — scored in ONE fixed-shape batched
    step.  The host accepts the longest prefix of drafts matching the
    program's own argmax continuations, plus one bonus token; that is
    bit-identical to step-by-step greedy BY CONSTRUCTION, because output
    i is only ever consumed when drafts 1..i already equal the true
    greedy tokens — at which point the KV rows written for them are
    exactly what sequential decode would have written, and rejected
    positions are overwritten by the next dispatch before any query can
    attend them unmasked.  Greedy-only (the arming gate enforces
    temperature == 0), so no sampling seeds enter the program.

    ``nvalid`` (per lane) bounds the query positions that may write and
    that feed the finiteness detector: a lane within K tokens of its
    token budget masks the surplus positions to the trash block, so
    near-capacity lanes neither write past their page table nor trip
    false poison quarantines on clamped-gather garbage."""
    def run(params, *args):
        pools = args[:4] if quantized else args[:2] + (None, None)
        tables, pos, toks, nvalid, active, poison = args[-6:]
        S, T = toks.shape
        posns = pos[:, None] + jnp.arange(T)[None, :]          # (S, T)
        x = params["wte"].astype(cfg.dtype)[toks] \
            + params["wpe"].astype(cfg.dtype)[
                jnp.minimum(posns, cfg.n_positions - 1)]       # (S, T, E)
        x = x + poison.astype(cfg.dtype)[:, None, None]
        valid_q = jnp.arange(T)[None, :] < nvalid[:, None]     # (S, T)
        writable = active[:, None] & valid_q & (posns < W * bs)
        blk = jnp.where(
            writable,
            tables[jnp.arange(S)[:, None],
                   jnp.minimum(posns // bs, W - 1)],
            TRASH_BLOCK)
        off = posns % bs
        maxpos = pos + nvalid - 1                              # (S,)
        x, pools = _paged_forward(params, cfg, pools, tables, posns,
                                  maxpos, blk.reshape(-1),
                                  off.reshape(-1), x, quantized)
        logits = _lm_logits(params, cfg,
                            x.reshape(S * T, -1)).reshape(S, T, -1)
        finite = jnp.where(valid_q, jnp.isfinite(logits).all(-1),
                           True).all(axis=1)                   # (S,)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)    # (S, T)
        nxt = jnp.where(active[:, None], nxt, 0)
        out = pools[:4] if quantized else pools[:2]
        return (*out, nxt, finite)

    n_pool = 4 if quantized else 2
    return _shard_wrap(run, mesh, axis_name, n_pool,
                       in_streams=(True,) * 6, n_out_streams=2)


@functools.lru_cache(maxsize=256)
def _make_prefill_chunk(cfg, C, W, bs, quantized, final, temperature,
                        top_k, top_p, mesh, axis_name):
    """One prefill chunk of (padded) length C for ONE sequence.  Under
    sharding every shard executes the chunk against its LOCAL pool with
    its own table row / n_valid — non-owner shards get n_valid == 0, so
    their writes all land in the trash block and their (finite) outputs
    are ignored by the host."""
    def run(params, *args):
        pools = args[:4] if quantized else args[:2] + (None, None)
        table_rows, tokens, start, n_valids, seed = args[-5:]
        row = table_rows[0]
        n_valid = n_valids[0]
        posns = start + jnp.arange(C)                      # (C,)
        x = params["wte"].astype(cfg.dtype)[tokens][None] \
            + params["wpe"].astype(cfg.dtype)[posns][None]  # (1, C, E)
        valid_i = jnp.arange(C) < n_valid
        blk = jnp.where(valid_i, row[posns // bs], TRASH_BLOCK)
        off = posns % bs
        maxpos = (start + n_valid - 1)[None]             # (1,)
        x, pools = _paged_forward(params, cfg, pools, row[None], posns,
                                  maxpos, blk, off, x, quantized)
        out = pools[:4] if quantized else pools[:2]
        if not final:
            return out
        xe = jax.lax.dynamic_index_in_dim(x[0], n_valid - 1, 0,
                                          keepdims=False)
        logits = _lm_logits(params, cfg, xe[None])
        finite = jnp.isfinite(logits).all(axis=-1)       # (1,)
        nxt = _pick_next(logits, seed[None], (start + n_valid - 1)[None],
                         temperature, top_k, top_p)
        return (*out, nxt, finite)

    n_pool = 4 if quantized else 2
    return _shard_wrap(run, mesh, axis_name, n_pool,
                       in_streams=(True, False, False, True, False),
                       n_out_streams=2 if final else 0)


@functools.lru_cache(maxsize=64)
def _make_sparse_decode_step(cfg, W, K, bs, quantized, temperature, top_k,
                             top_p, mesh, axis_name):
    """Sparse-policy decode: identical to :func:`_make_decode_step`
    except the KV gather reads the K-page active table instead of the
    full W-page table.  K is STATIC (the policy's fixed gather width),
    so this is still one fixed-shape program inside the zero-recompile
    pin; the host refreshes ``stables``/``sbase`` per step with the same
    no-mutation-before-fetch discipline as ``_pos``/``_tok``.  The
    single decode query needs no per-query ``allowed`` mask: its active
    row IS exactly its own policy set (lut row of its query block)."""
    def run(params, *args):
        pools = args[:4] if quantized else args[:2] + (None, None)
        tables, stables, sbase, pos, tok, active, seeds, poison = args[-8:]
        S = tok.shape[0]
        x = params["wte"].astype(cfg.dtype)[tok][:, None, :] \
            + params["wpe"].astype(cfg.dtype)[pos][:, None, :]   # (S, 1, E)
        x = x + poison.astype(cfg.dtype)[:, None, None]
        blk = jnp.where(active, tables[jnp.arange(S), pos // bs],
                        TRASH_BLOCK)
        off = pos % bs
        x, pools = _paged_forward(params, cfg, pools, tables, pos, pos,
                                  blk, off, x, quantized,
                                  sparse=(stables, sbase))
        logits = _lm_logits(params, cfg, x[:, 0])
        finite = jnp.isfinite(logits).all(axis=-1)
        nxt = _pick_next(logits, seeds, pos, temperature, top_k, top_p)
        nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
        out = pools[:4] if quantized else pools[:2]
        return (*out, nxt, finite)

    n_pool = 4 if quantized else 2
    return _shard_wrap(run, mesh, axis_name, n_pool,
                       in_streams=(True,) * 8, n_out_streams=2)


@functools.lru_cache(maxsize=256)
def _make_sparse_prefill_chunk(cfg, C, W, K, bs, win, g, quantized, final,
                               temperature, top_k, top_p, mesh, axis_name):
    """Sparse-policy prefill chunk: the gather row is the UNION of the
    chunk queries' active sets (globals + one contiguous window run —
    fixed width K per bucket, see ``SparseContext.prefill_K``), so an
    early query's gather would include blocks below its OWN window; the
    trace-constant policy layout masks those per (query, key-block)
    pair inside the jit.  Same shard semantics as the dense chunk:
    non-owner shards get n_valid == 0 and all-sentinel sparse rows."""
    def run(params, *args):
        pools = args[:4] if quantized else args[:2] + (None, None)
        table_rows, stab_rows, sbase_rows, tokens, start, n_valids, seed = \
            args[-7:]
        row = table_rows[0]
        srow = stab_rows[0]
        sbase = sbase_rows[0]
        n_valid = n_valids[0]
        posns = start + jnp.arange(C)                      # (C,)
        x = params["wte"].astype(cfg.dtype)[tokens][None] \
            + params["wpe"].astype(cfg.dtype)[posns][None]  # (1, C, E)
        valid_i = jnp.arange(C) < n_valid
        blk = jnp.where(valid_i, row[posns // bs], TRASH_BLOCK)
        off = posns % bs
        maxpos = (start + n_valid - 1)[None]             # (1,)
        layout = jnp.asarray(_policy_layout(win, g, W) > 0)
        qb = jnp.minimum(posns // bs, W - 1)               # (C,)
        view_pos = sbase[:, None] + jnp.arange(bs)[None, :]
        sblk = jnp.minimum(view_pos // bs, W - 1)          # (K, bs)
        allow = layout[qb[:, None, None], sblk[None]] \
            .reshape(C, K * bs)[None]                      # (1, C, K*bs)
        x, pools = _paged_forward(
            params, cfg, pools, row[None], posns, maxpos, blk, off, x,
            quantized, sparse=(srow[None], sbase[None]), allowed=allow)
        out = pools[:4] if quantized else pools[:2]
        if not final:
            return out
        xe = jax.lax.dynamic_index_in_dim(x[0], n_valid - 1, 0,
                                          keepdims=False)
        logits = _lm_logits(params, cfg, xe[None])
        finite = jnp.isfinite(logits).all(axis=-1)       # (1,)
        nxt = _pick_next(logits, seed[None], (start + n_valid - 1)[None],
                         temperature, top_k, top_p)
        return (*out, nxt, finite)

    n_pool = 4 if quantized else 2
    return _shard_wrap(run, mesh, axis_name, n_pool,
                       in_streams=(True, True, True, False, False, True,
                                   False),
                       n_out_streams=2 if final else 0)


class InferenceEngine:
    """Continuous-batching serving engine (see module docstring).

    ``temperature``/``top_k``/``top_p`` are ENGINE-static (baked into the
    compiled programs); per-request randomness comes from each request's
    ``seed``.  temperature=0 (greedy) is the bit-parity configuration.
    """

    def __init__(self, model, params, *, max_slots=4, kv_block_size=16,
                 kv_blocks=None, max_blocks_per_seq=None, prefill_chunk=16,
                 quantize_kv=False, temperature=0.0, top_k=0, top_p=0.0,
                 policy="continuous", shards=1, mesh=None,
                 axis_name="data", watchdog=None, clock=time.monotonic,
                 reliability=None, telemetry=None, prefix_cache=False,
                 speculative=None, sparse_context=None,
                 prefill_fairness=0):
        cfg = model.config
        assert not getattr(cfg, "moe_num_experts", 0), \
            "InferenceEngine serves dense blocks only: chunked prefill " \
            "changes MoE capacity-gating semantics (generation._moe_ffn " \
            "gates whole prompts); use models.generation.generate for MoE"
        assert prefill_chunk >= _MIN_BUCKET \
            and (prefill_chunk & (prefill_chunk - 1)) == 0, \
            f"prefill_chunk must be a power of two >= {_MIN_BUCKET}"
        assert max_slots % shards == 0, (max_slots, shards)
        if mesh is not None:
            assert shards == mesh.shape[axis_name], \
                f"shards={shards} != mesh axis {axis_name} size"
        else:
            assert shards == 1, "shards > 1 requires a mesh"
        self.model, self.cfg, self.params = model, cfg, params
        self.max_slots = int(max_slots)
        self.shards = int(shards)
        self.mesh = mesh
        self.axis_name = axis_name
        self.bs = int(kv_block_size)
        self.W = int(max_blocks_per_seq
                     or -(-int(cfg.n_positions) // self.bs))
        if kv_blocks is None:
            kv_blocks = shards + max_slots * self.W     # never evicts
        self.pool = PagedKVPool(cfg, num_blocks=kv_blocks,
                                block_size=self.bs, shards=shards,
                                mesh=mesh, axis_name=axis_name,
                                quantize_kv=quantize_kv)
        self.prefill_chunk = int(prefill_chunk)
        self.temperature = float(temperature)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p or 0.0)
        self.scheduler = Scheduler(max_slots, policy=policy)
        # admission placement: prefer the slot whose shard already holds
        # the candidate's cached prefix (prefix-cache locality beats raw
        # headroom — a hit skips whole prefill chunks), then the slot
        # whose shard has the most free KV blocks, so new sequences
        # spread across shard pools instead of piling evictions onto
        # shard 0
        self.scheduler.slot_ranker = self._rank_slot
        self.scheduler.prefix_probe = self._prefix_probe
        self.clock = clock
        self.metrics = ServingMetrics(clock)
        self.results = {}
        self._watchdog = watchdog
        self._last_metrics = {}
        self._step_idx = 0
        self._rids = itertools.count()
        self._warming = False
        self._drain_requested = False
        # fleet identity: set by serving/fleet.py's router so per-replica
        # chaos (kill_replica / slow_replica) can target THIS engine;
        # None = not part of a fleet, fleet hooks are no-ops
        self._replica_index = None
        rel_cfg = reliability if isinstance(reliability, ReliabilityConfig) \
            else ReliabilityConfig(**(reliability or {}))
        self.reliability = Reliability(self, rel_cfg)
        self._arm_telemetry(telemetry)
        # compiled-program registry (telemetry/programs.py): ALWAYS on —
        # every serving jit registers its shape capture + HLO contract at
        # first dispatch for tools/graftlint/program_lint.py; the pool
        # registers its COW-split program through the same seam
        from deepspeed_tpu.telemetry import ProgramRegistry

        self._programs = ProgramRegistry("serving")
        self.pool.programs = self._programs
        S = self.max_slots
        self._tables = np.full((S, self.W), TRASH_BLOCK, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._tok = np.zeros(S, np.int32)
        self._active = np.zeros(S, bool)
        self._seeds = np.zeros(S, np.int32)
        self._poison = np.zeros(S, np.float32)
        self.prefix_cache = self._arm_prefix_cache(prefix_cache,
                                                   quantize_kv)
        self._readmit_rids = set()
        self.spec_k = self._arm_speculative(speculative)
        self._spec = None
        self._drafts = np.zeros((S, max(1, self.spec_k)), np.int32)
        if self.spec_k:
            self._spec = _make_spec_verify(
                cfg, self.spec_k, self.W, self.bs, self.pool.quantized,
                mesh, axis_name)
        # sparse page attention (serving/sparse_context.py) arms AFTER
        # speculation — draft-k is one of its DISARMED blockers — and
        # picks which decode program the engine serves
        self.sparse = self._arm_sparse_context(sparse_context)
        self.prefill_fairness = int(prefill_fairness or 0)
        if self.prefill_fairness and policy != "continuous":
            logger.warning(
                "prefill fairness: DISARMED — the static batch gate "
                "already runs each batch to completion; the pause "
                "quantum only applies to continuous batching.")
            self.prefill_fairness = 0
        self._stables = self._sbase = None
        if self.sparse is not None:
            self._stables = np.full((S, self.sparse.K), TRASH_BLOCK,
                                    np.int32)
            self._sbase = np.full((S, self.sparse.K),
                                  int(self.sparse.sentinel), np.int32)
            self._decode_name = "sparse_decode_step"
            self._decode = _make_sparse_decode_step(
                cfg, self.W, self.sparse.K, self.bs, self.pool.quantized,
                self.temperature, self.top_k, self.top_p, mesh, axis_name)
        else:
            self._decode_name = "decode_step"
            self._decode = _make_decode_step(
                cfg, self.W, self.bs, self.pool.quantized,
                self.temperature, self.top_k, self.top_p, mesh, axis_name)

    @property
    def program_registry(self):
        """The engine's compiled-program registry (always armed): every
        serving jit dispatched so far, with its declarative HLO contract.
        Read by ``python -m tools.graftlint --programs``."""
        return self._programs

    def _pool_contract(self, **extra):
        """The contract every pool-threading serving jit shares: pure
        device work, ZERO collective bytes under batch-axis sharding
        (comm_accounting.serving_decode_collectives' placement-semantics
        claim), and the paged KV pool (argnums 1..n_pool) donated —
        steady-state serving is allocation-free on the pool."""
        contract = {
            "host_transfer_free": True,
            "collective_free": True,
            "donates_argnums": tuple(range(1, 1 + self.n_pool_tensors())),
        }
        contract.update(extra)
        return contract

    def _register_serving_program(self, name, jit_fn, args, **extra):
        from deepspeed_tpu.telemetry import register_program

        register_program(self._programs, name, jit_fn, args,
                         mesh=None, contract=self._pool_contract(**extra))

    def _arm_prefix_cache(self, requested, quantize_kv_requested):
        """COW shared-prefix caching arms only where its bookkeeping is
        honest; every blocked request warns loudly naming the blocker
        (the armed-or-warns DISARMED discipline).  The cache itself is
        sampling-safe — cached KV rows are a pure function of the token
        prefix — so unlike speculation it does NOT require greedy."""
        if not requested:
            return False
        if quantize_kv_requested and not self.pool.quantized:
            logger.warning(
                "prefix cache: DISARMED — int8 KV was requested but the "
                "pool disarmed it (off-profitability: scale overhead >= "
                "byte savings at this head_dim/dtype); refusing to stack "
                "block sharing on a pool whose storage already silently "
                "differs from the asked-for config.  Serving without "
                "prefix caching.")
            return False
        if self.scheduler.draining:
            logger.warning(
                "prefix cache: DISARMED — the engine is draining: "
                "admission is closed, so no request could ever consult "
                "the tree; arming now would only pin blocks a successor "
                "cannot inherit.")
            return False
        return True

    def _arm_speculative(self, spec):
        """Self-speculative decoding (``speculative=k`` or
        ``{"draft_len": k}``) arms only in the greedy configuration:
        acceptance compares ARGMAX continuations token-for-token, so
        with sampling (temperature > 0) the accepted prefix would not
        equal what the sampled step-by-step stream emits — blocked
        requests warn DISARMED naming the blocker and serve the plain
        one-token decode jit instead.  Returns the armed draft length
        (0 = disarmed)."""
        if not spec:
            return 0
        k = int(spec.get("draft_len", 4)) if isinstance(spec, dict) \
            else int(spec)
        if k < 1:
            logger.warning(
                "speculative decoding: DISARMED — draft_len=%d < 1 "
                "drafts nothing; serving the plain decode jit.", k)
            return 0
        if self.temperature != 0.0:
            logger.warning(
                "speculative decoding: DISARMED — sampling != greedy: "
                "temperature=%g, but the acceptance rule (accepted "
                "prefix == step-by-step greedy argmax) is only defined "
                "at temperature=0; a sampled stream would diverge from "
                "the verified continuations.  Serving the plain decode "
                "jit.", self.temperature)
            return 0
        return k

    def _arm_sparse_context(self, spec):
        """Sparse page attention (``sparse_context=`` as a policy dict,
        an ``ops/sparse_attention`` SparsityConfig-style object, or a
        prebuilt :class:`SparseContext`) arms only where the policy maps
        soundly onto the paged pool — every blocked request warns
        DISARMED naming the blocker and the engine serves the dense
        decode jit instead (the armed-or-warns discipline).  Blockers:
        a token window that is not a multiple of the pool block size
        (the window edge would land mid-page), beam search (active-page
        lists are single-hypothesis), draft-k speculation (the verify
        jit gathers the full table — composing them is future work),
        and non-prefix global anchors.  Returns the armed SparseContext
        or None."""
        if not spec:
            return None
        if self.spec_k:
            logger.warning(
                "sparse context: DISARMED — draft-k speculative decoding "
                "is armed (draft_len=%d): the verify jit scores K+1 "
                "query tokens against the FULL page table and its "
                "acceptance rule assumes dense attention; composing the "
                "two gather policies is not supported yet.  Serving "
                "dense attention.", self.spec_k)
            return None
        if isinstance(spec, SparseContext):
            if spec.bs != self.bs or spec.W != self.W:
                logger.warning(
                    "sparse context: DISARMED — the supplied "
                    "SparseContext was compiled for block_size=%d/"
                    "table_width=%d but this engine runs %d/%d; its LUT "
                    "would address the wrong pages.  Serving dense "
                    "attention.", spec.bs, spec.W, self.bs, self.W)
                return None
            return spec
        if isinstance(spec, dict):
            d = dict(spec)
            beam = int(d.pop("beam_width", 1) or 1)
            if beam > 1:
                logger.warning(
                    "sparse context: DISARMED — beam_width=%d > 1: beam "
                    "lanes share pages under different hypotheses and "
                    "the per-lane active-page lists are single-"
                    "hypothesis.  Serving dense attention.", beam)
                return None
            wt = d.pop("window_tokens", None)
            if wt is not None:
                if int(wt) % self.bs != 0:
                    logger.warning(
                        "sparse context: DISARMED — window_tokens=%d is "
                        "not a multiple of the KV block size %d: the "
                        "policy's block granularity must BE the pool's "
                        "block size or the window edge lands mid-page.  "
                        "Round the window to a block multiple (e.g. %d "
                        "or %d).  Serving dense attention.",
                        int(wt), self.bs,
                        (int(wt) // self.bs) * self.bs,
                        (int(wt) // self.bs + 1) * self.bs)
                    return None
                d.setdefault("num_sliding_window_blocks",
                             int(wt) // self.bs)
            win = int(d.get("num_sliding_window_blocks", 0))
            if win < 1:
                logger.warning(
                    "sparse context: DISARMED — num_sliding_window_"
                    "blocks=%d < 1 cannot cover the query's own block.  "
                    "Serving dense attention.", win)
                return None
            return SparseContext(block_size=self.bs, table_width=self.W,
                                 **d)
        try:
            return SparseContext.from_sparsity_config(
                spec, block_size=self.bs, table_width=self.W)
        except ValueError as e:
            logger.warning(
                "sparse context: DISARMED — %s.  Serving dense "
                "attention.", e)
            return None

    def _arm_telemetry(self, spec):
        """Arm the serving telemetry session from the ``telemetry=``
        kwarg: ``None`` (off), a ``Telemetry`` instance, or a dict of
        Telemetry kwargs (plus ``"enabled"``).  Disarmed serving holds
        ``self._tracer = None`` — one attribute check per step, the
        compiled decode surface untouched (zero recompiles pinned by the
        telemetry test's CompilationCounter).  A config handed in with
        ``enabled=false`` or with every channel off would observe
        nothing, so it warns DISARMED instead of silently dropping the
        ask."""
        self.telemetry = None
        self._tracer = None
        self._owns_telemetry = False
        self._lane_serve = 0
        self._memacct = None
        if spec is None:
            return
        from deepspeed_tpu.telemetry import Telemetry

        if isinstance(spec, Telemetry):
            tel = spec
        else:
            self._owns_telemetry = True
            cfg = dict(spec)
            if not cfg.pop("enabled", True):
                logger.warning(
                    "serving telemetry: DISARMED — a telemetry config was "
                    "passed with enabled=false; no trace, step stream or "
                    "MFU accounting will be produced")
                return
            tel = Telemetry(**cfg)
        if tel.tracer is None and tel.stream is None and tel.mfu is None:
            logger.warning(
                "serving telemetry: every channel is off (trace=false, "
                "metrics_jsonl unset, mfu=false) — effectively DISARMED")
        self.telemetry = tel
        self._tracer = tel.tracer
        if self._tracer is not None:
            self._lane_serve = self._tracer.lane("serve")
            self._tracer.intern("serving_step", args=("step",))
            self._tracer.intern("decode_step", args=("lanes",))
            self._tracer.intern("admit", args=("rid",))
        # measured HBM accounting (ISSUE 15): per-jit memory_analysis()
        # registered capture-by-shape alongside MFU, sharing its lazy
        # compile cache — one compile per jit, zero on the decode path
        from deepspeed_tpu.runtime.memory_accounting import \
            MemoryAccounting

        self._memacct = MemoryAccounting(shared=tel.mfu)

    def export_trace(self, path, complete_events=True):
        """Chrome-trace JSON of the retained events (None when tracing
        is disarmed)."""
        tr = self._tracer
        if tr is None:
            return None
        return tr.export_chrome_trace(path, complete_events=complete_events)

    def close_telemetry(self):
        """Close the metrics-stream file handle of a telemetry session
        THIS engine created from a dict spec (a caller-provided
        ``Telemetry`` instance is the caller's to close).  Idempotent;
        also runs at GC so bench loops never leak JSONL fds."""
        if getattr(self, "_owns_telemetry", False) \
                and self.telemetry is not None:
            self.telemetry.close()

    def __del__(self):
        try:
            self.close_telemetry()
        except Exception:  # lint: allow-broad-except — interpreter
            # teardown can fail imports mid-GC; never raise from __del__
            pass

    # -- public API -----------------------------------------------------
    @property
    def capacity_per_seq(self) -> int:
        """Longest admissible prompt+max_new: the position budget
        (n_positions), the page-table width, AND one shard's usable
        block pool all bound it."""
        return min(int(self.cfg.n_positions), self.W * self.bs,
                   (self.pool.blocks_per_shard - 1) * self.bs)

    def submit(self, prompt, max_new_tokens, *, priority=0,
               eos_token_id=None, seed=0, deadline_s=None,
               work_budget=None, _generated=None, _rid=None,
               _work_done=0, _readmit=False) -> int:
        """Submit one request.  ``deadline_s``/``work_budget`` (engine
        defaults from the ReliabilityConfig) bound its wall-clock life
        and total scheduled token-writes; under predicted SLO overload
        the admission gate may shed lower-priority queued work or turn
        this request away (``results[rid]["status"] == "shed"``).
        ``_generated``/``_rid``/``_work_done`` are the :meth:`recover`
        re-submission hooks (journal replay through the eviction
        re-prefill path; the restored ``_work_done`` keeps work budgets
        accumulating across crash-migrate cycles instead of granting
        each recovery a fresh budget).  ``_readmit=True`` marks a
        recovery/migration re-submission: the request was ADMITTED once
        already, so the SLO admission gate must not shed it again — it
        is journaled directly."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        assert prompt.size >= 1 and max_new_tokens >= 1
        total = prompt.size + int(max_new_tokens)
        assert total <= self.capacity_per_seq, \
            f"prompt+max_new={total} exceeds per-sequence capacity " \
            f"{self.capacity_per_seq} (W={self.W} blocks x {self.bs}, " \
            f"{self.pool.blocks_per_shard - 1} usable blocks/shard)"
        rel_cfg = self.reliability.config
        if self._warming:
            deadline_s = work_budget = None   # synthetic warmup traffic
        else:
            if deadline_s is None:
                deadline_s = rel_cfg.default_deadline_s
            if work_budget is None:
                work_budget = rel_cfg.default_work_budget
        if deadline_s is not None and float(deadline_s) <= 0:
            raise ValueError(
                f"deadline_s={deadline_s} is not a positive budget: the "
                f"request would expire before its first step ever runs. "
                f"Submit with deadline_s=None (no deadline) or a positive "
                f"number of seconds.")
        rid = next(self._rids) if _rid is None else int(_rid)
        if deadline_s is not None and not self._warming and not _readmit:
            # deadline-impossible max_new: even PERFECT service — an
            # empty queue, every step at the measured EMA — cannot fit
            # the minimum step count inside the budget.  Reject at
            # admission instead of burning prefill work that is
            # guaranteed to expire mid-flight.  Strict lower bound only:
            # a request feasible in isolation is never turned away here
            # (queueing delay stays the reliability layer's call).
            ema = self.metrics.step_time()
            min_steps = -(-int(prompt.size) // self.prefill_chunk) \
                + int(max_new_tokens)
            if ema is not None and min_steps * ema > float(deadline_s):
                logger.warning(
                    "submit(rid=%d): deadline-impossible — prompt=%d "
                    "tokens + max_new=%d needs >= %d engine steps; at "
                    "the measured %.4fs/step that is a %.3fs zero-queue "
                    "lower bound, over deadline_s=%.3f.  Rejected at "
                    "admission (no prefill work wasted).  Raise "
                    "deadline_s or shrink max_new_tokens.",
                    rid, prompt.size, int(max_new_tokens), min_steps,
                    ema, min_steps * ema, float(deadline_s))
                self.results[rid] = {
                    "tokens": prompt.copy(), "status": ABORT_EXPIRED,
                    "evictions": 0,
                }
                self.metrics.record_finish(rid, ABORT_EXPIRED)
                if self._tracer is not None:
                    self._tracer.instant(f"abort_{ABORT_EXPIRED}",
                                         self._lane_serve, a0=rid)
                return rid
        req = Request(rid=rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      priority=int(priority), eos_token_id=eos_token_id,
                      seed=int(seed), deadline_s=deadline_s,
                      work_budget=work_budget)
        if deadline_s is not None:
            req.deadline = self.clock() + float(deadline_s)
        if _generated:
            req.generated = [int(t) for t in _generated]
        if _work_done:
            req.work_done = int(_work_done)
        # TTFT class: "long" prompts (several prefill chunks) vs chatty
        # "short" ones — the per-class view the long-context bench's
        # fairness guard reads
        self.metrics.record_submit(
            rid, klass="long" if prompt.size >= 4 * self.prefill_chunk
            else "short")
        if not self._warming:
            if _readmit:
                # already-admitted work (recovery/migration): bypass the
                # shedding gate, but journal it here so THIS engine's
                # crash covers it too.  Tagged so the prefix probe can
                # attribute cache savings to the recovery path.
                self._readmit_rids.add(rid)
                if self.reliability.journal is not None:
                    self.reliability.journal.record_submit(req)
            elif self.reliability.on_submit(req) == "reject":
                self.results[rid] = {
                    "tokens": np.asarray(req.full_tokens, np.int32),
                    "status": ABORT_SHED, "evictions": 0,
                }
                self.metrics.record_finish(rid, ABORT_SHED)
                return rid
        self.scheduler.submit(req)
        return rid

    def cancel(self, rid) -> bool:
        req = self.scheduler.cancel(rid)
        if req is None:
            return False
        self._cleanup(req, "cancelled")
        return True

    def step(self) -> dict:
        """One serving tick: chaos hooks, deadline/budget enforcement,
        at most one prefill chunk, one batched decode dispatch, then
        host-side bookkeeping on a SINGLE batched token+finiteness
        fetch, and the journal's step-boundary commit."""
        self._step_idx += 1
        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        slow = chaos.serving_slow_step_s(self._step_idx) \
            + chaos.fleet_slow_replica_s(self._replica_index,
                                         self._step_idx)
        if slow:
            time.sleep(slow)
        if self._watchdog is not None:
            self._watchdog.observe_serving_step(self._step_idx)
        if self._drain_requested:
            if tr is not None and not self.scheduler.draining:
                tr.instant("drain_requested", self._lane_serve,
                           a0=self._step_idx)
            self.scheduler.draining = True
        events = {"admitted": [], "finished": [], "evicted": [],
                  "cancelled": [], "expired": [], "budget": [],
                  "poisoned": []}
        rid = self.scheduler.chaos_cancel()
        if rid is not None and self.cancel(rid):
            events["cancelled"].append(rid)
        if tr is None:
            self._enforce_deadlines(events)
            self._prefill_tick(events)
            decoded = self._decode_tick(events)
        else:
            _t = tr.begin()
            self._enforce_deadlines(events)
            tr.complete("deadline_sweep", self._lane_serve, _t)
            _t = tr.begin()
            self._prefill_tick(events)
            tr.complete("prefill_tick", self._lane_serve, _t)
            _t = tr.begin()
            decoded = self._decode_tick(events)
            tr.complete("decode_step", self._lane_serve, _t, a0=decoded)
            for rid_ in events["admitted"]:
                tr.instant("admit", self._lane_serve, a0=rid_)
        self.scheduler.on_drained()
        self.reliability.on_step_end()
        if tr is not None and self.reliability.journal is not None:
            tr.instant("journal_commit", self._lane_serve,
                       a0=self.reliability.journal_depth())
        occ = self.pool.occupancy()
        frag = self.pool.fragmentation()
        qd = self.scheduler.queue_depth()
        self.metrics.record_step(
            queue_depth=qd, running=decoded, slots=self.max_slots,
            occupancy=occ, fragmentation=frag, decoded=decoded > 0)
        rel = self.reliability
        self._last_metrics = {
            "step": self._step_idx, "queue_depth": qd,
            "running": len(self.scheduler.running),
            "kv_occupancy": occ, "kv_fragmentation": frag,
            "decoded_lanes": decoded,
            "events": {k: len(v) for k, v in events.items()},
            "shed": rel.aborts[ABORT_SHED],
            "expired": rel.aborts[ABORT_EXPIRED],
            "poisoned": rel.aborts[ABORT_POISONED],
            "journal_depth": rel.journal_depth(),
            "draining": self.scheduler.draining,
            # prefix cache + speculation ride the same host-dict idiom:
            # scalar values flow into the fleet's flattened
            # replica_metrics automatically, the histogram dict is
            # aggregated explicitly by FleetRouter.telemetry_report()
            "prefix_hit_rate": self.metrics.prefix_hit_rate(),
            "prefix_avoided_tokens": self.metrics.prefix_avoided_tokens,
            "prefill_tokens_computed":
                self.metrics.prefill_computed_tokens,
            "tokens_per_verify": self.metrics.tokens_per_verify(),
            "spec_accept_hist": dict(self.metrics.spec_accept_hist),
            # sparse page attention (ISSUE 20): scalars only, so the
            # fleet's flattened replica_metrics carry them for free
            "active_page_fraction": self.metrics.active_page_fraction(),
            "window_expired_frees": self.metrics.window_expired_frees,
            "short_ttft_p95": self.metrics.class_ttft_p95("short"),
        }
        if tr is not None:
            tr.complete("serving_step", self._lane_serve, _t0,
                        a0=self._step_idx)
        if self.telemetry is not None and not self._warming:
            self.telemetry.on_step(self._step_idx, self._last_metrics)
        return events

    def serve(self, *, max_steps=100000) -> dict:
        steps = 0
        while self.scheduler.has_work():
            if self._drain_requested and not self.scheduler.in_flight():
                break    # drained: waiting work stays journaled
            if steps >= max_steps:
                raise RuntimeError(
                    f"serve() exceeded max_steps={max_steps} with "
                    f"{self.scheduler.queue_depth()} queued")
            self.step()
            steps += 1
        return self.results

    # -- reliability lifecycle (drain / recover) ------------------------
    def request_drain(self) -> None:
        """Ask for a graceful drain: admission stops at the next step
        boundary, in-flight requests run to completion, queued requests
        stay journaled for a successor's :meth:`recover`.  Signal-
        handler safe: only sets a flag (the PR 7
        ``request_preemption`` idiom)."""
        self._drain_requested = True
        # NOTE: no tracer event here — this runs in signal-handler
        # context and the tracer takes a lock; the step loop emits the
        # drain instant at the next (safe) step boundary instead

    def install_preemption_handler(self, signals=None) -> None:
        """Route SIGTERM (the preemption notice on TPU pods) into
        :meth:`request_drain` — the serving analog of the training
        engine's ``install_preemption_handler``.  Any previously
        installed Python-level handler is CHAINED, not replaced: a
        process hosting BOTH a training engine and a serving engine
        (the fine-tune-and-serve colocation) must graceful-preempt the
        trainer AND drain the server on one SIGTERM — ``signal.signal``
        alone is last-wins and silently dropped whichever handler
        registered first.  Main thread only (a Python signal-handler
        constraint)."""
        import signal as signal_mod

        from deepspeed_tpu.runtime.resilience.watchdog import \
            chain_signal_handlers

        sigs = chain_signal_handlers(self.request_drain, signals)
        logger.info("serving preemption handler installed for %s",
                    [signal_mod.Signals(s).name for s in sigs])

    def drain(self, *, max_steps=100000) -> dict:
        """Graceful shutdown: stop admission, finish every in-flight
        request (deadlines still enforced — a hung request cannot stall
        the drain past its budget), commit the journal, and return the
        results so far.  Queued requests stay live in the journal; a
        replacement engine picks them up via :meth:`recover`."""
        self.request_drain()
        self.scheduler.draining = True
        steps = 0
        while self.scheduler.in_flight():
            if steps >= max_steps:
                raise RuntimeError(
                    f"drain() exceeded max_steps={max_steps} with "
                    f"{len(self.scheduler.running)} still running")
            self.step()
            steps += 1
        self.reliability.on_step_end()
        left = self.scheduler.queue_depth()
        if left and self.reliability.journal is None:
            logger.warning(
                "drain: %d queued requests have NO journal armed "
                "(ReliabilityConfig.journal_path unset) — they are lost "
                "on exit instead of recoverable.", left)
        return self.results

    def recover(self, journal_path) -> list:
        """Crash recovery: replay a (dead predecessor's) request journal
        and re-submit every live request — with its journaled generated
        tokens — through the SAME re-prefill path eviction uses, so
        greedy continuations are bit-identical to the uninterrupted
        run.  Original rids and FCFS order are preserved; deadlines
        restart (wall clocks do not survive processes; the journal
        stores the relative budget).  Returns the recovered rids."""
        assert not self.scheduler.has_work(), "recover() on a busy engine"
        entries = RequestJournal.replay(journal_path)
        rids = []
        max_rid = -1
        for e in entries:
            rid = self.submit(
                np.asarray(e["prompt"], np.int32),
                e["max_new"], priority=e["priority"],
                eos_token_id=e["eos"], seed=e["seed"],
                deadline_s=e["deadline_s"], work_budget=e["work_budget"],
                _generated=e["generated"], _rid=e["rid"],
                _work_done=e.get("work_done", 0), _readmit=True)
            rids.append(rid)
            max_rid = max(max_rid, rid)
        self._rids = itertools.count(max_rid + 1)
        if self._tracer is not None:
            self._tracer.instant("recover", self._lane_serve,
                                 a0=len(rids))
        logger.info("recover: re-submitted %d journaled requests from %s",
                    len(rids), journal_path)
        return rids

    # -- fleet migration (serving/fleet.py drives these) ----------------
    def export_request(self, rid) -> dict:
        """Detach one RUNNING request for migration to another replica:
        ONE batched device fetch of its paged KV blocks (a fixed-shape
        (L, W, ...) gather — compiles once, shared by every same-config
        replica), then scheduler/pool/journal bookkeeping that removes
        the request WITHOUT a terminal result — its journal end record
        says ``migrated``, so this replica's journal no longer lists it
        live (the destination's journal does, from its re-submission).
        Returns the state dict :meth:`import_request` consumes.

        The KV handoff is the disaggregated prefill/decode transfer of
        PAPERS.md 2601.02311: prefill is compute-bound, decode is
        memory-bound, and moving the finished prompt's KV blocks once
        is what makes separately-provisioned replicas composable.  The
        payload is priced analytically by
        ``comm_accounting.serving_kv_handoff_collectives``.

        Sharded pools (``shards > 1``) hand off through the same path:
        the gather addresses GLOBAL block ids (local + the owning
        shard's base — ``pool.global_table_row``), so the host copy is
        shard-layout-free and imports into a destination with ANY shard
        count."""
        req = self.scheduler.requests.get(rid)
        assert req is not None and req.state is RequestState.RUNNING, \
            f"export_request({rid}): not a RUNNING request"
        assert req.generated, "RUNNING request with no first token"
        row = self.pool.global_table_row(rid, self.W)
        n_blocks = len(self.pool._blocks[rid])
        n_positions = self.pool._positions[rid]
        # one fixed-shape gather + ONE batched fetch: (L, W, H, bs, D)
        # per pool tensor, trash-padded rows included (their content is
        # garbage by contract; the value mask keeps it inert)
        kv = jax.device_get(tuple(
            a[:, row] for a in self.pool.tensors.arrays))
        slot = req.slot
        self.scheduler.finish(req, "migrated")
        self.pool.free(rid)
        self._clear_slot(slot)
        self.metrics.record_finish(rid, "migrated")
        if not self._warming:
            self.reliability.on_finish(req, "migrated")
        return {
            "rid": req.rid, "prompt": req.prompt,
            "generated": list(req.generated),
            "max_new_tokens": req.max_new_tokens,
            "priority": req.priority, "eos": req.eos_token_id,
            "seed": req.seed, "deadline_s": req.deadline_s,
            "work_budget": req.work_budget, "work_done": req.work_done,
            "evictions": req.evictions,
            "kv": kv, "n_blocks": n_blocks, "n_positions": n_positions,
        }

    def import_request(self, entry) -> str:
        """Adopt a migrated RUNNING request with its transferred KV:
        allocate blocks, scatter the paged rows into the local pool (one
        fixed-shape ``.at[].set`` per pool tensor — compiles once), and
        join the decode batch DIRECTLY, no re-prefill.  Decoding resumes
        at the exact position the source stopped, so greedy
        continuations stay bit-identical.  Falls back to the journal
        re-prefill path (a normal re-submission) when no slot or not
        enough blocks are free here — always correct, just re-pays the
        prefill.  Deadlines restart relative (the :meth:`recover`
        semantics — clocks do not cross replicas); work budgets carry
        over.  Returns ``"adopted"`` or ``"requeued"``."""
        rid = int(entry["rid"])
        assert rid not in self.scheduler.requests, \
            f"import_request({rid}): rid already live here"
        slot = self.scheduler.free_slot()
        shard = 0 if slot is None else self._shard_for_slot(slot)
        if slot is None \
                or self.pool.free_blocks(shard) < entry["n_blocks"]:
            self.submit(np.asarray(entry["prompt"], np.int32),
                        entry["max_new_tokens"],
                        priority=entry["priority"],
                        eos_token_id=entry["eos"], seed=entry["seed"],
                        deadline_s=entry["deadline_s"],
                        work_budget=entry["work_budget"],
                        _generated=entry["generated"], _rid=rid,
                        _work_done=entry["work_done"], _readmit=True)
            return "requeued"
        req = Request(rid=rid,
                      prompt=np.asarray(entry["prompt"], np.int32),
                      max_new_tokens=int(entry["max_new_tokens"]),
                      priority=int(entry["priority"]),
                      eos_token_id=entry["eos"], seed=int(entry["seed"]),
                      deadline_s=entry["deadline_s"],
                      work_budget=entry["work_budget"])
        req.generated = [int(t) for t in entry["generated"]]
        assert req.generated, "adopted request must carry a first token"
        req.work_done = int(entry["work_done"])
        req.evictions = int(entry.get("evictions", 0))
        req.prefill_done = len(req.full_tokens)
        req.shard = shard
        if req.deadline_s is not None:
            req.deadline = self.clock() + float(req.deadline_s)
        ok = self.pool.alloc(rid, shard, entry["n_positions"])
        assert ok, "free_blocks precheck lied"
        # the scatter addresses GLOBAL rows (trash padding lands in the
        # adopting shard's own trash block); the decode table stays
        # LOCAL — inside the sharded decode shard_map each shard sees
        # only its local block range
        dst_row = self.pool.global_table_row(rid, self.W)
        t = self.pool.tensors.arrays
        new = tuple(a.at[:, dst_row].set(jnp.asarray(part))
                    for a, part in zip(t, entry["kv"]))
        if self.shards > 1 and self.pool.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # the out-of-jit scatter may resolve to a different layout;
            # pin the pool's (None, 'data') block-axis split back so the
            # donated decode jit sees its expected input sharding
            spec = NamedSharding(self.pool.mesh,
                                 P(None, self.pool.axis_name))
            new = tuple(jax.device_put(x, spec) for x in new)
        self._rebind(new)
        self.scheduler.adopt_running(req, slot)
        self._tables[slot] = self.pool.table_row(rid, self.W)
        self._pos[slot] = len(req.full_tokens) - 1
        self._tok[slot] = req.generated[-1]
        self._seeds[slot] = req.seed
        self._active[slot] = True
        if self.sparse is not None:
            self._stables[slot], self._sbase[slot] = \
                self.sparse.active_row(self._tables[slot],
                                       int(self._pos[slot]))
        # journal directly (no admission gate: this work was admitted
        # once already); no metrics.record_submit — TTFT stays at the
        # replica that admitted it
        if not self._warming and self.reliability.journal is not None:
            self.reliability.journal.record_submit(req)
        return "adopted"

    def can_adopt(self, n_blocks) -> bool:
        """True when :meth:`import_request` would adopt directly (a
        free slot whose shard has ``n_blocks`` free) — the router
        checks BEFORE exporting, so a full decode tier never pays a
        device fetch just to discard the computed KV and re-prefill."""
        slot = self.scheduler.free_slot()
        return slot is not None and \
            self.pool.free_blocks(self._shard_for_slot(slot)) \
            >= n_blocks

    def warmup(self) -> None:
        """Compile every program the steady state can need — the decode
        jit plus each (bucket, final/non-final) prefill variant that an
        ADMISSIBLE request can reach — by serving throwaway requests,
        then reset results/metrics.  After warmup, request churn
        triggers ZERO new compilations.

        Coverage argument: a final chunk of residue r compiles the same
        program as any residue in its power-of-two bucket, and every
        reachable bucket admits a single-chunk prompt of length r
        (multi-chunk prompts only shrink the admissible residue), so one
        short prompt per bucket plus ONE prompt longer than
        prefill_chunk (iff any admissible prompt is) covers everything."""
        assert not self.scheduler.has_work(), "warmup on a busy engine"
        # warmup traffic is synthetic: bypass the admission gate and the
        # journal (a recovery replay must never see throwaway requests)
        self._warming = True
        cap = self.capacity_per_seq
        lens = set()
        for b in self._buckets():
            n = b if b == _MIN_BUCKET else b // 2 + 1
            if n + 1 <= cap:
                lens.add(n)               # single-chunk final, bucket b
        if cap - 1 > self.prefill_chunk:
            # some admissible prompt spans chunks: compile the non-final
            # (always full-chunk) variant too
            lens.add(min(2 * self.prefill_chunk, cap - 1))
        for ln in sorted(lens):
            self.submit(np.zeros(ln, np.int32),
                        max_new_tokens=min(2, cap - ln))
        if cap >= 3:
            # the first token comes from the prefill-final jit; the
            # decode jit only compiles on a SECOND token — guarantee one
            # even when every bucket prompt above could only afford
            # max_new=1
            self.submit(np.zeros(1, np.int32), max_new_tokens=2)
        self.serve()
        if self.prefix_cache:
            # the COW-split copy is the one non-jit device program the
            # cache can reach — compile it here, inside warmup
            self.pool.warm_cow()
        self._warming = False
        self.results.clear()
        self.metrics.reset()
        self._last_metrics = {}
        self._step_idx = 0

    def result(self, rid) -> np.ndarray:
        """prompt + generated tokens of a finished/cancelled request."""
        return self.results[rid]["tokens"]

    def serving_report(self) -> dict:
        """TTFT / TPOT / throughput / queue-depth / KV-pool occupancy of
        the run so far — the serving analog of the training engine's
        comm_volume_report(): pure host accounting, no device sync."""
        rep = self.metrics.report()
        rep["config"] = {
            "max_slots": self.max_slots, "shards": self.shards,
            "kv_block_size": self.bs, "kv_blocks": self.pool.num_blocks,
            "max_blocks_per_seq": self.W,
            "prefill_chunk": self.prefill_chunk,
            "quantized_kv": self.pool.quantized,
            "policy": self.scheduler.policy,
            "temperature": self.temperature, "top_k": self.top_k,
            "top_p": self.top_p,
            "prefix_cache": self.prefix_cache,
            "speculative_draft_len": self.spec_k,
            "sparse_context": self.sparse.describe()
            if self.sparse is not None else None,
            "prefill_fairness": self.prefill_fairness,
        }
        rep["kv_pool"]["now"] = self.pool.stats()
        rep["reliability"] = self.reliability.report()
        return rep

    def telemetry_report(self) -> dict:
        """Unified observability report (the serving face of the training
        engines' ``telemetry_report()``): the full legacy
        ``serving_report()`` plus the telemetry sections — metrics
        registry snapshot, trace summary, and the decode MFU/HFU ledger
        (``mfu``, populated from the decode jit's
        ``cost_analysis()``)."""
        rep = self.serving_report()
        tel = self.telemetry
        # same top-level schema as the training engines' report
        # (telemetry_armed/metrics/trace/mfu) so shared consumers never
        # branch on engine type; the nested "telemetry" section mirrors
        # them for back-compat
        rep["telemetry_armed"] = tel is not None
        rep["telemetry"] = {"armed": tel is not None}
        # memory leg (ISSUE 15): pool + params analytic always, measured
        # per-jit memory_analysis when telemetry is armed
        rep["memory"] = self.memory_report()
        if tel is None:
            return rep
        rep["metrics"] = rep["telemetry"]["metrics"] = \
            tel.registry.snapshot()
        if tel.tracer is not None:
            rep["trace"] = rep["telemetry"]["trace"] = \
                tel.tracer.summary()
        if tel.mfu is not None:
            from deepspeed_tpu.telemetry import model_flops_per_step

            n_params = sum(
                int(l.size)
                for l in jax.tree_util.tree_leaves(self.params))
            # decode model FLOPs: 2ND forward-only over every dispatched
            # lane (idle lanes still compute — multiply by
            # slot_utilization for a goodput-adjusted MFU)
            rep["mfu"] = tel.mfu.report(
                step_time_s=self.metrics.step_time() or tel.step_time_s(),
                n_devices=max(1, self.shards),
                model_flops=model_flops_per_step(n_params, self.max_slots,
                                                 fwd_only=True),
                device_kind=getattr(jax.devices()[0], "device_kind", None))
            rep["mfu"]["n_params"] = n_params
            rep["mfu"]["tokens_per_step"] = self.max_slots
        return rep

    def memory_report(self) -> dict:
        """The serving face of the memory accounting (ISSUE 15):
        analytic device bytes — replicated params plus the paged KV
        block pool, priced through the SAME
        ``memory_accounting.kv_pool_bytes`` builder the pool's own
        ``stats()`` uses (byte-exact vs the allocated arrays) — next to
        the measured per-jit ``memory_analysis()`` of the decode/prefill
        programs and the per-device ``memory_stats()`` watermark.  Cold
        report builder: never call it from the step loop."""
        from deepspeed_tpu.runtime import memory_accounting as mem_acc

        pool_bytes = self.pool.device_bytes()
        params_bytes = mem_acc.tree_device_bytes(self.params)
        analytic = {
            "components": {
                "params_bytes": params_bytes,
                "kv_pool_bytes": pool_bytes,
            },
            "persistent_bytes": params_bytes + pool_bytes,
            "transient_bytes": 0,
            "peak_bytes": params_bytes + pool_bytes,
        }
        devices = list(self.mesh.devices.reshape(-1)) \
            if self.mesh is not None else None
        return mem_acc.memory_report(
            analytic=analytic, accounting=self._memacct, devices=devices,
            extra={"engine": type(self).__name__})

    def _decode_args(self):
        """Full argument tuple of the armed decode program (dense or
        sparse) — shared by dispatch, program registration, telemetry
        shape capture and :meth:`decode_hlo`."""
        if self.sparse is not None:
            return (self.params, *self.pool.tensors.arrays, self._tables,
                    self._stables, self._sbase, self._pos, self._tok,
                    self._active, self._seeds, self._poison)
        return (self.params, *self.pool.tensors.arrays, self._tables,
                self._pos, self._tok, self._active, self._seeds,
                self._poison)

    def decode_hlo(self) -> str:
        """Compiled HLO of the decode program (for the graftlint HLO
        contracts: host-transfer-free, pool donated, zero collectives)."""
        args = self._decode_args()
        return self._decode.lower(*args).compile().as_text()

    def spec_hlo(self) -> str:
        """Compiled HLO of the draft-verify program (same contracts as
        the decode jit: host-transfer-free, pool donated, zero
        collectives).  Only callable when speculation is armed."""
        assert self.spec_k, "spec_hlo() requires speculative decoding"
        toks = np.zeros((self.max_slots, self.spec_k + 1), np.int32)
        nvalid = np.zeros(self.max_slots, np.int32)
        args = (self.params, *self.pool.tensors.arrays, self._tables,
                self._pos, toks, nvalid, self._active, self._poison)
        return self._spec.lower(*args).compile().as_text()

    def n_pool_tensors(self) -> int:
        return len(self.pool.tensors.arrays)

    # -- internals ------------------------------------------------------
    def _buckets(self):
        b, out = _MIN_BUCKET, []
        while b <= self.prefill_chunk:
            out.append(b)
            b *= 2
        return out

    def _bucket(self, n):
        for b in self._buckets():
            if n <= b:
                return b
        raise AssertionError(f"chunk {n} > prefill_chunk")

    def _rebind(self, arrays):
        # 2 arrays (k, v) or 4 (+ scales); the NamedTuple defaults cover
        # the missing scale slots with None
        self.pool.tensors = PoolTensors(*arrays)

    def _shard_for_slot(self, slot):
        return slot // (self.max_slots // self.shards)

    def _rank_slot(self, slot, req=None):
        """Admission slot score: (cached-prefix coverage on the slot's
        shard, free blocks).  Pure host walk of the radix tree — no
        device syncs on the admission path."""
        shard = self._shard_for_slot(slot)
        hit = 0
        if req is not None and self.prefix_cache and not self._warming:
            full, _, cow_len = self.pool.prefix_lookup(
                shard, req.full_tokens)
            hit = len(full) * self.bs + cow_len
        return (hit, self.pool.free_blocks(shard))

    def _prefix_probe(self, req):
        """Admission-time prefix consult (installed as the scheduler's
        ``prefix_probe``): map cached prompt blocks read-only into the
        new request's page table and advance ``prefill_done`` past them
        — the covered chunks are never dispatched.  Journal-replayed and
        migration-readmitted requests take the same path (their
        ``full_tokens`` re-prefill shares the prompt blocks), which is
        the fleet-honesty fix: recovery no longer re-prefills from
        token 0 when the prompt's KV is already resident."""
        req.shard = self._shard_for_slot(req.slot)
        if not self.prefix_cache or self._warming:
            return 0
        hit = self.pool.prefix_attach(req.rid, req.shard, req.full_tokens)
        if hit:
            req.prefill_done = hit
        self.metrics.record_prefix_lookup(
            hit, readmit=req.rid in self._readmit_rids)
        return hit

    def _ensure_blocks(self, req, n_positions, *, admission, events):
        """Grow ``req``'s page table to cover ``n_positions``, preempting
        victims from the scheduler's policy until the shard has room.
        False = req itself was deferred/evicted (caller must not use
        it this step)."""
        while not self.pool.alloc(req.rid, req.shard, n_positions):
            victim = self.scheduler.victim(for_req=req,
                                           admission=admission,
                                           shard=req.shard)
            if victim is None:
                if admission:
                    self.scheduler.drop_prefill(req, requeue=True)
                    self.pool.free(req.rid)
                else:
                    self._evict(req, events)
                return False
            self._evict(victim, events)
        return True

    def _evict(self, req, events):
        slot = req.slot
        self.scheduler.preempt(req)
        self.pool.free(req.rid)
        self._clear_slot(slot)
        self.metrics.record_eviction(req.rid)
        events["evicted"].append(req.rid)

    def _clear_slot(self, slot):
        if slot is None:
            return
        self._active[slot] = False
        self._tables[slot] = TRASH_BLOCK
        self._pos[slot] = 0
        self._tok[slot] = 0
        if self.sparse is not None:
            self._stables[slot] = TRASH_BLOCK
            self._sbase[slot] = int(self.sparse.sentinel)

    def _cleanup(self, req, reason):
        self.pool.free(req.rid)
        self._clear_slot(req.slot)
        self.results[req.rid] = {
            "tokens": np.concatenate(
                [req.prompt, np.asarray(req.generated, np.int32)]),
            "status": reason, "evictions": req.evictions,
        }
        self.metrics.record_finish(req.rid, reason)
        if not self._warming:
            self.reliability.on_finish(req, reason)

    def _abort(self, req, reason, events=None):
        """Terminal non-completion in ANY live state (waiting, prefill,
        running): scheduler bookkeeping, KV blocks freed, slot scrubbed,
        result recorded with the explicit reason — an expired/poisoned
        request can never wedge the shared decode batch."""
        self.scheduler.finish(req, reason)
        self._cleanup(req, reason)
        if self._tracer is not None:
            self._tracer.instant(f"abort_{reason}", self._lane_serve,
                                 a0=req.rid)
        if events is not None and reason in events:
            events[reason].append(req.rid)

    def _enforce_deadlines(self, events):
        """Step-boundary deadline + work-budget enforcement over every
        live request.  Pure host accounting (the clock and two ints per
        request) — no device syncs, held to the hot-path lint bar."""
        now = self.clock()
        for req in list(self.scheduler.requests.values()):
            if req.state in (RequestState.FINISHED,
                             RequestState.CANCELLED):
                continue
            if req.deadline is not None and now > req.deadline:
                self._abort(req, ABORT_EXPIRED, events)
            elif req.work_budget is not None \
                    and req.work_done >= req.work_budget:
                self._abort(req, ABORT_BUDGET, events)

    def _finish(self, req, reason, events):
        self.scheduler.finish(req, reason)
        self._cleanup(req, reason)
        events["finished"].append(req.rid)

    def _on_new_token(self, req, token, events, *, promote):
        req.generated.append(int(token))
        self.metrics.record_token(req.rid)
        if not self._warming:
            self.reliability.on_token(req, int(token))
        if req.done:
            self._finish(req, "finished", events)
            return
        if promote:
            self.scheduler.promote(req)
            slot = req.slot
            self._tables[slot] = self.pool.table_row(req.rid, self.W)
            self._pos[slot] = len(req.full_tokens) - 1
            self._tok[slot] = req.generated[-1]
            self._seeds[slot] = req.seed
            self._active[slot] = True

    def _prefill_args(self, req, n):
        rows = np.full((self.shards, self.W), TRASH_BLOCK, np.int32)
        nv = np.zeros(self.shards, np.int32)
        rows[req.shard] = self.pool.table_row(req.rid, self.W)
        nv[req.shard] = n
        return rows, nv

    def _prefill_tick(self, events):
        sch = self.scheduler
        req = sch.prefilling
        if req is None:
            req = sch.start_admission()
            if req is not None:
                req.shard = self._shard_for_slot(req.slot)
                events["admitted"].append(req.rid)
            else:
                # no fresh admission (empty queue or no free slot): give
                # the lane back to the oldest fairness-paused prefill.
                # Trying admissions FIRST is what makes the quantum
                # round-robin — a paused giant never starves newcomers.
                req = sch.resume_prefill()
            if req is None:
                return
        toks = req.full_tokens
        total = len(toks)
        start = req.prefill_done
        n = min(self.prefill_chunk, total - start)
        final = start + n == total
        # the final chunk also reserves the first decode write position
        if not self._ensure_blocks(req, start + n + (1 if final else 0),
                                   admission=True, events=events):
            return
        if self.sparse is not None:
            # blocks below the chunk's FIRST query window (keeping the
            # global anchors) are already unreachable — free them before
            # building the table row, exactly like the decode tick
            freed = self.pool.window_expired_free(
                req.rid, self.sparse.first_active_block(start),
                keep_blocks=self.sparse.g)
            if freed:
                self.metrics.record_window_expired(freed)
        bucket = self._bucket(n)
        tok_pad = np.zeros(bucket, np.int32)
        tok_pad[:n] = toks[start:start + n]
        rows, nv = self._prefill_args(req, n)
        if self.sparse is not None:
            K_pf = self.sparse.prefill_K(bucket)
            fn = _make_sparse_prefill_chunk(
                self.cfg, bucket, self.W, K_pf, self.bs, self.sparse.win,
                self.sparse.g, self.pool.quantized, final,
                self.temperature, self.top_k, self.top_p, self.mesh,
                self.axis_name)
            srows = np.full((self.shards, K_pf), TRASH_BLOCK, np.int32)
            sbases = np.full((self.shards, K_pf),
                             int(self.sparse.sentinel), np.int32)
            srows[req.shard], sbases[req.shard] = \
                self.sparse.prefill_active_row(rows[req.shard], start, n,
                                               bucket)
            pf_name = f"sparse_prefill_chunk{bucket}" \
                + ("_final" if final else "")
            pf_args = (self.params, *self.pool.tensors.arrays, rows,
                       srows, sbases, tok_pad, np.int32(start), nv,
                       np.int32(req.seed))
            group = "serving:sparse_prefill_final" if final \
                else "serving:sparse_prefill"
        else:
            fn = _make_prefill_chunk(
                self.cfg, bucket, self.W, self.bs, self.pool.quantized,
                final, self.temperature, self.top_k, self.top_p,
                self.mesh, self.axis_name)
            pf_name = f"prefill_chunk{bucket}" + ("_final" if final
                                                  else "")
            pf_args = (self.params, *self.pool.tensors.arrays, rows,
                       tok_pad, np.int32(start), nv, np.int32(req.seed))
            group = "serving:prefill_final" if final \
                else "serving:prefill"
        # bucketed prefill programs at the same schedule slot must post
        # identical collective sequences (uniform_group) — a divergence
        # between buckets would deadlock a multi-host SPMD dispatch
        self._register_serving_program(pf_name, fn, pf_args,
                                       uniform_group=group)
        if self.telemetry is not None:
            # every bucketed prefill jit joins the MFU + memory ledgers
            # (capture-by-shape, no-op after the first registration)
            from deepspeed_tpu.runtime import memory_accounting as mem_acc
            from deepspeed_tpu.telemetry import register_by_shape

            register_by_shape(self.telemetry.mfu, pf_name, fn, pf_args)
            mem_acc.register_by_shape(self._memacct, pf_name, fn, pf_args)
        out = fn(*pf_args)
        req.work_done += n
        self.metrics.record_prefill(n)
        if final:
            # ONE batched fetch: the sampled token and the non-finite-
            # logits detector travel together (no extra host sync)
            fetched = jax.device_get((out[-2], out[-1]))
            self._rebind(out[:-2])
            first = int(np.asarray(fetched[0]).reshape(-1)[req.shard])
            ok = bool(np.asarray(fetched[1]).reshape(-1)[req.shard])
            req.prefill_done = total
            if not ok:
                self._abort(req, ABORT_POISONED, events)
                return
            if self.prefix_cache and not self._warming:
                # publish the (finite-checked) prompt blocks into the
                # radix tree — the next request sharing this prefix
                # skips their prefill chunks entirely
                self.pool.prefix_insert(req.rid, req.shard, req.prompt)
            self._on_new_token(req, first, events, promote=True)
        else:
            self._rebind(out)
            req.prefill_done = start + n
            if self.prefill_fairness:
                # chunked-prefill fairness: after a quantum of chunks a
                # huge prompt yields the lane IF anyone is waiting for
                # it — chatty short requests interleave instead of
                # queueing behind the whole giant
                req.fair_chunks += 1
                if req.fair_chunks >= self.prefill_fairness and \
                        (sch.peek_waiting() is not None or sch.paused):
                    sch.pause_prefill(req)

    def _draft_tokens(self, req, k):
        """Host-side n-gram drafter: propose the continuation that
        followed the most recent earlier occurrence of the current last
        token (repeating the last token when history has none).
        Deterministic and correctness-free — the verify step accepts
        only the bit-exact greedy prefix, so a bad draft costs speed,
        never parity."""
        toks = req.full_tokens
        last = int(toks[-1])
        out = None
        for i in range(len(toks) - 2, -1, -1):
            if int(toks[i]) == last:
                cont = [int(t) for t in toks[i + 1:i + 1 + k]]
                if cont:
                    out = cont
                break
        if out is None:
            out = [last]
        while len(out) < k:
            out.append(out[-1])
        return out[:k]

    def _spec_decode_tick(self, events):
        """Speculative variant of the decode tick: ONE fixed-shape
        draft-verify dispatch scores the current token plus K drafts per
        lane; the host accepts the longest draft prefix matching the
        program's own argmax stream (plus the bonus token).  Same
        single-batched-fetch / poison-quarantine / zero-recompile
        discipline as the plain tick."""
        sch = self.scheduler
        if not sch.running:
            return 0
        K = self.spec_k
        # growth: each lane writes up to min(K+1, remaining) positions
        # this step — cover them, preempting within the shard if needed
        for slot in sorted(sch.running):
            req = sch.running.get(slot)
            if req is None:
                continue
            n = min(K + 1, req.max_new_tokens - len(req.generated))
            self._ensure_blocks(req, int(self._pos[slot]) + n,
                                admission=False, events=events)
        running = dict(sch.running)
        if not running:
            return 0
        if chaos.serving_poison_step(self._step_idx):
            victim = max(running.values(), key=lambda r: r.submit_seq)
            self._poison[victim.slot] = np.nan
            chaos.record_serving_poison(victim.rid)
        nvalid = np.zeros(self.max_slots, np.int32)
        toks_in = np.zeros((self.max_slots, K + 1), np.int32)
        for slot, req in running.items():
            self._tables[slot] = self.pool.table_row(req.rid, self.W)
            n = min(K + 1, req.max_new_tokens - len(req.generated))
            nvalid[slot] = n
            toks_in[slot, 0] = self._tok[slot]
            drafts = self._draft_tokens(req, K)
            toks_in[slot, 1:] = drafts
            self._drafts[slot] = drafts
            req.work_done += n
        self.metrics.record_gather(
            len(running), len(running) * self.W, len(running) * self.W,
            sum(self.pool.blocks_of(r.rid) for r in running.values()))
        tel = self.telemetry
        spec_args = (self.params, *self.pool.tensors.arrays,
                     self._tables, self._pos, toks_in, nvalid,
                     self._active, self._poison)
        self._register_serving_program("spec_verify", self._spec,
                                       spec_args)
        if tel is not None:
            from deepspeed_tpu.runtime import memory_accounting as mem_acc
            from deepspeed_tpu.telemetry import register_by_shape

            register_by_shape(tel.mfu, "spec_verify", self._spec,
                              spec_args)
            mem_acc.register_by_shape(
                self._memacct, "spec_verify", self._spec, spec_args,
                expect_label="serving draft-verify step: donated "
                "in-place KV block pool + argmax continuations")
        out = self._spec(self.params, *self.pool.tensors.arrays,
                         self._tables, self._pos, toks_in, nvalid,
                         self._active, self._poison)
        self._rebind(out[:-2])
        chaos.serving_kill_step(self._step_idx)
        chaos.fleet_kill_replica_step(self._replica_index, self._step_idx)
        # ONE batched fetch per step: K+1 argmax tokens per lane + the
        # per-lane finiteness detector travel together
        outs, fins = jax.device_get((out[-2], out[-1]))
        outs = np.asarray(outs)
        fins = np.asarray(fins)
        self._poison[:] = 0.0
        for slot, req in running.items():
            if not fins[slot]:
                self._abort(req, ABORT_POISONED, events)
                continue
            row = outs[slot]
            drafts = self._drafts[slot]
            m = 1
            while m <= K and drafts[m - 1] == row[m - 1]:
                m += 1
            m = min(m, int(nvalid[slot]))
            consumed = 0
            for i in range(m):
                consumed += 1
                self._on_new_token(req, int(row[i]), events,
                                   promote=False)
                if req.done:
                    break
            self.metrics.record_verify(consumed)
            if sch.running.get(slot) is req:
                self._pos[slot] += consumed
                self._tok[slot] = int(row[consumed - 1])
        return len(running)

    def _decode_tick(self, events):
        if self.spec_k:
            return self._spec_decode_tick(events)
        sch = self.scheduler
        if not sch.running:
            return 0
        # growth: each lane writes position pos this step — make sure its
        # page table covers it, preempting within the lane's shard if the
        # pool is full
        for slot in sorted(sch.running):
            req = sch.running.get(slot)
            if req is None:
                continue
            self._ensure_blocks(req, int(self._pos[slot]) + 1,
                                admission=False, events=events)
        running = dict(sch.running)
        if not running:
            return 0
        # chaos poison: NaN into the youngest DISPATCHED lane's embedding
        # (chosen after the growth loop so an evicted lane is never the
        # victim) — its logits go non-finite and must be quarantined
        if chaos.serving_poison_step(self._step_idx):
            victim = max(running.values(), key=lambda r: r.submit_seq)
            self._poison[victim.slot] = np.nan
            chaos.record_serving_poison(victim.rid)
        for slot, req in running.items():
            if self.sparse is not None:
                # pages below every remaining query's window (keeping
                # the global anchors) can never be gathered again —
                # return them to the allocator before refreshing the
                # table row, so this step's row already shows the holes
                freed = self.pool.window_expired_free(
                    req.rid,
                    self.sparse.first_active_block(int(self._pos[slot])),
                    keep_blocks=self.sparse.g)
                if freed:
                    self.metrics.record_window_expired(freed)
            self._tables[slot] = self.pool.table_row(req.rid, self.W)
            if self.sparse is not None:
                # host-side LUT maintenance: same no-mutation-before-
                # fetch discipline as _pos/_tok (the previous dispatch's
                # batched fetch already completed)
                self._stables[slot], self._sbase[slot] = \
                    self.sparse.active_row(self._tables[slot],
                                           int(self._pos[slot]))
            req.work_done += 1
        lanes = len(running)
        if self.sparse is not None:
            nonpad = int(sum(
                (self._sbase[slot] != int(self.sparse.sentinel)).sum()
                for slot in running))
            self.metrics.record_gather(lanes, lanes * self.sparse.K,
                                       lanes * self.W, nonpad)
        else:
            self.metrics.record_gather(
                lanes, lanes * self.W, lanes * self.W,
                sum(self.pool.blocks_of(r.rid) for r in running.values()))
        tel = self.telemetry
        # capture-by-shape BEFORE dispatch (the pool is donated by it);
        # the lower+compile runs lazily at report/lint time, outside any
        # recompile-guard window
        decode_args = self._decode_args()
        self._register_serving_program(self._decode_name, self._decode,
                                       decode_args)
        if tel is not None:
            from deepspeed_tpu.runtime import memory_accounting as mem_acc
            from deepspeed_tpu.telemetry import register_by_shape

            register_by_shape(tel.mfu, self._decode_name, self._decode,
                              decode_args)
            mem_acc.register_by_shape(
                self._memacct, self._decode_name, self._decode,
                decode_args,
                expect_label="serving decode step: donated-in-place KV "
                "block pool + sampled tokens")
        out = self._decode(*decode_args)
        self._rebind(out[:-2])
        # kill-mid-decode chaos: the dispatch happened, NO host
        # bookkeeping has — the journal holds the last committed step
        chaos.serving_kill_step(self._step_idx)
        chaos.fleet_kill_replica_step(self._replica_index, self._step_idx)
        # ONE batched fetch per step: sampled tokens + per-lane
        # finiteness (the poison detector) travel together
        toks, fins = jax.device_get((out[-2], out[-1]))
        toks = np.asarray(toks)
        fins = np.asarray(fins)
        # one-step injection, reset only AFTER the fetch: the CPU
        # backend may alias numpy inputs zero-copy, so host mutation
        # must wait for the execution to complete (same discipline as
        # _pos/_tok below)
        self._poison[:] = 0.0
        for slot, req in running.items():
            if not fins[slot]:
                # per-request fault isolation: quarantine THIS request;
                # its blocks are freed and the value mask keeps any NaN
                # it wrote from ever reaching another lane's einsum
                self._abort(req, ABORT_POISONED, events)
                continue
            self._pos[slot] += 1
            self._tok[slot] = int(toks[slot])
            self._on_new_token(req, int(toks[slot]), events,
                               promote=False)
        return len(running)
