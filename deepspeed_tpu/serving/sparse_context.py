"""Sparse page attention policies for the paged KV pool (ISSUE 20).

Long-context serving pays O(total pages) per decode step on the dense
path: the fixed-shape decode jit gathers EVERY page of every lane's
page table (``W`` blocks) no matter how long the sequence is, so a
32k-token request gathers 32k keys to score ONE query.  This module is
the policy layer that closes that gap: a ``SparsityConfig``-style
layout — sliding window + global anchor blocks, the BigBird /
BSLongformer shapes of ``ops/sparse_attention/sparsity_config.py`` —
compiled down to per-lane ACTIVE-PAGE lists whose block granularity IS
the KV pool's block size.

The contract with the serving engine:

- **Fixed K.**  Every lane gathers exactly ``K = min(W, globals +
  window)`` pages per dispatch, whatever its length.  Fixed K means
  fixed shapes, which keeps the sparse decode/prefill programs inside
  the zero-recompile pin (one compile each, ever).  Padded entries
  point at the trash block (0) with a sentinel view position the
  engine's masks reject — the existing masked-lane idiom.
- **LUT at arm time, row maintenance per step.**  ``_compile_luts``
  builds the (W, K) query-block → active-logical-blocks table ONCE when
  the policy arms (a cold builder, held to the graftlint
  COLD_BUILDER_NAMES bar).  Per decode step the engine calls
  :meth:`active_row` — pure numpy indexing, no device sync — to refresh
  each lane's physical gather row, following the same
  host-mutation-before-dispatch discipline as ``_pos``/``_tok``.
- **Bit-identity escape hatch.**  With a window covering the whole
  context (``globals + window >= W``) every active row is exactly the
  dense page table in dense order, the view positions are exactly the
  dense positions, and the masks reduce to the dense causal masks —
  sparse greedy decode is bit-identical to the dense path (the
  acceptance test).  At genuinely long context the reference is the
  XLA ``layout_to_token_mask`` path over :meth:`layout`.

Pages that fall out of every lane's active set become early-freeable —
``PagedKVPool.window_expired_free`` returns them to the allocator while
prefix-cache-shared blocks stay resident (the radix tree's refcounts
win; see the satellite test).
"""
from typing import Optional

import numpy as np

from deepspeed_tpu.serving.kv_cache import TRASH_BLOCK


def _policy_layout(win: int, g: int, nb: int) -> np.ndarray:
    """(nb, nb) 0/1 block layout of the causal sliding-window + global
    policy: query block ``qb`` attends key blocks ``[qb-win+1 .. qb]``
    plus the ``g`` leading global anchor blocks.  This is the
    BSLongformer shape of ``sparsity_config.py`` restricted to its
    causal (lower-triangular) half — decode only ever looks backward."""
    rows = np.arange(nb)[:, None]
    cols = np.arange(nb)[None, :]
    window = (cols <= rows) & (cols > rows - win)
    anchors = (cols < g) & (cols <= rows)
    return (window | anchors).astype(np.int64)


class SparseContext:
    """One armed sparse-attention policy over a ``W``-block page table.

    ``num_sliding_window_blocks`` (``win``) and ``num_global_blocks``
    (``g``) are in POOL blocks — the policy's block granularity is the
    KV pool's ``block_size`` by construction, so an active block maps
    1:1 onto a gatherable page.  ``K`` is the fixed per-lane gather
    width; ``sentinel`` is the view position padded entries carry
    (``W * block_size`` — beyond every valid query/maxpos, so the
    causal and validity masks reject padded pages unconditionally)."""

    def __init__(self, *, block_size: int, table_width: int,
                 num_sliding_window_blocks: int, num_global_blocks: int = 1):
        assert num_sliding_window_blocks >= 1, \
            "the sliding window must cover at least the current block"
        assert num_global_blocks >= 0
        self.bs = int(block_size)
        self.W = int(table_width)
        self.win = int(num_sliding_window_blocks)
        self.g = int(num_global_blocks)
        self.K = min(self.W, self.g + self.win)
        self.sentinel = np.int32(self.W * self.bs)
        self.lut = self._compile_luts()

    @classmethod
    def from_sparsity_config(cls, sc, *, block_size: int, table_width: int):
        """Compile an ``ops/sparse_attention`` SparsityConfig-style
        object (BSLongformer / BigBird) into a serving policy.  The
        symmetric ``num_sliding_window_blocks`` window of those configs
        spans ``w // 2`` blocks on each side; causally clipped that is a
        backward window of ``w // 2 + 1`` blocks (self included).
        Global anchors must be the LEADING blocks — decode can only
        anchor on pages every sequence has already written."""
        w = int(getattr(sc, "num_sliding_window_blocks"))
        idx = list(getattr(sc, "global_block_indices", [0]) or [0])
        ends = getattr(sc, "global_block_end_indices", None)
        if ends is not None:
            blocks = sorted({b for s, e in zip(idx, ends)
                             for b in range(int(s), int(e))})
        else:
            blocks = sorted({int(b) for b in idx})
        g = len(blocks)
        if blocks != list(range(g)):
            raise ValueError(
                f"global blocks {blocks} are not a leading prefix: the "
                f"serving policy anchors on pages every lane has written, "
                f"i.e. blocks [0..g)")
        return cls(block_size=block_size, table_width=table_width,
                   num_sliding_window_blocks=w // 2 + 1,
                   num_global_blocks=g)

    # -- arm-time compile (cold builder) --------------------------------
    def _compile_luts(self) -> np.ndarray:
        """(W, K) int32: row ``qb`` lists the ACTIVE logical block
        indices (ascending) of a query in block ``qb``, padded with -1.
        Padded entries point at block 0, skipped via the sentinel view
        position — never via a data-dependent shape."""
        lut = np.full((self.W, self.K), -1, np.int32)
        for qb in range(self.W):
            lo = max(0, qb - self.win + 1)
            act = list(range(min(self.g, qb + 1)))
            act += list(range(max(lo, self.g), qb + 1))
            lut[qb, :len(act)] = act
        return lut

    def layout(self, nb: Optional[int] = None) -> np.ndarray:
        """The policy as a (nb, nb) 0/1 block layout — the input the XLA
        ``layout_to_token_mask`` reference path consumes (parity tests
        mask a dense cache with it and compare greedy tokens)."""
        return _policy_layout(self.win, self.g, int(nb or self.W))

    def prefill_K(self, chunk: int) -> int:
        """Fixed gather width of a ``chunk``-token prefill dispatch: the
        union of every chunk query's active set is the globals plus one
        CONTIGUOUS block run (windows of consecutive query blocks
        overlap), so ``g + win + blocks-spanned-by-the-chunk`` bounds
        it.  Fixed per bucket ⇒ one compile per (bucket, final)."""
        span = (int(chunk) + self.bs - 1) // self.bs + 1
        return min(self.W, self.g + self.win + span)

    # -- per-step row maintenance (hot path: pure numpy, no device) -----
    def active_row(self, table_row: np.ndarray, pos: int):
        """Physical gather row of ONE decode lane at absolute position
        ``pos``: ``(stables, sbase)`` of width K — the physical page ids
        to gather and the absolute view position of each page's first
        token.  Pads (and window-expired holes, which ``table_row``
        already maps to the trash block) carry the sentinel position, so
        the in-jit masks zero them exactly like dense trash padding."""
        qb = min(int(pos) // self.bs, self.W - 1)
        row = self.lut[qb]
        phys = table_row[np.maximum(row, 0)].astype(np.int32)
        live = (row >= 0) & (phys != TRASH_BLOCK)
        stables = np.where(live, phys, np.int32(TRASH_BLOCK))
        sbase = np.where(live, row.astype(np.int32) * self.bs,
                         self.sentinel)
        return stables, sbase

    def prefill_active_row(self, table_row: np.ndarray, start: int,
                           n: int, bucket: int):
        """Gather row of ONE prefill chunk covering absolute positions
        ``[start, start+n)``, padded to the fixed ``prefill_K(bucket)``
        width: the union of the chunk queries' active sets — globals
        plus the contiguous run from the FIRST query's window start to
        the last query's block.  Per-query window restriction happens
        in-jit (the layout mask); this row only bounds what is
        gathered."""
        K = self.prefill_K(bucket)
        qb0 = int(start) // self.bs
        qb1 = min((int(start) + max(int(n), 1) - 1) // self.bs, self.W - 1)
        lo = max(0, qb0 - self.win + 1)
        act = list(range(min(self.g, qb1 + 1)))
        act += list(range(max(lo, self.g), qb1 + 1))
        row = np.full(K, -1, np.int32)
        row[:len(act)] = act
        phys = table_row[np.maximum(row, 0)].astype(np.int32)
        live = (row >= 0) & (phys != TRASH_BLOCK)
        stables = np.where(live, phys, np.int32(TRASH_BLOCK))
        sbase = np.where(live, row.astype(np.int32) * self.bs,
                         self.sentinel)
        return stables, sbase

    def first_active_block(self, pos: int) -> int:
        """Lowest logical block index still inside the window of a
        query at ``pos`` — everything below it (except the global
        anchors) is window-expired and early-freeable."""
        return max(0, int(pos) // self.bs - self.win + 1)

    def describe(self) -> dict:
        return {
            "num_sliding_window_blocks": self.win,
            "num_global_blocks": self.g,
            "active_pages_per_lane": self.K,
            "table_width": self.W,
            "block_size": self.bs,
        }
