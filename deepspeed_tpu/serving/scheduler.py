"""Token-level continuous batching: admit / evict between decode steps.

The scheduler is pure host-side policy — no device state.  It owns the
waiting queue (priority classes, FCFS within a class), the running-slot
map, the single in-flight chunked prefill, and the victim choice for
eviction.  The engine consults it between decode steps; every decision
is deterministic (heap keyed on (priority, submit_seq)) so parity tests
can replay exact schedules.

Policies:

- ``continuous`` (the point of this subsystem): a slot freed by a
  finished/evicted/cancelled request is refilled on the very next step;
- ``static`` (the naive baseline tools/serve_bench.py measures against):
  admission only happens while the batch gate is open — the gate opens
  when the engine fully drains and closes once the batch is formed, so
  every batch runs to its slowest member like a classic batched
  ``generate()`` call.

Eviction: when the KV pool cannot cover a growth or an admission, the
victim is the least-important (highest priority value), youngest running
request — preempted requests keep their generated tokens and re-enter
the waiting queue for a chunked re-prefill of prompt+generated (the
recompute flavor of preemption; parity tests pin that the continuation
is bit-identical).  Admission only ever preempts STRICTLY less important
requests; growth of a running sequence may preempt its own class but
never a more important one, and self-evicts when nothing else yields.

Chaos tie-in: ``chaos_cancel`` consults
runtime/resilience/chaos.serving_cancel_request so fault-injection tests
can drive request-cancellation churn through the same code path users
hit.
"""
import heapq
import itertools
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.resilience import chaos


class RequestState(Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"


@dataclass
class Request:
    """One generation request.  ``generated`` survives eviction: on
    re-admission the prefill covers prompt+generated and decoding
    continues where it stopped."""
    rid: int
    prompt: np.ndarray                 # (S0,) int32
    max_new_tokens: int
    priority: int = 0                  # lower = more important
    eos_token_id: Optional[int] = None
    seed: int = 0
    # -- reliability (deepspeed_tpu/serving/reliability.py) -------------
    deadline_s: Optional[float] = None   # relative budget (journaled)
    deadline: Optional[float] = None     # absolute, in the engine's clock
    work_budget: Optional[int] = None    # max scheduled token-writes
    # -- dynamic state --------------------------------------------------
    state: RequestState = RequestState.WAITING
    generated: List[int] = field(default_factory=list)
    prefill_done: int = 0              # pool positions already written
    slot: Optional[int] = None
    shard: int = 0
    submit_seq: int = -1
    evictions: int = 0
    work_done: int = 0                 # token-writes scheduled so far
    fair_chunks: int = 0               # chunks since last fairness pause
    finish_reason: Optional[str] = None

    @property
    def full_tokens(self) -> np.ndarray:
        """Every KNOWN token — what a (re-)prefill must cover."""
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)]) \
            if self.generated else self.prompt

    @property
    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def done(self) -> bool:
        if self.remaining_new_tokens <= 0:
            return True
        return (self.eos_token_id is not None and self.generated
                and self.generated[-1] == self.eos_token_id)

    def sort_key(self):
        return (self.priority, self.submit_seq)


class Scheduler:
    def __init__(self, max_slots: int, *, policy: str = "continuous"):
        assert policy in ("continuous", "static"), policy
        self.max_slots = int(max_slots)
        self.policy = policy
        self._seq = itertools.count()
        self._waiting: List = []                  # heap of (key, rid)
        self.requests: Dict[int, Request] = {}    # every live request
        self.running: Dict[int, Request] = {}     # slot -> Request
        self.prefilling: Optional[Request] = None
        # chunked-prefill fairness (long-context traffic): a huge prompt
        # mid-prefill can be PAUSED — it keeps its slot, blocks and
        # prefill_done, and waits here FIFO while shorter prompts take a
        # turn.  Distinct from _requeue, which resets prefill progress.
        self.paused: List[Request] = []
        # static-policy batch gate: a batch's MEMBERSHIP is fixed when it
        # forms — the budget stops freed lanes from being refilled until
        # the whole batch drains (that refill IS continuous batching)
        self._gate_open = True
        self._batch_left = self.max_slots
        self.chaos_step = 0
        # graceful drain (engine.request_drain / SIGTERM): admission
        # stops, in-flight work runs to completion, waiting requests
        # stay journaled for a successor's recover()
        self.draining = False

    # -- queue ----------------------------------------------------------
    def submit(self, req: Request) -> None:
        req.submit_seq = next(self._seq)
        req.state = RequestState.WAITING
        self.requests[req.rid] = req
        heapq.heappush(self._waiting, (req.sort_key(), req.rid))

    def _requeue(self, req: Request) -> None:
        # preempted requests keep their ORIGINAL submit_seq: FCFS age, not
        # eviction time, decides their place back in line
        req.state = RequestState.WAITING
        req.prefill_done = 0
        req.slot = None
        heapq.heappush(self._waiting, (req.sort_key(), req.rid))

    def _pop_waiting(self) -> Optional[Request]:
        while self._waiting:
            _, rid = heapq.heappop(self._waiting)
            req = self.requests.get(rid)
            if req is not None and req.state is RequestState.WAITING:
                return req
        return None

    def peek_waiting(self) -> Optional[Request]:
        while self._waiting:
            _, rid = self._waiting[0]
            req = self.requests.get(rid)
            if req is not None and req.state is RequestState.WAITING:
                return req
            heapq.heappop(self._waiting)
        return None

    def queue_depth(self) -> int:
        return sum(1 for r in self.requests.values()
                   if r.state is RequestState.WAITING)

    def waiting(self) -> List[Request]:
        """Every WAITING request (shed-victim selection + the admission
        gate's queue accounting)."""
        return [r for r in self.requests.values()
                if r.state is RequestState.WAITING]

    def queued_prefill_tokens(self) -> int:
        """Prefill tokens the engine still owes the queue: every waiting
        request's known tokens plus the in-flight prefill's remainder —
        the numerator of the predicted-TTFT admission model."""
        toks = sum(len(r.full_tokens) for r in self.requests.values()
                   if r.state is RequestState.WAITING)
        if self.prefilling is not None:
            toks += len(self.prefilling.full_tokens) \
                - self.prefilling.prefill_done
        for r in self.paused:
            toks += len(r.full_tokens) - r.prefill_done
        return toks

    def has_work(self) -> bool:
        return bool(self.running) or self.prefilling is not None \
            or bool(self.paused) or self.queue_depth() > 0

    def in_flight(self) -> bool:
        """Admitted work only (what a graceful drain must finish)."""
        return bool(self.running) or self.prefilling is not None \
            or bool(self.paused)

    # -- slots ----------------------------------------------------------
    # the engine installs a ranker so admission steers toward the slot
    # whose pool shard has the most free blocks (ties -> lowest slot);
    # with a candidate request the ranker also sees it, so prefix-cache
    # placement can prefer the shard already holding the prompt's KV;
    # without a ranker, first-free wins
    slot_ranker = None
    # the engine installs a probe that consults the pool's prefix tree at
    # admission time: cached prompt blocks are mapped read-only into the
    # new request's page table and its ``prefill_done`` advances past
    # them, so the engine skips the covered prefill chunks entirely
    prefix_probe = None

    def free_slot(self, req: Optional[Request] = None) -> Optional[int]:
        taken = set(self.running)
        if self.prefilling is not None and self.prefilling.slot is not None:
            taken.add(self.prefilling.slot)
        for p in self.paused:      # paused prefills keep their slot
            if p.slot is not None:
                taken.add(p.slot)
        free = [s for s in range(self.max_slots) if s not in taken]
        if not free:
            return None
        if self.slot_ranker is None:
            return free[0]
        return max(free, key=lambda s: (self.slot_ranker(s, req), -s))

    def may_admit(self) -> bool:
        if self.draining:
            return False
        if self.policy == "continuous":
            return True
        return self._gate_open

    def on_drained(self) -> None:
        """Engine signal: no running, no prefilling — a static batch may
        form again."""
        if not self.running and self.prefilling is None:
            self._gate_open = True
            self._batch_left = self.max_slots

    def start_admission(self) -> Optional[Request]:
        """Pop the next admissible request into the PREFILL state (the
        engine assigns shard + drives chunks).  None when no slot, no
        candidate, or the static gate is closed."""
        if self.prefilling is not None or not self.may_admit():
            return None
        slot = self.free_slot(self.peek_waiting())
        if slot is None:
            return None
        req = self._pop_waiting()
        if req is None:
            if self.policy == "static" and (self.running or self.prefilling):
                self._gate_open = False   # batch formed: queue exhausted
            return None
        if self.policy == "static":
            self._batch_left -= 1
            if self._batch_left <= 0:
                self._gate_open = False   # batch formed: slots budgeted
        req.state = RequestState.PREFILL
        req.slot = slot
        self.prefilling = req
        if self.prefix_probe is not None:
            # admission consults the prefix tree: cached prompt blocks
            # are attached read-only and their prefill chunks skipped
            self.prefix_probe(req)
        return req

    def promote(self, req: Request) -> None:
        """Prefill finished: the request joins the decode batch."""
        assert req is self.prefilling
        self.prefilling = None
        req.state = RequestState.RUNNING
        self.running[req.slot] = req

    def adopt_running(self, req: Request, slot: int) -> None:
        """Adopt a migrated-in request DIRECTLY into the decode batch
        (its KV arrived as a paged-block transfer — no prefill here).
        FCFS age restarts in this scheduler's sequence space: the
        request is older than anything submitted after it arrives,
        exactly like a normal admission at this instant."""
        assert slot not in self.running, slot
        assert req.rid not in self.requests, req.rid
        req.submit_seq = next(self._seq)
        req.state = RequestState.RUNNING
        req.slot = slot
        self.requests[req.rid] = req
        self.running[slot] = req

    def drop_prefill(self, req: Request, *, requeue: bool) -> None:
        assert req is self.prefilling
        self.prefilling = None
        if self.policy == "static":
            # the dropped request was the LAST admission: hand its batch
            # budget back (and reopen the gate it may just have closed),
            # or repeated drop/re-admit cycles shrink the batch
            self._batch_left += 1
            self._gate_open = True
        if requeue:
            self._requeue(req)

    # -- chunked-prefill fairness ---------------------------------------
    def pause_prefill(self, req: Request) -> None:
        """Yield the prefill lane mid-prompt: the request keeps its slot,
        pool blocks and ``prefill_done`` (no recompute — unlike
        preemption) and joins the paused FIFO; the lane is free for a
        shorter prompt's turn.  The fairness quantum in the engine
        decides when this fires."""
        assert req is self.prefilling
        self.prefilling = None
        req.fair_chunks = 0
        self.paused.append(req)

    def resume_prefill(self) -> Optional[Request]:
        """Resume the oldest paused prefill (FIFO) when the lane is
        idle.  The engine calls this AFTER trying fresh admissions, so
        paused giants and queued newcomers round-robin the lane."""
        if self.prefilling is not None or not self.paused:
            return None
        req = self.paused.pop(0)
        self.prefilling = req
        return req

    # -- eviction / completion ------------------------------------------
    def victim(self, *, for_req: Request, admission: bool,
               shard: Optional[int] = None) -> Optional[Request]:
        """Who to preempt so ``for_req`` can take blocks.  Admission only
        preempts STRICTLY less important runners; growth may preempt its
        own class (youngest first) but never itself.  ``shard`` filters
        to victims whose blocks actually help (same pool shard)."""
        candidates = [r for r in self.running.values() if r is not for_req]
        if shard is not None:
            candidates = [r for r in candidates if r.shard == shard]
        if admission:
            candidates = [r for r in candidates
                          if r.priority > for_req.priority]
        else:
            candidates = [r for r in candidates
                          if r.priority >= for_req.priority]
        if not candidates:
            return None
        # least important first, then youngest (largest submit_seq)
        return max(candidates,
                   key=lambda r: (r.priority, r.submit_seq))

    def preempt(self, req: Request) -> None:
        """Remove a RUNNING request and requeue it (tokens preserved)."""
        assert req.slot in self.running and self.running[req.slot] is req
        del self.running[req.slot]
        req.evictions += 1
        self._requeue(req)

    def finish(self, req: Request, reason: str = "finished") -> None:
        if req.slot is not None and self.running.get(req.slot) is req:
            del self.running[req.slot]
        if req is self.prefilling:
            self.prefilling = None
        if req in self.paused:
            self.paused.remove(req)
        # every terminal-without-completing reason (cancelled, and the
        # reliability layer's expired/budget/shed/poisoned) lands in the
        # CANCELLED state; only "finished" means the request completed
        req.state = RequestState.FINISHED if reason == "finished" \
            else RequestState.CANCELLED
        req.finish_reason = reason
        # req.slot is deliberately NOT cleared: the engine still needs it
        # to scrub the slot's host arrays (active mask, page-table row)
        self.requests.pop(req.rid, None)

    def cancel(self, rid: int) -> Optional[Request]:
        """Cancel a request in ANY live state; returns it (the engine
        frees its pool blocks) or None if unknown/already finished."""
        req = self.requests.get(rid)
        if req is None:
            return None
        self.finish(req, reason="cancelled")
        return req

    def chaos_cancel(self) -> Optional[int]:
        """Chaos-driven cancellation: when an armed ChaosPlan fires at
        this scheduler step, cancel the YOUNGEST running request
        (deterministic victim) through the normal cancel path."""
        self.chaos_step += 1
        if not chaos.serving_cancel_request(self.chaos_step):
            return None
        if not self.running:
            return None
        victim = max(self.running.values(), key=lambda r: r.submit_seq)
        chaos.record_serving_cancel(victim.rid)
        return victim.rid
