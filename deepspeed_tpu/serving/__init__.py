"""Continuous-batching inference serving (the DeepSpeed-Inference analog).

- :mod:`kv_cache` — paged KV pool: a fixed block pool plus per-sequence
  page tables, donated into the decode jit and updated in place, with
  optional int8 storage via runtime/quantization.py;
- :mod:`scheduler` — token-level continuous batching: admission, chunked
  prefill, priority classes, eviction and cancellation between steps;
- :mod:`engine` — :class:`InferenceEngine`: ONE fixed-shape batched
  decode jit with slot masking (requests joining/leaving never
  recompile) plus length-bucketed prefill jits;
- :mod:`metrics` — TTFT / TPOT / throughput / goodput / KV-pool
  occupancy, exposed via ``InferenceEngine.serving_report()``;
- :mod:`reliability` — deadlines/work budgets, SLO-aware admission and
  load shedding, graceful drain, the crash-recovery request journal,
  and per-request poison quarantine;
- :mod:`fleet` — :class:`FleetRouter`: a host-level router over K
  replicas — SLO-aware dispatch, replica failure detection with a
  circuit breaker, journal-backed request migration, and role-tagged
  prefill/decode replicas with paged-block KV handoff.
"""
from deepspeed_tpu.serving.engine import InferenceEngine
from deepspeed_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                         ReplicaHandle)
from deepspeed_tpu.serving.kv_cache import PagedKVPool
from deepspeed_tpu.serving.metrics import CompilationCounter, ServingMetrics
from deepspeed_tpu.serving.reliability import (ReliabilityConfig,
                                               RequestJournal)
from deepspeed_tpu.serving.scheduler import Request, Scheduler

__all__ = ["InferenceEngine", "PagedKVPool", "Scheduler", "Request",
           "ServingMetrics", "CompilationCounter", "ReliabilityConfig",
           "RequestJournal", "FleetRouter", "FleetConfig",
           "ReplicaHandle"]
