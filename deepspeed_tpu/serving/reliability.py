"""Serving reliability layer: deadlines, SLO admission, drain, recovery.

The PR 5 continuous-batching engine is a fair-weather system on its own:
no deadlines, no admission backpressure, no drain, and a host crash
loses every in-flight request.  This module is the serving analog of the
training side's resilience stack (atomic checkpoints, watchdog, chaos,
preemption) — graceful DEGRADATION instead of congestion collapse:

- **Deadlines & work budgets** — every request may carry a TTLT
  deadline (seconds from submit) and a work budget (total scheduled
  token-writes: prefill chunks + decode steps, so eviction re-prefill
  loops are bounded too).  Both are enforced at step boundaries by the
  engine's ``_enforce_deadlines``: expired requests are aborted with an
  explicit reason, their KV blocks freed — a stuck request can never
  wedge the shared decode batch.
- **SLO-aware admission / load shedding** — a predicted-TTFT gate: the
  queue's remaining prefill work (in steps of ``prefill_chunk``) times
  the measured per-step time (the TPOT proxy — one decode step emits
  one token per running lane).  When the prediction exceeds the SLO the
  gate shed the LOWEST-priority waiting work first and rejects the
  newcomer only when it is itself the least important.  Backpressure is
  visible in ``serving_report()["reliability"]``.
- **Request journal / crash recovery** — an append-only JSONL journal
  (prompt, sampling seed, priority, deadline, generated tokens)
  committed once per step.  ``InferenceEngine.recover()`` replays it on
  a fresh engine and re-submits every live request through the SAME
  eviction re-prefill path, so greedy continuations are bit-identical
  to the uninterrupted run.
- **Poison quarantine** — per-request fault isolation: non-finite
  logits (numeric blow-up in one lane) abort THAT request with reason
  ``poisoned`` instead of poisoning the shared batch.  Detection rides
  the decode jit's existing batched stats fetch — zero new host syncs.

Arming follows the repo's DISARMED discipline (`_arm_shedding`), and the
whole layer preserves the engine's core contracts: ONE fixed-shape
decode jit, zero recompiles across churn, zero collectives in the
compiled step.
"""
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

# terminal non-finished statuses this layer introduces (results["status"])
ABORT_EXPIRED = "expired"      # deadline passed before completion
ABORT_BUDGET = "budget"        # work budget exhausted (incl. re-prefill)
ABORT_SHED = "shed"            # dropped by the overload guard
ABORT_POISONED = "poisoned"    # non-finite logits quarantined
ABORT_REASONS = (ABORT_EXPIRED, ABORT_BUDGET, ABORT_SHED, ABORT_POISONED)


@dataclass(frozen=True)
class ReliabilityConfig:
    """Knobs for the serving reliability layer (all optional — the
    zero-config default arms nothing and costs one ``is None`` per
    step, mirroring the chaos hooks)."""
    slo_ttft_s: Optional[float] = None      # admission gate target
    slo_headroom: float = 1.0               # gate fires at slo * headroom
    default_deadline_s: Optional[float] = None
    default_work_budget: Optional[int] = None
    journal_path: Optional[str] = None
    journal_fsync: bool = False             # fsync each step commit


class RequestJournal:
    """Append-only JSONL request journal (the serving analog of the
    training checkpoint, at request granularity).

    Record kinds::

        {"op": "submit", "rid", "prompt", "max_new", "priority",
         "eos", "seed", "deadline_s", "work_budget", "generated",
         "work_done"}
        {"op": "tok", "rid", "t": [tokens accepted this step]}
        {"op": "end", "rid", "status"}

    ``deadline_s`` is the request's RELATIVE budget: wall clocks are not
    comparable across processes (``time.monotonic``), so recovery grants
    a fresh deadline of the same length — documented, honest semantics.
    ``work_done`` is different: the work BUDGET bounds total scheduled
    token-writes across the request's whole life, so it must CARRY OVER
    — the submit record journals the work already charged at submission
    and :meth:`replay` adds the work provably done since (committed
    decode steps, plus the prefill that demonstrably ran if any token
    was committed), so repeated crash-migrate cycles keep accumulating
    against the bound instead of resetting it.
    Token records are buffered per step and flushed by :meth:`commit`
    (once per serving step), so a crash loses at most the current
    step's tokens and the journal is always record-aligned.
    """

    def __init__(self, path: str, *, fsync: bool = False):
        self.path = path
        self._fsync = bool(fsync)
        self._fh = open(path, "a", encoding="utf-8")
        self._pending: Dict[int, List[int]] = {}   # rid -> step's tokens
        self._live = set()                         # rids submitted, not ended
        self._order: List[int] = []                # flush order within a step

    # -- write side -----------------------------------------------------
    def record_submit(self, req) -> None:
        self._live.add(req.rid)
        self._write({
            "op": "submit", "rid": req.rid,
            "prompt": [int(t) for t in req.prompt],
            "max_new": int(req.max_new_tokens),
            "priority": int(req.priority),
            "eos": (None if req.eos_token_id is None
                    else int(req.eos_token_id)),
            "seed": int(req.seed),
            "deadline_s": req.deadline_s,
            "work_budget": req.work_budget,
            # non-empty for recovered requests: the re-prefill baseline
            "generated": [int(t) for t in req.generated],
            # work already charged at submission (non-zero for
            # recovered/migrated requests) — budgets carry over
            "work_done": int(req.work_done),
        })
        # the returned rid is an ACCEPTANCE acknowledgment — the submit
        # record must survive a crash in the same step, so it flushes
        # immediately (tokens stay buffered until the step commit)
        self._fh.flush()

    def record_token(self, rid: int, token: int) -> None:
        if rid not in self._pending:
            self._pending[rid] = []
            self._order.append(rid)
        self._pending[rid].append(int(token))

    def record_end(self, rid: int, status: str) -> None:
        self._flush_tokens(rid)
        self._live.discard(rid)
        self._write({"op": "end", "rid": rid, "status": status})
        # an end record changes what replay() migrates — a "migrated"
        # end left buffered while the host crashes would re-place a
        # request that already lives on another replica, so end records
        # flush immediately, same rationale as submit records
        self._fh.flush()

    def commit(self) -> None:
        """Step-boundary durability point: flush every buffered token
        record, then push the file to the OS (optionally fsync)."""
        for rid in list(self._order):
            self._flush_tokens(rid)
        self._order.clear()
        self._fh.flush()
        if self._fsync:
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        self.commit()
        self._fh.close()

    @property
    def depth(self) -> int:
        """Live (journaled, not yet ended) requests."""
        return len(self._live)

    def _flush_tokens(self, rid: int) -> None:
        toks = self._pending.pop(rid, None)
        if toks:
            self._write({"op": "tok", "rid": rid, "t": toks})

    def _write(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")

    # -- read side ------------------------------------------------------
    @staticmethod
    def replay(path: str) -> List[dict]:
        """Reconstruct the LIVE request set from a journal: submit
        records (in original FCFS order) minus ended ones, each with
        every committed generated token.  Tolerates a torn final line
        (the crash can land mid-write of the last record).

        ``work_done`` restoration (budgets carry over, deadlines do
        not): the submit record's journaled baseline, plus one work
        unit per token committed since (each committed token is one
        scheduled decode write), plus — when any token WAS committed —
        the prefill token-writes that demonstrably ran to produce it
        (prompt + the tokens the submit record already carried).  A
        request that never produced a token keeps its baseline alone.
        The estimate is deliberately >= the work actually scheduled, so
        repeated crash-migrate cycles converge ON OR BEFORE the budget
        bound, never past it."""
        live: Dict[int, dict] = {}
        order: List[int] = []
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    logger.warning(
                        "RequestJournal.replay: torn trailing record in "
                        "%s ignored (crash mid-write)", path)
                    continue
                op, rid = rec.get("op"), rec.get("rid")
                if op == "submit":
                    entry = dict(rec)
                    entry["generated"] = list(rec.get("generated", []))
                    entry["work_done"] = int(rec.get("work_done", 0))
                    entry["_committed_toks"] = 0
                    live[rid] = entry
                    order.append(rid)
                elif op == "tok" and rid in live:
                    live[rid]["generated"].extend(rec["t"])
                    live[rid]["_committed_toks"] += len(rec["t"])
                elif op == "end":
                    live.pop(rid, None)
        out = []
        for r in order:
            if r not in live:
                continue
            e = live[r]
            committed = e.pop("_committed_toks")
            if committed:
                prefill_paid = len(e.get("prompt", [])) \
                    + (len(e["generated"]) - committed)
                e["work_done"] += committed + prefill_paid
            out.append(e)
        return out

    @staticmethod
    def replay_many(paths) -> List[dict]:
        """Merge the live request sets of SEVERAL journals — the fleet
        router's whole-fleet recovery path, where each dead replica left
        its own journal.  Replicas hold DISTINCT rid namespaces (the
        router assigns globally-unique rids in arrival order), so the
        global FCFS order across journals IS ascending rid order; each
        journal individually tolerates its own torn final record.  A rid
        appearing live in more than one journal (a request migrated
        mid-flight whose source end record was lost with the crash)
        resolves to the LATER journal in ``paths`` — the router lists
        journals in migration order, so the freshest copy wins."""
        merged: Dict[int, dict] = {}
        for path in paths:
            for e in RequestJournal.replay(path):
                merged[e["rid"]] = e
        return [merged[r] for r in sorted(merged)]


class Reliability:
    """Per-engine reliability orchestrator: owns the journal, the
    admission gate state, and the abort counters.  The engine calls the
    ``on_*`` hooks; everything here is pure host work (no device
    syncs — graftlint holds these fns to the hot-path bar)."""

    def __init__(self, engine, config: ReliabilityConfig):
        self.engine = engine
        self.config = config
        self.journal: Optional[RequestJournal] = None
        if config.journal_path:
            self.journal = RequestJournal(config.journal_path,
                                          fsync=config.journal_fsync)
        self._arm_shedding()
        self.aborts = {r: 0 for r in ABORT_REASONS}
        self.rejected_at_admission = 0
        self.predicted_ttft_hist: List[float] = []
        self.last_predicted_ttft_s: Optional[float] = None
        self.overloaded = False

    # -- arming (DISARMED discipline) -----------------------------------
    def _arm_shedding(self) -> None:
        """Arm the SLO admission gate, or warn loudly (DISARMED) naming
        the blocker — the armed-or-warns discipline graftlint enforces
        on every ``_arm_*``/``*_armed`` site."""
        self.shedding_armed = False
        cfg = self.config
        if cfg.slo_ttft_s is None:
            return
        if cfg.slo_ttft_s <= 0:
            logger.warning(
                "serving reliability: SLO shedding DISARMED — "
                "slo_ttft_s=%g is not positive; admission gate off, "
                "overload will queue unboundedly.", cfg.slo_ttft_s)
            return
        if self.engine.scheduler.policy != "continuous":
            logger.warning(
                "serving reliability: SLO shedding DISARMED — the "
                "'%s' scheduler policy gates admission on batch "
                "membership, which the predicted-TTFT model does not "
                "describe; use policy='continuous'.",
                self.engine.scheduler.policy)
            return
        self.shedding_armed = True

    # -- predicted TTFT (the admission model) ---------------------------
    def measured_tpot_s(self) -> Optional[float]:
        """Measured per-token time: the finished-request TPOT when
        available, else the per-step wall-time EMA (one decode step =
        one token per running lane, so they coincide at steady state)."""
        m = self.engine.metrics
        return m.tpot() or m.step_time()

    def predicted_ttft_s(self, extra_tokens: int = 0) -> Optional[float]:
        """Queue-depth x measured-TPOT prediction of a new arrival's
        TTFT: steps to absorb every queued prefill token at one
        ``prefill_chunk`` per step (plus one final-chunk step per queued
        request), times the measured step time.  None until a step time
        has been measured (an idle engine admits freely)."""
        tpot = self.measured_tpot_s()
        if tpot is None:
            return None
        sch = self.engine.scheduler
        chunk = self.engine.prefill_chunk
        toks = sch.queued_prefill_tokens() + int(extra_tokens)
        steps = -(-toks // chunk) + len(sch.waiting())
        return steps * tpot

    # -- hooks the engine drives ----------------------------------------
    def on_submit(self, req) -> str:
        """Admission decision for ``req``: ``"admit"`` or ``"reject"``.
        Under predicted overload, lower-priority WAITING work is shed
        (aborted with reason ``shed``) before the newcomer is rejected;
        the newcomer is only turned away when it is itself the least
        important."""
        if not self.shedding_armed:
            if self.journal is not None:
                self.journal.record_submit(req)
            return "admit"
        limit = self.config.slo_ttft_s * self.config.slo_headroom
        extra = len(req.full_tokens)     # prompt (+ recovered generated)
        pred = self.predicted_ttft_s(extra_tokens=extra)
        if pred is not None:
            self.last_predicted_ttft_s = pred
            self.predicted_ttft_hist.append(pred)
        while pred is not None and pred > limit:
            victim = self._shed_victim(than=req)
            if victim is None:
                break
            self.engine._abort(victim, ABORT_SHED)
            pred = self.predicted_ttft_s(extra_tokens=extra)
        self.overloaded = pred is not None and pred > limit
        if self.overloaded:
            self.rejected_at_admission += 1
            self.aborts[ABORT_SHED] += 1
            return "reject"
        if self.journal is not None:
            self.journal.record_submit(req)
        return "admit"

    def _shed_victim(self, *, than):
        """Least-important (largest priority value), youngest WAITING
        request STRICTLY less important than ``than`` — shedding never
        touches running work (their KV investment is sunk) nor peers of
        equal importance (FCFS stays honest within a class)."""
        waiting = [r for r in self.engine.scheduler.waiting()
                   if r.priority > than.priority]
        if not waiting:
            return None
        return max(waiting, key=lambda r: (r.priority, r.submit_seq))

    def on_token(self, req, token: int) -> None:
        if self.journal is not None:
            self.journal.record_token(req.rid, token)

    def on_finish(self, req, reason: str) -> None:
        if reason in self.aborts:
            self.aborts[reason] += 1
        if self.journal is not None:
            self.journal.record_end(req.rid, reason)

    def on_step_end(self) -> None:
        """Step-boundary durability point (journal commit)."""
        if self.journal is not None:
            self.journal.commit()

    # -- reporting ------------------------------------------------------
    def journal_depth(self) -> int:
        return self.journal.depth if self.journal is not None else 0

    def report(self) -> dict:
        m = self.engine.metrics
        hist = self.predicted_ttft_hist
        return {
            "armed": {
                "shedding": self.shedding_armed,
                "journal": self.journal is not None,
                "deadlines": self.config.default_deadline_s is not None,
            },
            "aborts": dict(self.aborts),
            "admission": {
                "slo_ttft_s": self.config.slo_ttft_s,
                "slo_headroom": self.config.slo_headroom,
                "overloaded": self.overloaded,
                "rejected": self.rejected_at_admission,
                "predicted_ttft_s": {
                    "last": self.last_predicted_ttft_s,
                    "mean": (sum(hist) / len(hist)) if hist else None,
                },
                "measured_ttft_s": {
                    "mean": (sum(m.ttft) / len(m.ttft)) if m.ttft else None,
                },
                "measured_tpot_s": self.measured_tpot_s(),
            },
            "journal_depth": self.journal_depth(),
            "journal_path": (self.journal.path
                             if self.journal is not None else None),
            "draining": self.engine.scheduler.draining,
        }
