"""Serving metrics: TTFT, TPOT, throughput, queue depth, pool occupancy.

Follows the engine's ``_last_metrics`` / ``comm_volume_report()`` idiom:
the engine feeds observations as plain host floats (never a device sync
— the decode token fetch already happened, batched, once per step) and
``report()`` assembles the summary dict that
``InferenceEngine.serving_report()`` returns.

Also home of :class:`CompilationCounter`, the compilation-count hook the
recompile-guard acceptance test uses: jax fires one
``/jax/core/compile/backend_compile_duration`` monitoring event per XLA
backend compilation, so steady-state serving (requests joining/leaving a
warmed engine) must count ZERO inside the guard window.
"""
import time
from typing import Dict, List

from deepspeed_tpu.telemetry.metrics import Histogram, nearest_rank

_MONITORING_KEY = "backend_compile"
_counters: List["CompilationCounter"] = []
_listener_installed = False


def _on_event(name, *args, **kwargs):
    if _MONITORING_KEY in name:
        for c in _counters:
            c.count += 1


def _install_listener():
    # jax.monitoring has no unregister; install ONE module-level listener
    # forever and let counters arm/disarm themselves on the host side
    global _listener_installed
    if _listener_installed:
        return
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_on_event)
    _listener_installed = True


class CompilationCounter:
    """Counts XLA backend compilations while active (context manager)."""

    def __init__(self):
        self.count = 0

    def __enter__(self):
        _install_listener()
        self.count = 0
        _counters.append(self)
        return self

    def __exit__(self, *exc):
        _counters.remove(self)
        return False


def _mean(xs):
    return sum(xs) / len(xs) if xs else None


def _pct(xs, q):
    """Nearest-rank percentile, total over its edge cases: empty input
    is ``None`` (never raises), a single sample IS every percentile,
    and q is clamped to [0, 1] — the overload guard reads p50/p95 off
    arbitrary slices of a run, including before the first token.

    Delegates to the repo-wide shared implementation
    (``telemetry.metrics.nearest_rank``, the same one the telemetry
    ``Histogram`` percentiles use) — the edge-case contract above is
    pinned by test_serving_reliability.py and test_telemetry.py."""
    return nearest_rank(xs, q)


class ServingMetrics:
    """Per-request latency + per-step utilization accounting."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.reset()

    def reset(self):
        self._arrival: Dict[int, float] = {}
        self._first_token: Dict[int, float] = {}
        self._last_token: Dict[int, float] = {}
        self._tokens: Dict[int, int] = {}
        self.ttft: List[float] = []
        self.completed = 0
        self.cancelled = 0
        self.migrated = 0              # handed off to another replica
        self.migrated_tokens = 0       # tokens billed at the destination
        self.evictions = 0
        # reliability-layer abort counters, keyed by abort reason
        # (expired / budget / shed / poisoned)
        self.aborted: Dict[str, int] = {}
        self.steps = 0
        self.decode_steps = 0
        self.slot_steps = 0            # decode lanes dispatched (incl. idle)
        self.active_slot_steps = 0     # decode lanes carrying a request
        self.total_tokens = 0          # generated tokens, all requests
        self.useful_tokens = 0         # tokens of requests that FINISHED
        self.wasted_tokens = 0         # tokens of aborted/shed/cancelled reqs
        # per-step utilization series ride the shared telemetry
        # Histogram (bounded reservoir; count/mean/max exact over the
        # whole run) instead of three ad-hoc unbounded lists
        self._queue_depth = Histogram()
        self._occupancy = Histogram()
        self._fragmentation = Histogram()
        self._t0 = None
        self._t_end = None
        self._step_dt_ema = None       # EMA of inter-step wall time
        # prefix cache (ISSUE 17): admission-time tree consults
        self.prefill_computed_tokens = 0   # positions actually dispatched
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_avoided_tokens = 0     # positions served from cache
        self.readmit_avoided_tokens = 0    # of those: journal-replay /
        #                                    migration re-submissions
        # speculative decoding (ISSUE 17): draft-verify accounting
        self.spec_verify_steps = 0         # verify dispatches (lane-steps)
        self.spec_accepted_tokens = 0      # tokens delivered by verifies
        self.spec_accept_hist: Dict[int, int] = {}  # accepted-length counts
        # sparse page attention (ISSUE 20): per-dispatch gather accounting
        self.sparse_gathered_pages = 0     # pages the jits actually gather
        self.sparse_dense_pages = 0        # what dense gathering would cost
        self.sparse_active_pages = 0       # non-padded entries (policy live)
        self.sparse_lane_steps = 0         # decode lanes the gathers served
        self.window_expired_frees = 0      # blocks early-freed by the window
        # per-class TTFT (long vs short under long-context contention)
        self._class_of: Dict[int, str] = {}
        self.ttft_by_class: Dict[str, List[float]] = {}

    # -- request lifecycle ---------------------------------------------
    def record_submit(self, rid, klass=None):
        """``klass`` (e.g. "short"/"long" by prompt length) buckets this
        request's eventual TTFT sample — the per-class view is how the
        long-context bench proves chatty short requests keep their
        latency while huge prompts prefill."""
        self._arrival[rid] = self._clock()
        if klass is not None:
            self._class_of[rid] = str(klass)

    def record_token(self, rid):
        now = self._clock()
        if rid not in self._first_token:
            self._first_token[rid] = now
            if rid in self._arrival:
                sample = now - self._arrival[rid]
                self.ttft.append(sample)
                klass = self._class_of.get(rid)
                if klass is not None:
                    self.ttft_by_class.setdefault(klass, []).append(sample)
        self._last_token[rid] = now
        self._tokens[rid] = self._tokens.get(rid, 0) + 1
        self.total_tokens += 1

    def record_finish(self, rid, reason="finished"):
        """Terminal accounting.  Only ``finished`` tokens count toward
        goodput — everything a cancelled/expired/shed/poisoned request
        generated was work the engine cannot bill, and the overload
        guard needs that honest denominator.  ``migrated`` is neither:
        the request left ALIVE for another replica, so its tokens are
        neither useful nor wasted here — they complete (and bill) at
        the destination."""
        if reason == "finished":
            self.completed += 1
            self.useful_tokens += self._tokens.get(rid, 0)
            return
        if reason == "migrated":
            self.migrated += 1
            self.migrated_tokens += self._tokens.pop(rid, 0)
            return
        self.wasted_tokens += self._tokens.get(rid, 0)
        if reason == "cancelled":
            self.cancelled += 1
        else:
            self.aborted[reason] = self.aborted.get(reason, 0) + 1

    def record_eviction(self, rid):
        self.evictions += 1

    def record_prefill(self, n_tokens):
        """Prefill positions actually DISPATCHED to the device — the
        numerator the prefix-cache ratio guard compares across cache
        on/off runs (cached positions never reach this counter)."""
        self.prefill_computed_tokens += int(n_tokens)

    def record_prefix_lookup(self, avoided_tokens, *, readmit=False):
        """One admission-time prefix-tree consult; ``avoided_tokens`` is
        the number of prompt positions served from cache (0 = miss).
        ``readmit`` marks journal-replay/migration re-submissions —
        counted separately so ``fleet_report()`` can attribute the
        recovery-path savings honestly."""
        self.prefix_lookups += 1
        if avoided_tokens > 0:
            self.prefix_hits += 1
            self.prefix_avoided_tokens += int(avoided_tokens)
            if readmit:
                self.readmit_avoided_tokens += int(avoided_tokens)

    def record_verify(self, accepted, lanes=1):
        """One speculative verify outcome per lane: ``accepted`` tokens
        (1..draft_len+1) were delivered by a single batched dispatch."""
        self.spec_verify_steps += int(lanes)
        self.spec_accepted_tokens += int(accepted)
        self.spec_accept_hist[int(accepted)] = \
            self.spec_accept_hist.get(int(accepted), 0) + 1

    def record_gather(self, lanes, gathered_pages, dense_pages,
                      active_pages=None):
        """One decode dispatch's KV gather bill: ``gathered_pages`` is
        what the jit actually pulled (lanes × K under a sparse policy,
        lanes × W dense), ``dense_pages`` what the dense path would have
        pulled for the same lanes — the A/B numerator/denominator of the
        ≥4x acceptance gate.  ``active_pages`` counts the non-padded
        entries (pages the policy genuinely needs)."""
        self.sparse_lane_steps += int(lanes)
        self.sparse_gathered_pages += int(gathered_pages)
        self.sparse_dense_pages += int(dense_pages)
        if active_pages is not None:
            self.sparse_active_pages += int(active_pages)

    def record_window_expired(self, n_blocks):
        """Blocks the pool early-freed because they fell below every
        remaining query's sliding window."""
        self.window_expired_frees += int(n_blocks)

    def class_ttft_p95(self, klass):
        """p95 TTFT of one request class (None before its first token —
        honest gap, not 0)."""
        xs = self.ttft_by_class.get(klass)
        return _pct(xs, .95) if xs else None

    def active_page_fraction(self):
        """Gathered pages as a fraction of the dense-equivalent gather
        (1.0 = dense, 1/K-ish under an effective window).  None before
        the first recorded gather (honest gap, not 0)."""
        if not self.sparse_dense_pages:
            return None
        return self.sparse_gathered_pages / self.sparse_dense_pages

    def tokens_per_verify(self):
        """Mean tokens delivered per speculative verify dispatch (the
        speedup signal: 1.0 = speculation never helps).  None before the
        first verify."""
        if not self.spec_verify_steps:
            return None
        return self.spec_accepted_tokens / self.spec_verify_steps

    def prefix_hit_rate(self):
        """Fraction of admission-time prefix lookups that found cached
        blocks.  None before the first lookup (honest gap, not 0)."""
        if not self.prefix_lookups:
            return None
        return self.prefix_hits / self.prefix_lookups

    # -- per step -------------------------------------------------------
    def record_step(self, *, queue_depth, running, slots, occupancy,
                    fragmentation, decoded):
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        elif self._t_end is not None:
            dt = now - self._t_end
            self._step_dt_ema = dt if self._step_dt_ema is None \
                else 0.8 * self._step_dt_ema + 0.2 * dt
        self._t_end = now
        self.steps += 1
        if decoded:
            self.decode_steps += 1
            self.slot_steps += slots
            self.active_slot_steps += running
        self._queue_depth.add(queue_depth)
        self._occupancy.add(occupancy)
        self._fragmentation.add(fragmentation)

    # -- summary --------------------------------------------------------
    def ttft_of(self, rid):
        """TTFT of ONE request (None when it has not produced a first
        token here, or arrived elsewhere — a migrated-in request keeps
        its TTFT at the replica that admitted it)."""
        if rid in self._first_token and rid in self._arrival:
            return self._first_token[rid] - self._arrival[rid]
        return None

    def export_timing(self, rid):
        """``(arrival, first_token)`` stamps of a migrating request —
        in-process fleet replicas share one clock, so the stamps carry
        across replicas verbatim."""
        return self._arrival.get(rid), self._first_token.get(rid)

    def adopt_timing(self, rid, arrival_s, first_token_s):
        """Carry a migrated-in request's original stamps so the fleet
        counts exactly ONE TTFT sample per rid: restoring the arrival
        makes the eventual sample include time spent waiting on the
        dead/drained source, and restoring the first-token stamp (when
        the source already emitted it) suppresses a duplicate sample
        here — :meth:`record_token` only samples an unseen rid."""
        if arrival_s is not None:
            self._arrival[rid] = arrival_s
        if first_token_s is not None and rid not in self._first_token:
            self._first_token[rid] = first_token_s

    def step_time(self):
        """EMA of the wall time between consecutive serving steps — the
        admission gate's measured-TPOT proxy (one decode step emits one
        token per running lane).  None before two steps completed."""
        return self._step_dt_ema

    def tpot(self):
        """Mean time-per-output-token over requests with >= 2 tokens."""
        spans, counts = 0.0, 0
        for rid, n in self._tokens.items():
            if n >= 2 and rid in self._first_token:
                spans += self._last_token[rid] - self._first_token[rid]
                counts += n - 1
        return spans / counts if counts else None

    def report(self) -> dict:
        wall = (self._t_end - self._t0) if self._t0 is not None else 0.0
        return {
            "requests": {
                "completed": self.completed,
                "cancelled": self.cancelled,
                "migrated": self.migrated,
                "evictions": self.evictions,
                "aborted": dict(self.aborted),
            },
            "ttft_s": {"mean": _mean(self.ttft), "p50": _pct(self.ttft, .5),
                       "p95": _pct(self.ttft, .95),
                       "max": max(self.ttft) if self.ttft else None},
            "tpot_s": self.tpot(),
            "tokens": {"generated": self.total_tokens,
                       "useful": self.useful_tokens,
                       "wasted": self.wasted_tokens,
                       "migrated_out": self.migrated_tokens},
            "throughput": {
                "wall_s": wall,
                "tokens_per_s": (self.total_tokens / wall) if wall > 0
                else None,
                # hardware-time proxy, deterministic on CPU: how full the
                # fixed decode batch ran (1.0 = every lane of every decode
                # dispatch carried a live request)
                "tokens_per_slot_step": (self.total_tokens / self.slot_steps)
                if self.slot_steps else None,
                # GOODPUT: only finished requests' tokens over the same
                # denominator — what the overload guard compares against
                # the steady-state baseline (shed/expired work is not
                # throughput, it is waste)
                "goodput_tokens_per_slot_step":
                    (self.useful_tokens / self.slot_steps)
                    if self.slot_steps else None,
                "useful_fraction": (self.useful_tokens / self.total_tokens)
                if self.total_tokens else None,
                "slot_utilization": (self.active_slot_steps / self.slot_steps)
                if self.slot_steps else None,
            },
            "steps": {"total": self.steps, "decode": self.decode_steps},
            "prefix_cache": {
                "lookups": self.prefix_lookups,
                "hits": self.prefix_hits,
                "hit_rate": self.prefix_hit_rate(),
                "avoided_prefill_tokens": self.prefix_avoided_tokens,
                "readmit_avoided_prefill_tokens":
                    self.readmit_avoided_tokens,
                "prefill_tokens_computed": self.prefill_computed_tokens,
            },
            "speculative": {
                "verify_steps": self.spec_verify_steps,
                "accepted_tokens": self.spec_accepted_tokens,
                "tokens_per_verify": self.tokens_per_verify(),
                "accept_len_hist": dict(sorted(
                    self.spec_accept_hist.items())),
            },
            "sparse_context": {
                "gathered_pages": self.sparse_gathered_pages,
                "dense_equivalent_pages": self.sparse_dense_pages,
                "active_page_fraction": self.active_page_fraction(),
                "gathered_pages_per_lane_step":
                    (self.sparse_gathered_pages / self.sparse_lane_steps)
                    if self.sparse_lane_steps else None,
                "active_pages_per_lane_step":
                    (self.sparse_active_pages / self.sparse_lane_steps)
                    if self.sparse_lane_steps else None,
                "window_expired_frees": self.window_expired_frees,
                "ttft_by_class": {
                    k: {"n": len(v), "mean": _mean(v), "p95": _pct(v, .95)}
                    for k, v in sorted(self.ttft_by_class.items())},
            },
            "queue_depth": {"mean": self._queue_depth.mean(),
                            "max": self._queue_depth.max()
                            if self._queue_depth.count else 0,
                            "p95": self._queue_depth.pct(.95)},
            "kv_pool": {"occupancy_mean": self._occupancy.mean(),
                        "occupancy_max": self._occupancy.max()
                        if self._occupancy.count else 0.0,
                        "fragmentation_mean": self._fragmentation.mean()},
        }
