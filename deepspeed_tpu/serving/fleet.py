"""Fleet-scale serving: a host-level router over K engine replicas.

Millions of users means more than one engine — and at fleet scale a
replica draining or dying is a ROUTINE event, not an outage.  This
module composes the PR-9 reliability primitives into a fault-tolerant
fleet layer:

- **SLO-aware dispatch** — every arrival is placed on the replica with
  the lowest predicted TTFT, computed per replica by the SAME
  queue-depth x measured-TPOT estimator the admission gate uses
  (``reliability.Reliability.predicted_ttft_s``).  An idle or
  not-yet-measured replica predicts 0 and soaks up traffic first.  When
  the estimator cannot describe a replica (non-``continuous`` scheduler
  policy) the router warns DISARMED — naming the blocker, per the
  repo's arming discipline — and falls back to round-robin.
- **Replica health / circuit breaker** — each replica carries a
  watchdog heartbeat (the engine's per-step ``observe_serving_step``);
  stall events, poison quarantines and step crashes are health STRIKES.
  A strike puts the replica in bounded retry/backoff
  (``retry_backoff_steps`` x streak); ``max_consecutive_failures``
  consecutive strikes trip the breaker and the replica is marked DEAD.
  A clean step resets the streak.
- **Journal-backed migration** — a dead (or drained) replica's
  journal-live requests are re-placed onto survivors through the
  existing ``recover()``/eviction-re-prefill path: rids and FCFS order
  preserved, work budgets carried over, greedy continuations
  BIT-IDENTICAL, zero recompiles (same-config replicas share the
  lru-cached compiled programs, so the fleet-wide CompilationCounter
  pin holds).  The router assigns globally-unique rids in arrival
  order, which is what makes multi-journal merges
  (``RequestJournal.replay_many``) FCFS-correct by construction.
- **Role-tagged replicas** — ``roles=("prefill", "decode", ...)``
  splits prefill (compute-bound, bursty) from decode (memory-bound,
  steady) per the placement semantics of PAPERS.md 2601.02311.  A
  request prefills on a prefill replica; the moment its first token
  exists, its KV moves to a decode replica as a PAGED-BLOCK transfer
  (``engine.export_request``/``import_request`` — the same block-pool
  layout checkpoints round-trip), priced per handoff by
  ``runtime.comm_accounting.serving_kv_handoff_collectives``.

The router's step loop is pure host work (graftlint holds
``serving/fleet.py`` to the hot-path bar): the only device traffic is
the KV handoff itself — one batched fetch on export, one fixed-shape
scatter on import, at most one handoff per prefill replica per step.

Chaos: ``kill_replica_after_steps`` / ``slow_replica_step_every``
(runtime/resilience/chaos.py) target ONE replica so the whole failure
matrix — kill mid-decode, kill mid-drain, kill during migration
replay — is tier-1-testable on a deterministic StepClock, the same way
the PR-9 overload guard is.  The router observes chaos firings through
a weakref trampoline (the PR-10 idiom), so abandoned fleets never pin
K engines in the process-global observer list.
"""
import itertools
import time
import weakref
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.comm_accounting import (
    serving_kv_handoff_bytes)
from deepspeed_tpu.runtime.resilience import chaos
from deepspeed_tpu.runtime.resilience.watchdog import (ACTION_CONTINUE,
                                                       EVENT_STALL,
                                                       TrainingWatchdog)
from deepspeed_tpu.serving.engine import InferenceEngine
from deepspeed_tpu.serving.reliability import (ABORT_POISONED,
                                               RequestJournal)
from deepspeed_tpu.telemetry.metrics import nearest_rank
from deepspeed_tpu.utils.logging import logger

REPLICA_HEALTHY = "healthy"
REPLICA_BACKOFF = "backoff"    # struck out, waiting out a bounded retry
REPLICA_DEAD = "dead"          # breaker tripped: migrated, never stepped
REPLICA_DRAINED = "drained"    # graceful retirement: migrated, done

ROLE_BOTH = "both"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
_ROLES = (ROLE_BOTH, ROLE_PREFILL, ROLE_DECODE)


@dataclass(frozen=True)
class FleetConfig:
    """Router knobs.  ``dispatch="slo"`` is the armed default;
    ``"round-robin"`` is the explicit baseline (no DISARM warning — the
    caller asked for it).  The breaker fields bound how long a sick
    replica is retried before it is declared dead: strike k backs off
    ``retry_backoff_steps * k`` router steps, and
    ``max_consecutive_failures`` strikes with no clean step between
    them trip the breaker.  ``transport_timeout_steps`` is the
    step-clock heartbeat window for a transport-backed fleet (ISSUE
    16): a peer silent past it is voted on and — agreed — marked dead
    through the same breaker/migration path a crash takes."""
    dispatch: str = "slo"                 # "slo" | "round-robin"
    max_consecutive_failures: int = 3
    retry_backoff_steps: int = 2
    stall_timeout_s: float = 0.0          # per-replica stall detector
    transport_timeout_steps: int = 3


@dataclass(frozen=True)
class AutoscaleConfig:
    """Telemetry-driven replica-set sizing (ISSUE 16).  The signals are
    the unified metrics the router already computes every step: queue
    depth per active replica (waiting + running, the load the fleet is
    actually carrying) and — optionally — the worst predicted TTFT
    across replicas (the same estimator SLO dispatch uses;
    ``scale_up_ttft_s=0`` disables that trigger).  ``cooldown_steps``
    ticks must pass after any scale event before the next one, so a
    burst cannot thrash the set; scale-down is a graceful
    ``drain_replica`` (a death you scheduled — journal-backed, zero
    lost requests), never a kill."""
    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_queue_depth: float = 4.0     # waiting+running per replica
    scale_down_queue_depth: float = 1.0
    scale_up_ttft_s: float = 0.0          # 0 = queue-depth trigger only
    cooldown_steps: int = 8
    evaluate_every: int = 1


class ReplicaHandle:
    """One replica's router-side state: the engine, its role, its
    health, and its journal path (the migration source of truth)."""

    def __init__(self, index, engine, role, journal_path):
        self.index = index
        self.engine = engine
        self.role = role
        self.journal_path = journal_path
        self.state = REPLICA_HEALTHY
        self.draining = False
        self.consecutive_failures = 0
        self.backoff_until = 0
        self.failures: Dict[str, int] = {}    # kind -> total strikes
        self.stall_flag = False
        self.placed = 0                       # requests routed here

    @property
    def alive(self) -> bool:
        return self.state in (REPLICA_HEALTHY, REPLICA_BACKOFF)


class FleetRouter:
    """Host-level router over K in-process :class:`InferenceEngine`
    replicas sharing one clock (a StepClock in tests/benches, so every
    latency and deadline is deterministic).

    The router owns the global rid space: every ``submit`` assigns the
    next rid and passes it down with ``_rid=``, so rids are unique and
    monotone in arrival order ACROSS replicas — journals from different
    replicas merge FCFS-correctly by rid alone.
    """

    def __init__(self, model, params, *, replicas=2, roles=None,
                 clock=time.monotonic, config=None, reliability=None,
                 journal_dir=None, engine_kwargs=None, telemetry=None,
                 autoscale=None, transport=None):
        assert replicas >= 1
        cfg = config if isinstance(config, FleetConfig) \
            else FleetConfig(**(config or {}))
        assert cfg.dispatch in ("slo", "round-robin"), cfg.dispatch
        self.config = cfg
        self.clock = clock
        roles = tuple(roles) if roles else (ROLE_BOTH,) * replicas
        assert len(roles) == replicas, (roles, replicas)
        assert all(r in _ROLES for r in roles), roles
        assert any(r in (ROLE_BOTH, ROLE_PREFILL) for r in roles), \
            "fleet needs at least one prefill-capable replica"
        if any(r != ROLE_BOTH for r in roles):
            assert any(r in (ROLE_BOTH, ROLE_DECODE) for r in roles), \
                "role-split fleet needs a decode-capable replica"
        self._role_split = any(r == ROLE_PREFILL for r in roles)
        # retained for autoscale scale-up: a grown replica is built from
        # the SAME spec as the founding set (and shares the lru-cached
        # compiled programs, so growing costs no recompile)
        self._model = model
        self._params = params
        self._engine_kwargs = dict(engine_kwargs or {})
        self._reliability_spec = dict(reliability or {})
        self._journal_dir = journal_dir
        self.replicas: List[ReplicaHandle] = []
        for i in range(replicas):
            self.replicas.append(self._new_replica(i, roles[i]))
        self._rids = itertools.count()
        self._owner: Dict[int, int] = {}      # rid -> replica index
        self._router_results: Dict[int, dict] = {}   # lost requests
        self._rr = itertools.count()
        self._step_idx = 0
        self.migrations = 0
        self.handoffs: List[dict] = []
        self.handoff_bytes = 0
        self.lost: List[int] = []
        self.replica_steps = 0      # sum of alive replicas over steps:
        #                             the honest autoscale denominator
        self._arm_dispatch()
        self._arm_telemetry(telemetry)
        self._arm_autoscale(autoscale)
        self._arm_transport(transport)

    def _new_replica(self, i, role):
        """Build one replica handle from the retained fleet spec — the
        shared constructor of the founding set and every autoscale
        grow."""
        rel = dict(self._reliability_spec)
        jpath = None
        if self._journal_dir is not None:
            import os

            os.makedirs(str(self._journal_dir), exist_ok=True)
            jpath = os.path.join(str(self._journal_dir),
                                 f"replica{i}.jsonl")
            rel["journal_path"] = jpath
        wd = None
        if self.config.stall_timeout_s > 0:
            wd = TrainingWatchdog(
                stall_timeout=self.config.stall_timeout_s)
        eng = InferenceEngine(self._model, self._params, clock=self.clock,
                              reliability=rel or None, watchdog=wd,
                              **self._engine_kwargs)
        eng._replica_index = i
        rep = ReplicaHandle(i, eng, role, jpath)
        if wd is not None:
            wd.add_callback(self._stall_cb(rep))
        return rep

    @staticmethod
    def _stall_cb(rep):
        # plain function over the handle (no engine/router capture): the
        # watchdog lives on the handle, so no process-global pinning
        def _cb(event):
            if event.kind == EVENT_STALL:
                rep.stall_flag = True
            return ACTION_CONTINUE
        return _cb

    # -- arming (DISARMED discipline) -----------------------------------
    def _arm_dispatch(self):
        """Arm SLO-aware placement, or warn loudly (DISARMED) naming
        every blocker and fall back to round-robin — the armed-or-warns
        discipline graftlint enforces on ``_arm_*`` sites."""
        self.dispatch_armed = False
        if self.config.dispatch == "round-robin":
            return    # explicitly requested baseline, not a fallback
        blockers = [
            f"replica {r.index} runs the "
            f"'{r.engine.scheduler.policy}' scheduler policy (the "
            f"predicted-TTFT model only describes 'continuous')"
            for r in self.replicas
            if r.engine.scheduler.policy != "continuous"]
        if blockers:
            logger.warning(
                "fleet router: SLO-aware dispatch DISARMED — %s; "
                "falling back to round-robin placement.",
                "; ".join(blockers))
            return
        self.dispatch_armed = True

    def _arm_telemetry(self, spec):
        """Arm the router telemetry session (``router`` tracer lane +
        chaos instants via a weakref observer).  Disarmed fleets hold
        ``self._tracer = None`` — one attribute check per step.  A spec
        with ``enabled=false`` warns DISARMED instead of silently
        observing nothing."""
        self.telemetry = None
        self._tracer = None
        self._owns_telemetry = False
        self._lane_router = 0
        self._chaos_observer = None
        if spec is None:
            return
        from deepspeed_tpu.telemetry import Telemetry

        if isinstance(spec, Telemetry):
            tel = spec
        else:
            self._owns_telemetry = True
            tcfg = dict(spec)
            if not tcfg.pop("enabled", True):
                logger.warning(
                    "fleet telemetry: DISARMED — a telemetry config was "
                    "passed with enabled=false; no router lane or "
                    "per-replica metric stream will be produced")
                return
            tel = Telemetry(**tcfg)
        self.telemetry = tel
        self._tracer = tel.tracer
        if self._tracer is None:
            return
        self._lane_router = self._tracer.lane("router")
        self._tracer.intern("router_step", args=("step",))
        # weakref trampoline (PR-10 idiom): the process-global chaos
        # observer list must never pin the router (and through it K
        # engines and their pools) after the caller drops it
        ref = weakref.ref(self)

        def _chaos_obs(kind, detail=None):
            rt = ref()
            if rt is not None:
                rt._telemetry_chaos_cb(kind, detail)

        self._chaos_observer = chaos.add_observer(_chaos_obs)

    def _arm_autoscale(self, spec):
        """Arm telemetry-driven autoscaling (ISSUE 16), or warn loudly
        (DISARMED) naming every blocker and keep the replica set fixed.
        Blockers: a role-split fleet (growing a replica means choosing
        its prefill/decode role — a placement policy this autoscaler
        does not make), invalid bounds, and — when the predicted-TTFT
        trigger is requested — any replica the estimator cannot
        describe."""
        self.autoscale_armed = False
        self._autoscale = None
        self.scale_events: List[dict] = []
        self._scale_cooldown_until = 0
        if spec is None:
            return
        cfg = spec if isinstance(spec, AutoscaleConfig) \
            else AutoscaleConfig(**spec)
        blockers = []
        if self._role_split:
            blockers.append(
                "the fleet is role-split (a grown replica needs a "
                "prefill/decode placement decision this autoscaler "
                "does not make)")
        if cfg.min_replicas < 1 or cfg.max_replicas < cfg.min_replicas:
            blockers.append(
                f"invalid replica bounds "
                f"[{cfg.min_replicas}, {cfg.max_replicas}]")
        if cfg.scale_up_ttft_s > 0:
            blockers.extend(
                f"replica {r.index} runs the "
                f"'{r.engine.scheduler.policy}' scheduler policy (the "
                f"predicted-TTFT trigger only describes 'continuous')"
                for r in self.replicas
                if r.engine.scheduler.policy != "continuous")
        if blockers:
            logger.warning(
                "fleet autoscaler: DISARMED — %s; the replica set stays "
                "fixed at %d.", "; ".join(blockers), len(self.replicas))
            return
        self._autoscale = cfg
        self.autoscale_armed = True

    def _arm_transport(self, transport):
        """Arm the cross-process peer bus (ISSUE 16 transport seam):
        replica ``i``'s host liveness rides transport peer ``i+1``
        (rank 0 is the router).  Armed, a peer silent past
        ``transport_timeout_steps`` router ticks is voted on and —
        agreed — its replica takes the breaker/migration path a crash
        takes.  Blockers warn DISARMED and leave replica liveness
        in-process (engine watchdog + chaos only): a world that does
        not map onto the replica set, or an armed autoscaler (a grown
        replica would have no transport peer)."""
        self._transport = None
        self.transport_armed = False
        if transport is None:
            return
        blockers = []
        if transport.world != len(self.replicas) + 1:
            blockers.append(
                f"transport world {transport.world} does not map onto "
                f"{len(self.replicas)} replicas + 1 router (peer rank "
                f"i+1 <-> replica i)")
        if self.autoscale_armed:
            blockers.append(
                "autoscaling is armed (a grown replica would have no "
                "transport peer; grow the transport world first)")
        if blockers:
            logger.warning(
                "fleet transport: DISARMED — %s; replica liveness stays "
                "in-process (watchdog/chaos only).", "; ".join(blockers))
            return
        self._transport = transport.start()
        self.transport_armed = True

    def _telemetry_chaos_cb(self, kind, detail=None):
        tr = self._tracer
        if tr is not None and kind in ("kill_replica", "slow_replica"):
            tr.instant(f"chaos_{kind}", self._lane_router,
                       a0=int(detail) if detail is not None else 0)

    def close(self):
        """Release process-global hooks (chaos observer) and close a
        telemetry session this router created from a dict spec.
        Idempotent; also runs at GC."""
        obs = getattr(self, "_chaos_observer", None)
        if obs is not None:
            self._chaos_observer = None
            chaos.remove_observer(obs)
        if getattr(self, "_owns_telemetry", False) \
                and self.telemetry is not None:
            self.telemetry.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: allow-broad-except — interpreter
            # teardown can fail imports mid-GC; never raise from __del__
            pass

    # -- placement ------------------------------------------------------
    def _eligible(self, *, decode_target=False, exclude=None):
        """Replicas a new request (or a KV handoff when
        ``decode_target``) may land on: alive, not draining, role
        matches.  Healthy replicas are preferred over ones sitting out
        a backoff; a backoff replica is still a legal last resort (it
        is suspected, not dead)."""
        want = (ROLE_BOTH, ROLE_DECODE) if decode_target \
            else (ROLE_BOTH, ROLE_PREFILL)
        cands = [r for r in self.replicas
                 if r is not exclude and r.alive and not r.draining
                 and r.role in want]
        healthy = [r for r in cands if r.state == REPLICA_HEALTHY]
        return healthy or cands

    def _place(self, extra_tokens, *, decode_target=False, exclude=None):
        """Pick the target replica: lowest predicted TTFT when armed
        (an unmeasured/idle replica predicts 0 — it admits freely, so
        it fills first), round-robin otherwise.  None = no eligible
        replica (total outage)."""
        cands = self._eligible(decode_target=decode_target,
                               exclude=exclude)
        if not cands:
            return None
        if not self.dispatch_armed:
            return cands[next(self._rr) % len(cands)]
        scored = [(r.engine.reliability.predicted_ttft_s(
            extra_tokens=extra_tokens) or 0.0, r.index, r)
            for r in cands]
        return min(scored)[2]

    # -- public API -----------------------------------------------------
    def submit(self, prompt, max_new_tokens, *, priority=0,
               eos_token_id=None, seed=0, deadline_s=None,
               work_budget=None, replica=None) -> int:
        """Submit one request to the fleet: the router assigns the
        globally-unique rid and places the request (``replica=`` pins
        it — tests and sticky-routing callers).  The chosen replica's
        own admission gate still applies: under predicted overload it
        may shed it (``results[rid]["status"] == "shed"``)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if replica is not None:
            rep = self.replicas[replica]
            if not rep.alive or rep.draining:
                raise RuntimeError(
                    f"fleet router: replica {replica} is "
                    f"{'draining' if rep.draining else rep.state} — a "
                    f"pinned submission there would queue forever "
                    f"(dead/drained replicas are never stepped); pin a "
                    f"live replica or let the router place it")
        else:
            rep = self._place(len(prompt))
        if rep is None:
            raise RuntimeError(
                "fleet router: no eligible replica (all dead, drained "
                "or draining) — total outage, submission refused")
        rid = next(self._rids)
        rep.engine.submit(prompt, max_new_tokens, priority=priority,
                          eos_token_id=eos_token_id, seed=seed,
                          deadline_s=deadline_s, work_budget=work_budget,
                          _rid=rid)
        self._owner[rid] = rep.index
        rep.placed += 1
        return rid

    def step(self) -> dict:
        """One router tick: step every live replica (health-checked,
        breaker-guarded), retire drained ones, run at most one KV
        handoff per prefill replica.  Pure host work apart from the
        handoff transfer itself."""
        self._step_idx += 1
        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        events = {"failures": [], "dead": [], "drained": [],
                  "migrated": [], "handoffs": [], "scaled": []}
        if self._transport is not None:
            self._transport_tick(events)
        for rep in self.replicas:
            self._step_replica(rep, events)
        if self.autoscale_armed:
            self._autoscale_tick(events)
        self.replica_steps += sum(1 for r in self.replicas if r.alive)
        self._last_metrics = {
            "step": self._step_idx,
            "alive": sum(1 for r in self.replicas if r.alive),
            "dead": sum(1 for r in self.replicas
                        if r.state == REPLICA_DEAD),
            "migrations": self.migrations,
            "handoffs": len(self.handoffs),
            "handoff_bytes": self.handoff_bytes,
            "lost": len(self.lost),
            "replica_steps": self.replica_steps,
            "scale_events": len(self.scale_events),
            **self._cache_spec_aggregates(),
        }
        if tr is not None:
            tr.complete("router_step", self._lane_router, _t0,
                        a0=self._step_idx)
        if self.telemetry is not None:
            self.telemetry.on_step(self._step_idx, self._last_metrics)
        return events

    def _step_replica(self, rep, events):
        if not rep.alive:
            return
        if rep.state == REPLICA_BACKOFF \
                and self._step_idx < rep.backoff_until:
            return
        eng = rep.engine
        if rep.state == REPLICA_BACKOFF and not eng.scheduler.has_work():
            # the backoff window elapsed and the replica has nothing to
            # retry against: close the probation instead of leaving it
            # deprioritized forever with a stale streak (a genuinely
            # hard-down replica re-strikes on its next real step)
            rep.state = REPLICA_HEALTHY
            rep.consecutive_failures = 0
        if eng.scheduler.has_work():
            poisoned0 = eng.reliability.aborts[ABORT_POISONED]
            try:
                eng.step()
            except Exception as e:  # lint: allow-broad-except — replica
                # fault ISOLATION is the router's job: any exception out
                # of one replica's step (chaos ChaosInterrupt, a real
                # crash) must strike that replica, never the fleet
                self._on_failure(rep, "crash", repr(e), events)
                return
            if rep.stall_flag:
                rep.stall_flag = False
                self._on_failure(rep, "stall",
                                 "stall detector fired", events)
                return
            if eng.reliability.aborts[ABORT_POISONED] > poisoned0:
                # the engine already quarantined the lane; the replica
                # made progress, but repeated poison is a sick host —
                # strike it (no early return: it can still drain/serve)
                self._on_failure(rep, "poison",
                                 "poisoned lane quarantined", events)
                if not rep.alive:
                    return
            else:
                rep.consecutive_failures = 0
                if rep.state == REPLICA_BACKOFF:
                    rep.state = REPLICA_HEALTHY
        if rep.draining and not eng.scheduler.in_flight():
            self._retire_drained(rep, events)
            return
        if self._role_split and rep.role == ROLE_PREFILL:
            self._handoff_tick(rep, events)

    def serve(self, *, max_steps=100000) -> dict:
        steps = 0
        while self.has_work():
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet serve() exceeded max_steps={max_steps}")
            self.step()
            steps += 1
        return self.results

    def has_work(self) -> bool:
        return any(r.alive and r.engine.scheduler.has_work()
                   for r in self.replicas)

    @property
    def results(self) -> dict:
        """Merged result view across the fleet (rids are globally
        unique, so the union is well-defined); router-level ``lost``
        entries cover requests no survivor could take."""
        out = dict(self._router_results)
        for rep in self.replicas:
            out.update(rep.engine.results)
        return out

    def warmup(self):
        """Compile everything steady state needs on every replica (the
        same-config replicas share the lru-cached programs, so the
        fleet pays for ONE compile set), plus — in a role-split fleet —
        one synthetic handoff to warm the paged-block gather/scatter
        shapes.  Resets every counter afterwards."""
        for rep in self.replicas:
            rep.engine.warmup()
        if self._role_split:
            for rep in self.replicas:
                rep.engine._warming = True
            try:
                # max_new must outlive the admission step: the engine
                # prefills AND decodes in one tick, so a 2-token request
                # finishes before the router's handoff tick can see it
                self.submit(np.zeros(2, np.int32), max_new_tokens=6)
                self.serve(max_steps=200)
                assert self.handoffs, \
                    "role-split warmup ran no KV handoff"
            finally:
                for rep in self.replicas:
                    rep.engine._warming = False
                    rep.engine.results.clear()
                    rep.engine.metrics.reset()
                    rep.engine._last_metrics = {}
                    rep.engine._step_idx = 0
        self._rids = itertools.count()
        self._rr = itertools.count()
        self._owner.clear()
        self._router_results.clear()
        self._step_idx = 0
        self.migrations = 0
        self.handoffs = []
        self.handoff_bytes = 0
        self.lost = []
        self.replica_steps = 0
        self.scale_events = []
        self._scale_cooldown_until = 0
        for rep in self.replicas:
            rep.placed = 0

    # -- drain / failure / migration ------------------------------------
    def drain_replica(self, index) -> None:
        """Gracefully retire one replica: admission stops at its next
        step boundary, in-flight requests finish there, queued ones
        migrate to survivors once it empties (journal-backed, same path
        as death — a drain is just a death you scheduled)."""
        rep = self.replicas[index]
        rep.draining = True
        rep.engine.request_drain()
        if self._tracer is not None:
            self._tracer.instant("drain_replica", self._lane_router,
                                 a0=index)
        logger.info("fleet: draining replica %d", index)

    # -- transport peer liveness (ISSUE 16) -----------------------------
    def _transport_tick(self, events):
        """One beat of the cross-process peer bus: broadcast the router
        step, classify each peer's step-clock lag, and turn an AGREED
        dead peer into the replica breaker/migration path.  Suspicion
        without agreement (the ack vote timed out on a wedged survivor)
        is a strike, never a one-sided verdict — the breaker's bounded
        streak still converges if the peer stays silent."""
        w = self._step_idx
        beats = self._transport.heartbeat_tick(w)
        timeout = self.config.transport_timeout_steps
        for rep in self.replicas:
            peer = rep.index + 1
            if not rep.alive:
                continue
            lag = w - beats.get(peer, 0)
            if lag <= timeout:
                continue
            if self._transport.vote_dead([peer], w):
                logger.warning(
                    "fleet: transport peer %d (replica %d) silent %d "
                    "steps — coordinated dead verdict at router step "
                    "%d; breaker tripped, migrating its journal",
                    peer, rep.index, lag, w)
                rep.failures["peer_dead"] = \
                    rep.failures.get("peer_dead", 0) + 1
                events["failures"].append(
                    {"replica": rep.index, "kind": "peer_dead"})
                if self._tracer is not None:
                    self._tracer.instant("replica_peer_dead",
                                         self._lane_router, a0=rep.index)
                self._transport.mark_dead(peer)
                self._mark_dead(rep, events)
            else:
                self._on_failure(
                    rep, "peer_stale",
                    f"transport peer {peer} silent {lag} steps, no "
                    f"verdict agreement yet", events)

    # -- telemetry-driven autoscaling (ISSUE 16) ------------------------
    def _autoscale_tick(self, events):
        """Resize the replica set from the unified metrics stream:
        queue depth per active replica (waiting + running) and — when
        the trigger is configured — the worst predicted TTFT across
        replicas.  Pure host bookkeeping; the only expensive act is the
        grow itself (one engine build sharing the lru-cached compiled
        programs) or a graceful drain."""
        cfg = self._autoscale
        w = self._step_idx
        if w < self._scale_cooldown_until \
                or (cfg.evaluate_every > 1 and w % cfg.evaluate_every):
            return
        active = [r for r in self.replicas
                  if r.alive and not r.draining]
        if not active:
            return
        depth = sum(r.engine.scheduler.queue_depth()
                    + len(r.engine.scheduler.running) for r in active)
        per_replica = depth / len(active)
        ttft = 0.0
        if cfg.scale_up_ttft_s > 0:
            ttft = max(r.engine.reliability.predicted_ttft_s(
                extra_tokens=0) or 0.0 for r in active)
        if len(active) < cfg.max_replicas \
                and (per_replica >= cfg.scale_up_queue_depth
                     or (cfg.scale_up_ttft_s > 0
                         and ttft >= cfg.scale_up_ttft_s)):
            self._scale_up(events, per_replica, ttft)
        elif len(active) > cfg.min_replicas \
                and per_replica <= cfg.scale_down_queue_depth \
                and not any(r.draining for r in self.replicas):
            self._scale_down(active, events, per_replica)

    def _record_scale(self, direction, replica, events, per_replica,
                      ttft):
        ev = {"step": self._step_idx, "dir": direction,
              "replica": replica,
              "active": sum(1 for r in self.replicas
                            if r.alive and not r.draining),
              "queue_depth_per_replica": round(per_replica, 4),
              "predicted_ttft_s": round(ttft, 4)}
        self.scale_events.append(ev)
        events["scaled"].append(dict(ev))
        self._scale_cooldown_until = self._step_idx \
            + self._autoscale.cooldown_steps
        if self._tracer is not None:
            self._tracer.instant(f"scale_{direction}", self._lane_router,
                                 a0=replica)
        logger.info(
            "fleet autoscaler: scale-%s replica %d at router step %d "
            "(queue depth/replica %.2f, predicted TTFT %.3fs) — %d "
            "active", direction.upper(), replica, self._step_idx,
            per_replica, ttft, ev["active"])

    def _scale_up(self, events, per_replica, ttft):
        idx = len(self.replicas)
        rep = self._new_replica(idx, ROLE_BOTH)
        self.replicas.append(rep)
        # same-config engines share the lru-cached compiled programs:
        # the grow pays host setup, never a recompile (the fleet-wide
        # CompilationCounter pin holds through scale events)
        rep.engine.warmup()
        self._record_scale("up", idx, events, per_replica, ttft)

    def _scale_down(self, active, events, per_replica):
        # retire the least-loaded active replica — but never the last
        # prefill-capable one (autoscale only arms on non-role-split
        # fleets, so any ROLE_BOTH survivor keeps the fleet whole)
        victim = min(active, key=lambda r: (
            r.engine.scheduler.queue_depth()
            + len(r.engine.scheduler.running), r.index))
        if sum(1 for r in active if r is not victim) < 1:
            return
        self.drain_replica(victim.index)
        self._record_scale("down", victim.index, events, per_replica,
                           0.0)

    def _on_failure(self, rep, kind, detail, events):
        rep.failures[kind] = rep.failures.get(kind, 0) + 1
        rep.consecutive_failures += 1
        events["failures"].append({"replica": rep.index, "kind": kind})
        if self._tracer is not None:
            self._tracer.instant(f"replica_{kind}", self._lane_router,
                                 a0=rep.index)
        if rep.consecutive_failures \
                >= self.config.max_consecutive_failures:
            logger.warning(
                "fleet: replica %d %s (%s) — strike %d/%d, breaker "
                "TRIPPED: marking dead and migrating its journal",
                rep.index, kind, detail, rep.consecutive_failures,
                self.config.max_consecutive_failures)
            self._mark_dead(rep, events)
        else:
            rep.state = REPLICA_BACKOFF
            rep.backoff_until = self._step_idx \
                + self.config.retry_backoff_steps \
                * rep.consecutive_failures
            logger.warning(
                "fleet: replica %d %s (%s) — strike %d/%d, backing off "
                "until router step %d",
                rep.index, kind, detail, rep.consecutive_failures,
                self.config.max_consecutive_failures, rep.backoff_until)

    def _mark_dead(self, rep, events):
        rep.state = REPLICA_DEAD
        events["dead"].append(rep.index)
        if self._tracer is not None:
            self._tracer.instant("replica_dead", self._lane_router,
                                 a0=rep.index)
        self._migrate(rep, events)

    def _retire_drained(self, rep, events):
        """The drain finished its in-flight work; move the queued
        remainder to survivors and retire the replica."""
        self._migrate(rep, events)
        rep.state = REPLICA_DRAINED
        events["drained"].append(rep.index)
        logger.info("fleet: replica %d drained and retired", rep.index)

    def _migrate(self, rep, events):
        """Re-place a dead/drained replica's journal-live requests onto
        survivors through the recover()/re-prefill path — FCFS order
        (the journal's submit order), rids, priorities and work budgets
        all preserved; greedy continuations bit-identical.  The JOURNAL
        is the source of truth (a crashed host's memory is not
        trustworthy); without one, the replica's requests are recorded
        as lost — loudly."""
        if rep.journal_path is None:
            lost = [r for r in rep.engine.scheduler.requests.values()]
            if lost:
                logger.warning(
                    "fleet: replica %d has NO journal armed "
                    "(journal_dir unset) — %d live requests are LOST, "
                    "not migrated", rep.index, len(lost))
            for req in lost:
                self._record_lost(req.rid, req.prompt, req.generated)
            return
        entries = RequestJournal.replay(rep.journal_path)
        # ownership filter: a rid this replica handed off (or that was
        # otherwise re-placed) can still read as live in ITS journal —
        # the "migrated" end record may be torn by the crash — but the
        # router's owner map is authoritative in-process; migrating it
        # again would put one rid live on two engines
        entries = [e for e in entries
                   if self._owner.get(e["rid"], rep.index) == rep.index]
        for e in entries:
            self._migrate_entry(rep, e, events)
        if entries:
            logger.warning(
                "fleet: migrated %d journal-live requests off replica "
                "%d onto survivors", len(entries), rep.index)

    def _migrate_entry(self, rep, e, events, *, timing_from=None):
        extra = len(e["prompt"]) + len(e["generated"])
        target = self._place(extra, exclude=rep)
        if target is None:
            self._record_lost(e["rid"], e["prompt"], e["generated"])
            return
        target.engine.submit(
            np.asarray(e["prompt"], np.int32), e["max_new"],
            priority=e["priority"], eos_token_id=e["eos"],
            seed=e["seed"], deadline_s=e["deadline_s"],
            work_budget=e["work_budget"], _generated=e["generated"],
            _rid=e["rid"], _work_done=e.get("work_done", 0),
            _readmit=True)
        src = rep if rep is not None else timing_from
        if src is not None:
            # in-process, the dead replica's metrics outlive it and the
            # clock is shared: carry the original arrival (the sample
            # must include time waited on the corpse) and, when a first
            # token already landed there, its stamp (so the fleet never
            # counts two TTFT samples for one rid)
            target.engine.metrics.adopt_timing(
                e["rid"], *src.engine.metrics.export_timing(e["rid"]))
        self._owner[e["rid"]] = target.index
        self.migrations += 1
        events["migrated"].append(e["rid"])
        if self._tracer is not None:
            self._tracer.instant("migrate", self._lane_router,
                                 a0=e["rid"], a1=target.index)

    def _record_lost(self, rid, prompt, generated):
        self.lost.append(rid)
        self._router_results[rid] = {
            "tokens": np.concatenate(
                [np.asarray(prompt, np.int32),
                 np.asarray(list(generated), np.int32)]),
            "status": "lost", "evictions": 0,
        }
        logger.warning(
            "fleet: request %d LOST — no surviving replica could take "
            "it", rid)

    def recover(self, journal_paths) -> list:
        """Whole-fleet cold recovery: merge SEVERAL dead predecessors'
        journals (``RequestJournal.replay_many`` — global FCFS by rid,
        per-journal torn-tail tolerance) and re-place every live
        request across this fleet.  Returns the recovered rids in
        FCFS order."""
        entries = RequestJournal.replay_many(journal_paths)
        rids = []
        events = {"failures": [], "dead": [], "drained": [],
                  "migrated": [], "handoffs": []}
        for e in entries:
            self._migrate_entry(None, e, events)
            rids.append(e["rid"])
        if rids:
            # never REWIND the global rid space: a warm fleet may have
            # issued rids above the recovered journals' range, and a
            # rewound counter would hand a live rid to a new request
            nxt = next(self._rids)
            self._rids = itertools.count(max(nxt, max(rids) + 1))
        logger.info("fleet recover: re-placed %d journaled requests "
                    "from %d journals", len(rids), len(journal_paths))
        return rids

    # -- KV handoff (role-split fleets) ---------------------------------
    def _handoff_tick(self, rep, events):
        """Move at most ONE just-prefilled request (oldest first) from
        this prefill replica to a decode replica: a paged-block KV
        transfer — one batched fetch, one fixed-shape scatter — instead
        of a re-prefill.  Bounded to one per replica per step so the
        router's step stays O(1) device transfers."""
        running = rep.engine.scheduler.running
        if not running:
            return
        req = min(running.values(), key=lambda r: r.submit_seq)
        target = self._place(0, decode_target=True, exclude=rep)
        if target is None:
            return        # no decode replica up: keep decoding here
        if not target.engine.can_adopt(
                rep.engine.pool.blocks_of(req.rid)):
            return        # decode tier full: exporting would discard
                          # the computed KV into a re-prefill — the
                          # request is better off decoding here
        try:
            entry = rep.engine.export_request(req.rid)
        except Exception as e:  # lint: allow-broad-except — fault
            # isolation: the export's device fetch runs first, so a
            # faulting SOURCE leaves the request untouched (still
            # RUNNING there); strike the source and move on
            self._on_failure(rep, "crash", repr(e), events)
            return
        try:
            outcome = target.engine.import_request(entry)
        except Exception as e:  # lint: allow-broad-except — fault
            # isolation: the source already detached the request, so
            # after a faulting import it exists ONLY in `entry` —
            # strike the target and re-place it through the journal
            # re-prefill path on whichever replica remains
            self._on_failure(target, "crash", repr(e), events)
            # exclude nobody: the SOURCE is prefill-capable and may
            # take its own request back through a re-prefill — but the
            # timing stamps still come from it (the rid's real arrival
            # and first token live there; a fresh arrival would fake a
            # second, re-prefill-sized TTFT sample)
            self._migrate_entry(None, {
                "rid": entry["rid"], "prompt": entry["prompt"],
                "generated": entry["generated"],
                "max_new": entry["max_new_tokens"],
                "priority": entry["priority"], "eos": entry["eos"],
                "seed": entry["seed"],
                "deadline_s": entry["deadline_s"],
                "work_budget": entry["work_budget"],
                "work_done": entry["work_done"]}, events,
                timing_from=rep)
            return
        eng = rep.engine
        nbytes = serving_kv_handoff_bytes(
            eng.cfg.n_layer, eng.cfg.n_head, eng.cfg.head_dim,
            blocks=entry["n_blocks"], block_size=eng.bs,
            kv_dtype=np.dtype(eng.pool.dtype).name,
            quantized=eng.pool.quantized)
        self.handoff_bytes += nbytes
        self.handoffs.append({
            "rid": entry["rid"], "src": rep.index, "dst": target.index,
            "blocks": entry["n_blocks"], "bytes": nbytes,
            "outcome": outcome})
        self._owner[entry["rid"]] = target.index
        events["handoffs"].append(entry["rid"])
        if self._tracer is not None:
            self._tracer.instant("kv_handoff", self._lane_router,
                                 a0=entry["rid"], a1=target.index)

    # -- reporting ------------------------------------------------------
    def request_ttft(self, rid):
        """Fleet-wide TTFT of one request (recorded at the replica that
        admitted it; migrated requests keep their original arrival)."""
        for rep in self.replicas:
            t = rep.engine.metrics.ttft_of(rid)
            if t is not None:
                return t
        return None

    def _cache_spec_aggregates(self) -> dict:
        """Fleet-wide prefix-cache and speculative-decode accounting:
        sums of every replica's counters, with the ratios recomputed
        from the sums (a mean of per-replica rates would weight an
        idle replica the same as a saturated one).  Migrated and
        journal-recovered requests re-enter through the normal
        admission probe, so the tokens their re-prefill did NOT pay
        for show up here as ``migration_avoided_prefill_tokens``."""
        reps = [r.engine.metrics for r in self.replicas]
        lookups = sum(m.prefix_lookups for m in reps)
        hits = sum(m.prefix_hits for m in reps)
        avoided = sum(m.prefix_avoided_tokens for m in reps)
        readmit = sum(m.readmit_avoided_tokens for m in reps)
        verify = sum(m.spec_verify_steps for m in reps)
        accepted = sum(m.spec_accepted_tokens for m in reps)
        hist: dict = {}
        for m in reps:
            for k, v in m.spec_accept_hist.items():
                hist[k] = hist.get(k, 0) + v
        return {
            "prefix_lookups": lookups,
            "prefix_hits": hits,
            "prefix_hit_rate": (hits / lookups) if lookups else None,
            "prefix_avoided_prefill_tokens": avoided,
            "migration_avoided_prefill_tokens": readmit,
            "spec_verify_steps": verify,
            "spec_accepted_tokens": accepted,
            "tokens_per_verify":
                (accepted / verify) if verify else None,
            "spec_accept_hist": dict(sorted(hist.items())),
        }

    def fleet_ttft(self) -> dict:
        """Fleet-wide TTFT distribution: the union of every replica's
        per-request TTFT samples."""
        ttfts = [t for rep in self.replicas
                 for t in rep.engine.metrics.ttft]
        return {"n": len(ttfts),
                "mean": (sum(ttfts) / len(ttfts)) if ttfts else None,
                "p50": nearest_rank(ttfts, .5),
                "p95": nearest_rank(ttfts, .95)}

    def fleet_report(self) -> dict:
        """Router + per-replica summary (the fleet face of
        ``serving_report()``): placement/dispatch state, the failure
        ledger, migration/handoff accounting, and each replica's full
        serving report under its ``replica<i>`` key."""
        agg_useful = sum(r.engine.metrics.useful_tokens
                         for r in self.replicas)
        agg_slot_steps = sum(r.engine.metrics.slot_steps
                             for r in self.replicas)
        return {
            "config": {
                "replicas": len(self.replicas),
                "roles": [r.role for r in self.replicas],
                "dispatch": self.config.dispatch,
                "dispatch_armed": self.dispatch_armed,
                "max_consecutive_failures":
                    self.config.max_consecutive_failures,
                "retry_backoff_steps": self.config.retry_backoff_steps,
                "autoscale_armed": self.autoscale_armed,
                "transport_armed": self.transport_armed,
            },
            "router": {
                "steps": self._step_idx,
                "placements": {f"replica{r.index}": r.placed
                               for r in self.replicas},
                "migrations": self.migrations,
                "handoffs": len(self.handoffs),
                "handoff_bytes": self.handoff_bytes,
                "lost": list(self.lost),
                "ttft_s": self.fleet_ttft(),
                "goodput_tokens_per_slot_step":
                    (agg_useful / agg_slot_steps) if agg_slot_steps
                    else None,
                "replica_steps": self.replica_steps,
                "goodput_tokens_per_replica_step":
                    (agg_useful / self.replica_steps)
                    if self.replica_steps else None,
                "scale_events": [dict(e) for e in self.scale_events],
                "cache_and_spec": self._cache_spec_aggregates(),
            },
            "replicas": {
                f"replica{r.index}": {
                    "state": r.state, "role": r.role,
                    "draining": r.draining,
                    "consecutive_failures": r.consecutive_failures,
                    "failures": dict(r.failures),
                    "journal_path": r.journal_path,
                    "report": r.engine.serving_report(),
                } for r in self.replicas
            },
        }

    def telemetry_report(self) -> dict:
        """Unified fleet observability: the full :meth:`fleet_report`
        plus the router telemetry sections and every replica's
        step-level metrics flattened under ``replica<i>/`` prefixes —
        one stream, one namespace, no per-engine consumers."""
        rep = self.fleet_report()
        tel = self.telemetry
        rep["telemetry_armed"] = tel is not None
        flat = {}
        for r in self.replicas:
            for k, v in (r.engine._last_metrics or {}).items():
                if isinstance(v, (bool, int, float)):
                    flat[f"replica{r.index}/{k}"] = v
        for k, v in (getattr(self, "_last_metrics", None) or {}).items():
            flat[f"router/{k}"] = v
        rep["replica_metrics"] = flat
        if tel is None:
            return rep
        rep["metrics"] = tel.registry.snapshot()
        if tel.tracer is not None:
            rep["trace"] = tel.tracer.summary()
        return rep

    def export_trace(self, path, complete_events=True):
        tr = self._tracer
        if tr is None:
            return None
        return tr.export_chrome_trace(path,
                                      complete_events=complete_events)
