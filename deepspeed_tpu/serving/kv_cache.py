"""Paged KV cache: fixed block pool + per-sequence page tables.

The single-sequence decode loop in models/generation.py preallocates one
contiguous (L, B, H, S_max, D) cache per call — fine for a batch that
lives and dies together, fatal for serving where sequences of wildly
different lengths join and leave every step.  This module is the
vLLM-style answer (PagedAttention, arXiv 2309.06180): KV lives in a
fixed pool of ``block_size``-token blocks, each sequence holds an
ordered page table of block ids, and the pool arrays are DONATED into
the decode jit and updated in place — steady-state decode allocates no
device memory at all.

Layout: ``k``/``v`` are ``(L, num_blocks, H, block_size, D)``; the
gathered per-sequence view reassembles ``(H, W*block_size, D)`` in
absolute-position order, so the attention math (shared
``generation._attn_core``) is bit-identical to the contiguous cache.

Block 0 of every shard is a reserved TRASH block: masked lanes (inactive
slots, prefill padding) route their writes there, which keeps every
scatter in the jit fully dense — no branches, no recompiles.

Optional int8 storage (``quantize_kv=True``) stores one symmetric scale
per (token, head) row via runtime/quantization.py's row quantizers —
per-row layout = ``block_layout(D, D)`` so the scale tensor is exactly
``(L, num_blocks, H, block_size)`` f32.  Arming follows the repo's
DISARMED discipline: when the configuration cannot profit (scale
overhead >= byte savings, or an unsupported pool dtype) the pool warns
loudly naming the blocker and serves full-precision instead.

Sharding (``shards > 1``): the block axis and the allocator are split
into per-shard ranges so a shard_map over the slot axis sees only local
blocks — the placement-semantics argument for why sharded decode moves
zero collective bytes (see runtime/comm_accounting.
serving_decode_collectives).

Prefix caching (SGLang-style RadixAttention, arXiv 2312.07104): each
shard additionally keeps a radix tree over block CONTENT — a node per
physical block, keyed by the token tuple whose KV the block holds,
chained parent→child in position order.  A new request walks the tree
(:meth:`prefix_lookup`), maps every fully-matching block read-only into
its own page table (:meth:`prefix_attach`, refcounted), and COW-splits
the first divergent block: the partial match is device-copied into a
private block the request may write into.  Completed prefills publish
their prompt blocks back into the tree (:meth:`prefix_insert`).  Shared
blocks are returned to the free list only when BOTH every mapping
request has freed them AND the cache reclaims the node (LRU,
unreferenced leaves first) — eviction never touches a block a live
request still maps, and the trash block (0) is never cached.

Window-expired reclamation (serving/sparse_context.py): under a
sliding-window attention policy, pages below every remaining query's
window can never be gathered again — :meth:`window_expired_free`
returns those PRIVATE blocks to the allocator early, recording the gap
as a ``None`` hole in the page table so logical position ↔ list index
stays intact (``table_row`` maps holes to the trash block; the sparse
gather's sentinel positions mask them).  Tree-owned blocks are NEVER
window-freed: the prefix cache's refcounts outrank the window policy,
so a shared prefix stays resident for the requests (and the tree) that
still hold it.
"""
import functools
from typing import Dict, List, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

TRASH_BLOCK = 0          # per-shard block 0 absorbs masked writes


@functools.partial(jax.jit, donate_argnums=0)
def _cow_copy_rows(arrs, src, dst):
    """Copy one block's rows across every pool tensor (the COW split).
    ``src``/``dst`` are TRACED scalars, so every (src, dst) pair reuses
    ONE compiled program per pool shape — block churn never recompiles —
    and the donated input keeps the copy allocation-free on the pool."""
    return tuple(a.at[:, dst].set(a[:, src]) for a in arrs)


class PoolTensors(NamedTuple):
    """The device-side pool state threaded through (and donated into)
    the decode/prefill jits.  ``k_scale``/``v_scale`` are None unless
    int8 KV is armed."""
    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def arrays(self):
        return tuple(t for t in self if t is not None)


class _PrefixNode:
    """One physical block in a shard's prefix tree.  ``tokens`` is the
    (≤ block_size) token tuple whose KV rows the block holds; ``refs``
    counts live requests currently mapping the block read-only.  The
    node itself keeps the block resident after refs drop to zero — that
    is the cache — until LRU reclaim returns it to the free list."""
    __slots__ = ("tokens", "block", "parent", "children", "refs", "tick")

    def __init__(self, tokens, block, parent, tick):
        self.tokens = tokens
        self.block = block
        self.parent = parent
        self.children = {}
        self.refs = 0
        self.tick = tick


def _common_prefix_len(a, b):
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class PagedKVPool:
    """Fixed device block pool + host-side block allocator/page tables.

    ``num_blocks`` is the TOTAL block count across shards (must divide by
    ``shards``); one block per shard is reserved as trash, so the usable
    capacity is ``num_blocks - shards`` blocks.
    """

    def __init__(self, cfg, *, num_blocks, block_size=16, shards=1,
                 mesh=None, axis_name="data", quantize_kv=False,
                 dtype=None):
        assert num_blocks % shards == 0, \
            f"num_blocks={num_blocks} must divide shards={shards}"
        assert num_blocks // shards >= 2, \
            "need at least one usable block per shard beyond the trash block"
        assert block_size >= 1
        self.cfg = cfg
        self.block_size = int(block_size)
        self.shards = int(shards)
        self.mesh = mesh
        self.axis_name = axis_name
        self.num_blocks = int(num_blocks)
        self.blocks_per_shard = self.num_blocks // self.shards
        self.dtype = dtype or cfg.dtype
        self.quantized = self._arm_quantized_kv(quantize_kv)
        # compiled-program registry seam (telemetry/programs.py): the
        # owning InferenceEngine installs its registry here so the
        # COW-split copy joins the same program view the serving jits
        # report to; None (standalone pools) skips registration
        self.programs = None

        L, H, D = cfg.n_layer, cfg.n_head, cfg.head_dim
        bs = self.block_size
        kv_shape = (L, self.num_blocks, H, bs, D)
        store = jnp.int8 if self.quantized else self.dtype
        k = jnp.zeros(kv_shape, store)
        v = jnp.zeros(kv_shape, store)
        sk = sv = None
        if self.quantized:
            sk = jnp.zeros((L, self.num_blocks, H, bs), jnp.float32)
            sv = jnp.zeros((L, self.num_blocks, H, bs), jnp.float32)
        if mesh is not None and shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            put = lambda t, spec: jax.device_put(
                t, NamedSharding(mesh, spec))
            k = put(k, P(None, axis_name))
            v = put(v, P(None, axis_name))
            if self.quantized:
                sk = put(sk, P(None, axis_name))
                sv = put(sv, P(None, axis_name))
        self.tensors = PoolTensors(k, v, sk, sv)

        # host-side allocator: per-shard sorted free lists (popping the
        # smallest id keeps runs deterministic), local block ids — the
        # trash block (0) is never handed out
        self._free: List[List[int]] = [
            list(range(1, self.blocks_per_shard))
            for _ in range(self.shards)]
        self._blocks: Dict[int, List[int]] = {}    # rid -> local block ids
        self._shard_of: Dict[int, int] = {}
        self._positions: Dict[int, int] = {}       # rid -> covered positions

        # prefix cache: per-shard radix tree over block content.  The
        # sentinel roots hold no block; ``_nodes`` maps local block id ->
        # node; ``_shared`` lists, per rid, the tree-owned blocks the rid
        # maps read-only (free() derefs these instead of recycling them).
        self._roots: List[_PrefixNode] = [
            _PrefixNode((), None, None, 0) for _ in range(self.shards)]
        self._nodes: List[Dict[int, _PrefixNode]] = [
            {} for _ in range(self.shards)]
        self._shared: Dict[int, List[int]] = {}
        self._tick = 0
        self.cow_splits = 0
        self.cache_reclaims = 0
        self.window_frees = 0      # blocks early-freed by window expiry

    # -- arming ---------------------------------------------------------
    def _arm_quantized_kv(self, requested):
        """int8 KV arms only where it actually saves bytes; every blocked
        request warns loudly (the armed-or-warns DISARMED discipline)."""
        if not requested:
            return False
        elem = np.dtype(self.dtype).itemsize
        D = self.cfg.head_dim
        if np.dtype(self.dtype) == np.float64:
            logger.warning(
                "PagedKVPool: int8 KV quantization DISARMED — pool dtype "
                "float64 is not supported by the symmetric per-row scheme "
                "(scales are f32); serving full-precision KV instead.")
            return False
        if D * (elem - 1) <= 4:
            logger.warning(
                "PagedKVPool: int8 KV quantization DISARMED — head_dim=%d "
                "at %s saves %d bytes/row but the per-(token,head) f32 "
                "scale costs 4; int8 would GROW the pool. Serving "
                "full-precision KV instead.",
                D, np.dtype(self.dtype).name, D * (elem - 1))
            return False
        return True

    # -- allocator ------------------------------------------------------
    def blocks_needed(self, n_positions: int) -> int:
        return -(-int(n_positions) // self.block_size)

    def alloc(self, rid: int, shard: int, n_positions: int) -> bool:
        """Ensure ``rid`` (pinned to ``shard``) owns enough blocks to
        cover ``n_positions`` absolute positions.  Returns False — with
        NOTHING changed — when the shard's free list cannot cover the
        growth; the caller preempts a victim and retries."""
        assert 0 <= shard < self.shards
        have = self._blocks.setdefault(rid, [])
        prev = self._shard_of.setdefault(rid, shard)
        assert prev == shard, f"rid {rid} moved shards {prev}->{shard}"
        need = self.blocks_needed(n_positions) - len(have)
        while need > len(self._free[shard]) and self._reclaim_block(shard):
            pass
        if need > len(self._free[shard]):
            if not have:
                self._drop(rid)
            return False
        for _ in range(max(0, need)):
            have.append(self._free[shard].pop(0))
        self._positions[rid] = max(self._positions.get(rid, 0),
                                   int(n_positions))
        return True

    def free(self, rid: int) -> None:
        """Release every block of ``rid``: private blocks return to the
        shard's free list; tree-owned (prefix-shared) blocks are DEREFED
        instead — they stay resident in the cache until LRU reclaim."""
        blocks = self._blocks.pop(rid, [])
        shard = self._shard_of.pop(rid, 0)
        self._positions.pop(rid, None)
        shared = set(self._shared.pop(rid, ()))
        nodes = self._nodes[shard]
        recycled = []
        for b in blocks:
            if b is None:             # window-expired hole, already freed
                continue
            node = nodes.get(b) if b in shared else None
            if node is not None:
                node.refs -= 1
            else:
                recycled.append(b)
        self._free[shard] = sorted(self._free[shard] + recycled)

    def window_expired_free(self, rid: int, first_active_block: int, *,
                            keep_blocks: int = 0) -> int:
        """Early-free the PRIVATE blocks of ``rid`` whose logical index
        has fallen below ``first_active_block`` — under a sliding-window
        policy no remaining query can ever gather them again.  The first
        ``keep_blocks`` logical blocks (the policy's global anchors) are
        always kept.  Freed slots become ``None`` holes so the page
        table keeps its positional indexing; tree-owned (prefix-shared)
        blocks are SKIPPED, refs untouched — the radix tree's ownership
        outranks the window.  Returns the number of blocks freed."""
        blocks = self._blocks.get(rid)
        if not blocks:
            return 0
        shard = self._shard_of[rid]
        shared = set(self._shared.get(rid, ()))
        nodes = self._nodes[shard]
        hi = min(int(first_active_block), len(blocks))
        recycled = []
        for i in range(max(0, int(keep_blocks)), hi):
            b = blocks[i]
            if b is None or b in shared or b in nodes:
                continue
            blocks[i] = None
            recycled.append(b)
        if recycled:
            self._free[shard] = sorted(self._free[shard] + recycled)
            self.window_frees += len(recycled)
        return len(recycled)

    def _drop(self, rid):
        self._blocks.pop(rid, None)
        self._shard_of.pop(rid, None)
        self._positions.pop(rid, None)
        self._shared.pop(rid, None)

    def table_row(self, rid: int, width: int) -> np.ndarray:
        """LOCAL block ids of ``rid`` padded with the trash block to the
        fixed table width (the decode jit's static W).  Window-expired
        holes (``None``) map to the trash block too — their positions
        are masked out by the policy before they could be gathered."""
        blocks = self._blocks.get(rid, [])
        assert len(blocks) <= width, \
            f"rid {rid} holds {len(blocks)} blocks > table width {width}"
        row = np.full(width, TRASH_BLOCK, np.int32)
        row[:len(blocks)] = [TRASH_BLOCK if b is None else b
                             for b in blocks]
        return row

    def global_table_row(self, rid: int, width: int) -> np.ndarray:
        """GLOBAL block ids of ``rid``: local ids offset by the owning
        shard's base (``shard * blocks_per_shard``), padding mapped to
        that shard's OWN trash block.  The decode shard_map sees only
        local ids (:meth:`table_row`); a host-side gather/scatter over
        the full pool tensors — the KV-handoff export/import path —
        addresses the unsplit block axis and needs these."""
        shard = self._shard_of.get(rid, 0)
        base = np.int32(shard * self.blocks_per_shard)
        return self.table_row(rid, width) + base

    def free_blocks(self, shard: int) -> int:
        """Free blocks on one shard — the admission slot-ranking signal
        (the engine steers new sequences toward the least-loaded shard)."""
        return len(self._free[shard])

    def blocks_of(self, rid: int) -> int:
        """Blocks currently allocated to ``rid`` (0 when unknown) — the
        payload size a KV handoff of this request would transfer.
        Window-expired holes no longer hold pool capacity."""
        return sum(1 for b in self._blocks.get(rid, ()) if b is not None)

    # -- prefix cache (copy-on-write shared blocks) ---------------------
    def _touch(self, node):
        self._tick += 1
        node.tick = self._tick

    def prefix_lookup(self, shard: int, tokens) -> tuple:
        """Walk ``shard``'s radix tree along ``tokens``.  Returns
        ``(full_nodes, cow_node, cow_len)``: the chain of exactly-matching
        full blocks, then the child sharing the longest strict prefix of
        the next block (the COW-split candidate, ``cow_len`` trusted
        positions).  Coverage is capped at ``len(tokens) - 1`` so the
        final prompt position is always computed — the final prefill
        chunk must still run to produce the first-token logits."""
        bs = self.block_size
        limit = len(tokens) - 1
        node = self._roots[shard]
        full = []
        pos = 0
        while pos + bs <= limit:
            key = tuple(int(t) for t in tokens[pos:pos + bs])
            child = node.children.get(key)
            if child is None:
                break
            full.append(child)
            node = child
            pos += bs
        rest = tuple(int(t) for t in tokens[pos:min(pos + bs, limit)])
        cow, cow_len = None, 0
        for child in node.children.values():
            p = _common_prefix_len(child.tokens, rest)
            if p > cow_len:
                cow, cow_len = child, p
        return full, cow, cow_len

    def prefix_attach(self, rid: int, shard: int, tokens) -> int:
        """Map the longest cached prefix of ``tokens`` into ``rid``'s
        (empty) page table: fully-matching blocks are shared read-only
        (refcounted); the first divergent block is COW-split — its
        trusted prefix rows are device-copied into a private block the
        request may write into.  Returns the number of positions covered,
        which the request's prefill can skip entirely."""
        assert not self._blocks.get(rid), \
            f"prefix_attach on rid {rid} with blocks already allocated"
        full, cow, cow_len = self.prefix_lookup(shard, tokens)
        if not full and cow_len == 0:
            return 0
        covered = len(full) * self.block_size
        blocks = []
        for node in full:
            node.refs += 1
            self._touch(node)
            blocks.append(node.block)
        if cow is not None and cow_len > 0:
            if not self._free[shard]:
                self._reclaim_block(shard)
            if self._free[shard]:
                dst = self._free[shard].pop(0)
                self._cow_copy(shard, cow.block, dst)
                self._touch(cow)
                blocks.append(dst)
                covered += cow_len
                self.cow_splits += 1
        self._blocks[rid] = blocks
        self._shard_of[rid] = shard
        self._positions[rid] = covered
        self._shared[rid] = [n.block for n in full]
        return covered

    def prefix_insert(self, rid: int, shard: int, tokens) -> int:
        """Publish ``rid``'s prompt blocks into ``shard``'s radix tree so
        later requests can share them.  Blocks already attached from the
        tree descend without re-insertion; content already cached under a
        DIFFERENT physical block keeps the existing entry (rid's copy
        stays private).  Returns the number of blocks newly shared."""
        bs = self.block_size
        blocks = self._blocks.get(rid, [])
        node = self._roots[shard]
        nodes = self._nodes[shard]
        inserted = 0
        pos = 0
        i = 0
        n = len(tokens)
        while pos < n and i < len(blocks):
            chunk = tuple(int(t) for t in tokens[pos:pos + bs])
            child = node.children.get(chunk)
            if child is not None:
                node = child          # cached already (ours or a twin's)
                self._touch(node)
            else:
                blk = blocks[i]
                if blk is None:       # window-expired hole: the KV
                    break             # content is gone, nothing past it
                                      # can be published
                if blk in nodes:      # block published by an earlier
                    break             # insert of this rid under another
                                      # key — never double-own a block
                child = _PrefixNode(chunk, blk, node, 0)
                child.refs = 1        # rid still maps it
                node.children[chunk] = child
                nodes[blk] = child
                self._touch(child)
                self._shared.setdefault(rid, []).append(blk)
                node = child
                inserted += 1
            pos += bs
            i += 1
        return inserted

    def _cow_copy(self, shard: int, src: int, dst: int) -> None:
        """Device-side copy of one block's rows (the COW split): global
        ids address the unsplit block axis, exactly like the KV-handoff
        scatter, and the result is re-pinned to the pool's sharding so
        the donated dispatch path sees identically-placed arrays."""
        base = shard * self.blocks_per_shard
        g_src, g_dst = np.int32(base + src), np.int32(base + dst)
        if self.programs is not None and not self.programs.has("cow_copy"):
            from deepspeed_tpu.telemetry import register_program

            # first dispatch (warm_cow's trash self-copy in production):
            # the COW split is pure device work, collective-free, and
            # donates the pool — block churn never allocates or syncs
            register_program(
                self.programs, "cow_copy", _cow_copy_rows,
                (self.tensors.arrays, g_src, g_dst),
                contract={"host_transfer_free": True,
                          "collective_free": True,
                          "donates_argnums": (0,)})
        arrs = _cow_copy_rows(self.tensors.arrays, g_src, g_dst)
        if self.mesh is not None and self.shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            spec = NamedSharding(self.mesh, P(None, self.axis_name))
            arrs = tuple(jax.device_put(a, spec) for a in arrs)
        it = iter(arrs)
        self.tensors = PoolTensors(*(next(it) if t is not None else None
                                     for t in self.tensors))

    def warm_cow(self) -> None:
        """Compile the COW-split copy program up front (a trash-block
        self-copy — bit-neutral) so the first REAL split inside a
        recompile-guard window compiles nothing."""
        self._cow_copy(0, TRASH_BLOCK, TRASH_BLOCK)

    def _reclaim_block(self, shard: int) -> bool:
        """Evict ONE least-recently-used unreferenced leaf node from the
        shard's prefix tree, returning its block to the free list.
        Blocks still mapped by a live request (refs > 0) are never
        reclaimed — eviction respects refcounts."""
        best = None
        stack = [self._roots[shard]]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (node.block is not None and not node.children
                    and node.refs <= 0):
                if best is None or node.tick < best.tick:
                    best = node
        if best is None:
            return False
        del best.parent.children[best.tokens]
        self._nodes[shard].pop(best.block, None)
        self._free[shard] = sorted(self._free[shard] + [best.block])
        self.cache_reclaims += 1
        return True

    def cached_blocks(self, shard: Optional[int] = None) -> int:
        """Blocks currently owned by the prefix tree (shared + resident)."""
        if shard is not None:
            return len(self._nodes[shard])
        return sum(len(n) for n in self._nodes)

    # -- accounting -----------------------------------------------------
    def device_bytes(self) -> int:
        """Per-shard device bytes of the pool tensors, priced through
        the shared analytic builder (``memory_accounting.
        kv_pool_bytes``) — byte-exact against the allocated k/v (+
        scale) arrays, asserted by tests/unit/test_memory_accounting."""
        from deepspeed_tpu.runtime.memory_accounting import kv_pool_bytes

        cfg = self.cfg
        return kv_pool_bytes(
            cfg.n_layer, self.num_blocks, cfg.n_head, self.block_size,
            cfg.head_dim, kv_dtype=np.dtype(self.dtype).name,
            quantized=self.quantized, shards=self.shards)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - self.shards          # minus trash blocks

    @property
    def blocks_in_use(self) -> int:
        """DISTINCT blocks not on a free list — refcount-shared blocks
        count ONCE no matter how many page tables map them, and
        cache-resident blocks (refs == 0, awaiting reclaim) count too:
        they genuinely occupy pool capacity."""
        return self.usable_blocks - sum(len(f) for f in self._free)

    def occupancy(self) -> float:
        return self.blocks_in_use / max(1, self.usable_blocks)

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of MAPPED pool positions not
        covered by live tokens (tail slack of each sequence's last
        block).  Shared blocks appear once per mapping request on both
        sides of the ratio, so this stays a pure slack measure under
        prefix sharing.  0 = every mapped slot holds a token.  Clamped
        at 0: window-expired frees can leave more live positions than
        mapped slots (the freed tokens are no longer resident)."""
        allocated = sum(
            sum(1 for blk in b if blk is not None)
            for b in self._blocks.values()) * self.block_size
        if allocated == 0:
            return 0.0
        used = sum(self._positions.values())
        return max(0.0, 1.0 - used / allocated)

    def stats(self) -> dict:
        return {
            "pool_device_bytes": self.device_bytes(),
            "blocks_total": self.usable_blocks,
            "blocks_in_use": self.blocks_in_use,
            "occupancy": self.occupancy(),
            "fragmentation": self.fragmentation(),
            "block_size": self.block_size,
            "shards": self.shards,
            "quantized": self.quantized,
            "free_per_shard": [len(f) for f in self._free],
            "prefix_cached_blocks": self.cached_blocks(),
            "prefix_shared_refs": sum(
                n.refs for nodes in self._nodes for n in nodes.values()),
            "prefix_cow_splits": self.cow_splits,
            "prefix_cache_reclaims": self.cache_reclaims,
            "window_expired_frees": self.window_frees,
        }
