"""Paged KV cache: fixed block pool + per-sequence page tables.

The single-sequence decode loop in models/generation.py preallocates one
contiguous (L, B, H, S_max, D) cache per call — fine for a batch that
lives and dies together, fatal for serving where sequences of wildly
different lengths join and leave every step.  This module is the
vLLM-style answer (PagedAttention, arXiv 2309.06180): KV lives in a
fixed pool of ``block_size``-token blocks, each sequence holds an
ordered page table of block ids, and the pool arrays are DONATED into
the decode jit and updated in place — steady-state decode allocates no
device memory at all.

Layout: ``k``/``v`` are ``(L, num_blocks, H, block_size, D)``; the
gathered per-sequence view reassembles ``(H, W*block_size, D)`` in
absolute-position order, so the attention math (shared
``generation._attn_core``) is bit-identical to the contiguous cache.

Block 0 of every shard is a reserved TRASH block: masked lanes (inactive
slots, prefill padding) route their writes there, which keeps every
scatter in the jit fully dense — no branches, no recompiles.

Optional int8 storage (``quantize_kv=True``) stores one symmetric scale
per (token, head) row via runtime/quantization.py's row quantizers —
per-row layout = ``block_layout(D, D)`` so the scale tensor is exactly
``(L, num_blocks, H, block_size)`` f32.  Arming follows the repo's
DISARMED discipline: when the configuration cannot profit (scale
overhead >= byte savings, or an unsupported pool dtype) the pool warns
loudly naming the blocker and serves full-precision instead.

Sharding (``shards > 1``): the block axis and the allocator are split
into per-shard ranges so a shard_map over the slot axis sees only local
blocks — the placement-semantics argument for why sharded decode moves
zero collective bytes (see runtime/comm_accounting.
serving_decode_collectives).
"""
from typing import Dict, List, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

TRASH_BLOCK = 0          # per-shard block 0 absorbs masked writes


class PoolTensors(NamedTuple):
    """The device-side pool state threaded through (and donated into)
    the decode/prefill jits.  ``k_scale``/``v_scale`` are None unless
    int8 KV is armed."""
    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def arrays(self):
        return tuple(t for t in self if t is not None)


class PagedKVPool:
    """Fixed device block pool + host-side block allocator/page tables.

    ``num_blocks`` is the TOTAL block count across shards (must divide by
    ``shards``); one block per shard is reserved as trash, so the usable
    capacity is ``num_blocks - shards`` blocks.
    """

    def __init__(self, cfg, *, num_blocks, block_size=16, shards=1,
                 mesh=None, axis_name="data", quantize_kv=False,
                 dtype=None):
        assert num_blocks % shards == 0, \
            f"num_blocks={num_blocks} must divide shards={shards}"
        assert num_blocks // shards >= 2, \
            "need at least one usable block per shard beyond the trash block"
        assert block_size >= 1
        self.cfg = cfg
        self.block_size = int(block_size)
        self.shards = int(shards)
        self.mesh = mesh
        self.axis_name = axis_name
        self.num_blocks = int(num_blocks)
        self.blocks_per_shard = self.num_blocks // self.shards
        self.dtype = dtype or cfg.dtype
        self.quantized = self._arm_quantized_kv(quantize_kv)

        L, H, D = cfg.n_layer, cfg.n_head, cfg.head_dim
        bs = self.block_size
        kv_shape = (L, self.num_blocks, H, bs, D)
        store = jnp.int8 if self.quantized else self.dtype
        k = jnp.zeros(kv_shape, store)
        v = jnp.zeros(kv_shape, store)
        sk = sv = None
        if self.quantized:
            sk = jnp.zeros((L, self.num_blocks, H, bs), jnp.float32)
            sv = jnp.zeros((L, self.num_blocks, H, bs), jnp.float32)
        if mesh is not None and shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            put = lambda t, spec: jax.device_put(
                t, NamedSharding(mesh, spec))
            k = put(k, P(None, axis_name))
            v = put(v, P(None, axis_name))
            if self.quantized:
                sk = put(sk, P(None, axis_name))
                sv = put(sv, P(None, axis_name))
        self.tensors = PoolTensors(k, v, sk, sv)

        # host-side allocator: per-shard sorted free lists (popping the
        # smallest id keeps runs deterministic), local block ids — the
        # trash block (0) is never handed out
        self._free: List[List[int]] = [
            list(range(1, self.blocks_per_shard))
            for _ in range(self.shards)]
        self._blocks: Dict[int, List[int]] = {}    # rid -> local block ids
        self._shard_of: Dict[int, int] = {}
        self._positions: Dict[int, int] = {}       # rid -> covered positions

    # -- arming ---------------------------------------------------------
    def _arm_quantized_kv(self, requested):
        """int8 KV arms only where it actually saves bytes; every blocked
        request warns loudly (the armed-or-warns DISARMED discipline)."""
        if not requested:
            return False
        elem = np.dtype(self.dtype).itemsize
        D = self.cfg.head_dim
        if np.dtype(self.dtype) == np.float64:
            logger.warning(
                "PagedKVPool: int8 KV quantization DISARMED — pool dtype "
                "float64 is not supported by the symmetric per-row scheme "
                "(scales are f32); serving full-precision KV instead.")
            return False
        if D * (elem - 1) <= 4:
            logger.warning(
                "PagedKVPool: int8 KV quantization DISARMED — head_dim=%d "
                "at %s saves %d bytes/row but the per-(token,head) f32 "
                "scale costs 4; int8 would GROW the pool. Serving "
                "full-precision KV instead.",
                D, np.dtype(self.dtype).name, D * (elem - 1))
            return False
        return True

    # -- allocator ------------------------------------------------------
    def blocks_needed(self, n_positions: int) -> int:
        return -(-int(n_positions) // self.block_size)

    def alloc(self, rid: int, shard: int, n_positions: int) -> bool:
        """Ensure ``rid`` (pinned to ``shard``) owns enough blocks to
        cover ``n_positions`` absolute positions.  Returns False — with
        NOTHING changed — when the shard's free list cannot cover the
        growth; the caller preempts a victim and retries."""
        assert 0 <= shard < self.shards
        have = self._blocks.setdefault(rid, [])
        prev = self._shard_of.setdefault(rid, shard)
        assert prev == shard, f"rid {rid} moved shards {prev}->{shard}"
        need = self.blocks_needed(n_positions) - len(have)
        if need > len(self._free[shard]):
            if not have:
                self._drop(rid)
            return False
        for _ in range(max(0, need)):
            have.append(self._free[shard].pop(0))
        self._positions[rid] = max(self._positions.get(rid, 0),
                                   int(n_positions))
        return True

    def free(self, rid: int) -> None:
        """Return every block of ``rid`` to its shard's free list."""
        blocks = self._blocks.pop(rid, [])
        shard = self._shard_of.pop(rid, 0)
        self._positions.pop(rid, None)
        self._free[shard] = sorted(self._free[shard] + blocks)

    def _drop(self, rid):
        self._blocks.pop(rid, None)
        self._shard_of.pop(rid, None)
        self._positions.pop(rid, None)

    def table_row(self, rid: int, width: int) -> np.ndarray:
        """LOCAL block ids of ``rid`` padded with the trash block to the
        fixed table width (the decode jit's static W)."""
        blocks = self._blocks.get(rid, [])
        assert len(blocks) <= width, \
            f"rid {rid} holds {len(blocks)} blocks > table width {width}"
        row = np.full(width, TRASH_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        return row

    def global_table_row(self, rid: int, width: int) -> np.ndarray:
        """GLOBAL block ids of ``rid``: local ids offset by the owning
        shard's base (``shard * blocks_per_shard``), padding mapped to
        that shard's OWN trash block.  The decode shard_map sees only
        local ids (:meth:`table_row`); a host-side gather/scatter over
        the full pool tensors — the KV-handoff export/import path —
        addresses the unsplit block axis and needs these."""
        shard = self._shard_of.get(rid, 0)
        base = np.int32(shard * self.blocks_per_shard)
        return self.table_row(rid, width) + base

    def free_blocks(self, shard: int) -> int:
        """Free blocks on one shard — the admission slot-ranking signal
        (the engine steers new sequences toward the least-loaded shard)."""
        return len(self._free[shard])

    def blocks_of(self, rid: int) -> int:
        """Blocks currently allocated to ``rid`` (0 when unknown) — the
        payload size a KV handoff of this request would transfer."""
        return len(self._blocks.get(rid, ()))

    # -- accounting -----------------------------------------------------
    def device_bytes(self) -> int:
        """Per-shard device bytes of the pool tensors, priced through
        the shared analytic builder (``memory_accounting.
        kv_pool_bytes``) — byte-exact against the allocated k/v (+
        scale) arrays, asserted by tests/unit/test_memory_accounting."""
        from deepspeed_tpu.runtime.memory_accounting import kv_pool_bytes

        cfg = self.cfg
        return kv_pool_bytes(
            cfg.n_layer, self.num_blocks, cfg.n_head, self.block_size,
            cfg.head_dim, kv_dtype=np.dtype(self.dtype).name,
            quantized=self.quantized, shards=self.shards)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - self.shards          # minus trash blocks

    @property
    def blocks_in_use(self) -> int:
        return sum(len(b) for b in self._blocks.values())

    def occupancy(self) -> float:
        return self.blocks_in_use / max(1, self.usable_blocks)

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of ALLOCATED pool positions
        not covered by live tokens (tail slack of each sequence's last
        block).  0 = every allocated slot holds a token."""
        allocated = self.blocks_in_use * self.block_size
        if allocated == 0:
            return 0.0
        used = sum(self._positions.values())
        return 1.0 - used / allocated

    def stats(self) -> dict:
        return {
            "pool_device_bytes": self.device_bytes(),
            "blocks_total": self.usable_blocks,
            "blocks_in_use": self.blocks_in_use,
            "occupancy": self.occupancy(),
            "fragmentation": self.fragmentation(),
            "block_size": self.block_size,
            "shards": self.shards,
            "quantized": self.quantized,
            "free_per_shard": [len(f) for f in self._free],
        }
