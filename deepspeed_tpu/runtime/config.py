"""DeepSpeedConfig: ds_config JSON -> typed config object.

Key-for-key parity with the reference config system (reference:
deepspeed/runtime/config.py:515-783), including the 6-case batch-size
triangulation (:675) and elasticity integration (:538-592).  TPU extensions
(bf16, mesh) are additive.
"""
import json
import os

from deepspeed_tpu.elasticity import (compute_elastic_config, elasticity_enabled,
                                      ensure_immutable_elastic_config)
from deepspeed_tpu.elasticity.config import (ElasticityConfigError,
                                             ElasticityIncompatibleWorldSize)
from deepspeed_tpu.elasticity.constants import (IGNORE_NON_ELASTIC_BATCH_INFO,
                                                IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
from deepspeed_tpu.profiling.config import DeepSpeedFlopsProfilerConfig
from deepspeed_tpu.runtime.activation_checkpointing.config import \
    DeepSpeedActivationCheckpointingConfig
from deepspeed_tpu.runtime.config_utils import (dict_raise_error_on_duplicate_keys,
                                                get_scalar_param)
from deepspeed_tpu.runtime.constants import *  # noqa: F401,F403
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.constants import (ZERO_OPTIMIZATION,
                                                  ZERO_OPTIMIZATION_DISABLED)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.version import __version__

TENSOR_CORE_ALIGN_SIZE = 8
# optimizer-name constants come from runtime/constants.py via the star import


class DeepSpeedConfigError(Exception):
    pass


def get_fp16_enabled(param_dict):
    if FP16 in param_dict:
        return get_scalar_param(param_dict[FP16], FP16_ENABLED, FP16_ENABLED_DEFAULT)
    return False


def get_bf16_enabled(param_dict):
    if BF16 in param_dict:
        return get_scalar_param(param_dict[BF16], BF16_ENABLED, BF16_ENABLED_DEFAULT)
    return False


def get_amp_enabled(param_dict):
    if AMP in param_dict:
        return get_scalar_param(param_dict[AMP], AMP_ENABLED, AMP_ENABLED_DEFAULT)
    return False


def get_amp_params(param_dict):
    if AMP in param_dict:
        d = dict(param_dict[AMP])
        d.pop(AMP_ENABLED, None)
        return d
    return False


def get_loss_scale(param_dict):
    if get_fp16_enabled(param_dict):
        return get_scalar_param(param_dict[FP16], FP16_LOSS_SCALE, FP16_LOSS_SCALE_DEFAULT)
    return FP16_LOSS_SCALE_DEFAULT


def get_initial_dynamic_scale(param_dict):
    if get_fp16_enabled(param_dict):
        power = get_scalar_param(param_dict[FP16], FP16_INITIAL_SCALE_POWER,
                                 FP16_INITIAL_SCALE_POWER_DEFAULT)
    else:
        power = FP16_INITIAL_SCALE_POWER_DEFAULT
    return 2 ** power


def get_dynamic_loss_scale_args(param_dict):
    loss_scale_args = None
    if get_fp16_enabled(param_dict):
        fp16_dict = param_dict[FP16]
        dynamic_props = [FP16_INITIAL_SCALE_POWER, FP16_LOSS_SCALE_WINDOW,
                         FP16_MIN_LOSS_SCALE, FP16_HYSTERESIS]
        if any(prop in fp16_dict for prop in dynamic_props):
            init_scale = get_scalar_param(fp16_dict, FP16_INITIAL_SCALE_POWER,
                                          FP16_INITIAL_SCALE_POWER_DEFAULT)
            scale_window = get_scalar_param(fp16_dict, FP16_LOSS_SCALE_WINDOW,
                                            FP16_LOSS_SCALE_WINDOW_DEFAULT)
            delayed_shift = get_scalar_param(fp16_dict, FP16_HYSTERESIS,
                                             FP16_HYSTERESIS_DEFAULT)
            min_loss_scale = get_scalar_param(fp16_dict, FP16_MIN_LOSS_SCALE,
                                              FP16_MIN_LOSS_SCALE_DEFAULT)
            loss_scale_args = {
                "init_scale": 2 ** init_scale,
                "scale_window": scale_window,
                "delayed_shift": delayed_shift,
                "min_scale": min_loss_scale,
            }
    return loss_scale_args


def get_gradient_accumulation_steps(param_dict):
    return get_scalar_param(param_dict, GRADIENT_ACCUMULATION_STEPS,
                            GRADIENT_ACCUMULATION_STEPS_DEFAULT)


def get_sparse_gradients_enabled(param_dict):
    return get_scalar_param(param_dict, SPARSE_GRADIENTS, SPARSE_GRADIENTS_DEFAULT)


def get_zero_optimization(param_dict):
    return get_scalar_param(param_dict, ZERO_OPTIMIZATION, ZERO_OPTIMIZATION_DISABLED)


def get_allow_untested_optimizer(param_dict):
    return get_scalar_param(param_dict, ZERO_ALLOW_UNTESTED_OPTIMIZER,
                            ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)


def get_gradient_clipping(param_dict):
    return get_scalar_param(param_dict, GRADIENT_CLIPPING, GRADIENT_CLIPPING_DEFAULT)


def get_checkpoint_params(param_dict):
    return param_dict.get(CHECKPOINT, {})


def get_checkpoint_tag_validation_mode(checkpoint_params):
    """Reference config.py:483-491: 'ignore' | 'warn' | 'fail'."""
    mode = checkpoint_params.get(CHECKPOINT_TAG_VALIDATION,
                                 CHECKPOINT_TAG_VALIDATION_DEFAULT)
    if isinstance(mode, str) and mode.upper() in CHECKPOINT_TAG_VALIDATION_MODES:
        return mode.upper()
    raise ValueError(
        f"Checkpoint config contains invalid tag_validation value "
        f"{mode!r}, expecting one of {CHECKPOINT_TAG_VALIDATION_MODES}")


def get_sparse_attention(param_dict):
    if SPARSE_ATTENTION in param_dict:
        sparsity = param_dict[SPARSE_ATTENTION]
        mode = get_scalar_param(sparsity, SPARSE_ATTENTION_MODE, SPARSE_ATTENTION_MODE_DEFAULT)
        sparsity = dict(sparsity)
        sparsity[SPARSE_ATTENTION_MODE] = mode
        return sparsity
    return None


def get_optimizer_name(param_dict):
    if OPTIMIZER in param_dict and TYPE in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][TYPE]
    return OPTIMIZER_TYPE_DEFAULT


def get_optimizer_params(param_dict):
    if get_optimizer_name(param_dict) is not None and \
            OPTIMIZER_PARAMS in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][OPTIMIZER_PARAMS]
    return None


def get_optimizer_gradient_clipping(param_dict):
    optimizer_params = get_optimizer_params(param_dict)
    if optimizer_params is not None and MAX_GRAD_NORM in optimizer_params:
        return optimizer_params[MAX_GRAD_NORM]
    return None


def get_optimizer_legacy_fusion(param_dict):
    if OPTIMIZER in param_dict and LEGACY_FUSION in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][LEGACY_FUSION]
    return LEGACY_FUSION_DEFAULT


def get_scheduler_name(param_dict):
    if SCHEDULER in param_dict and TYPE in param_dict[SCHEDULER]:
        return param_dict[SCHEDULER][TYPE]
    return SCHEDULER_TYPE_DEFAULT


def get_scheduler_params(param_dict):
    if get_scheduler_name(param_dict) is not None and \
            SCHEDULER_PARAMS in param_dict[SCHEDULER]:
        return param_dict[SCHEDULER][SCHEDULER_PARAMS]
    return None


def get_train_batch_size(param_dict):
    return get_scalar_param(param_dict, TRAIN_BATCH_SIZE, TRAIN_BATCH_SIZE_DEFAULT)


def get_train_micro_batch_size_per_gpu(param_dict):
    return get_scalar_param(param_dict, TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                            TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)


def get_wall_clock_breakdown(param_dict):
    return get_scalar_param(param_dict, WALL_CLOCK_BREAKDOWN, WALL_CLOCK_BREAKDOWN_DEFAULT)


def get_memory_breakdown(param_dict):
    return get_scalar_param(param_dict, MEMORY_BREAKDOWN, MEMORY_BREAKDOWN_DEFAULT)


def get_tensorboard_enabled(param_dict):
    if TENSORBOARD in param_dict:
        return get_scalar_param(param_dict[TENSORBOARD], TENSORBOARD_ENABLED,
                                TENSORBOARD_ENABLED_DEFAULT)
    return False


def get_tensorboard_output_path(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[TENSORBOARD], TENSORBOARD_OUTPUT_PATH,
                                TENSORBOARD_OUTPUT_PATH_DEFAULT)
    return TENSORBOARD_OUTPUT_PATH_DEFAULT


def get_tensorboard_job_name(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[TENSORBOARD], TENSORBOARD_JOB_NAME,
                                TENSORBOARD_JOB_NAME_DEFAULT)
    return TENSORBOARD_JOB_NAME_DEFAULT


def get_steps_per_print(param_dict):
    return get_scalar_param(param_dict, STEPS_PER_PRINT, STEPS_PER_PRINT_DEFAULT)


def get_disable_allgather(param_dict):
    return get_scalar_param(param_dict, DISABLE_ALLGATHER, DISABLE_ALLGATHER_DEFAULT)


def get_dump_state(param_dict):
    return get_scalar_param(param_dict, DUMP_STATE, DUMP_STATE_DEFAULT)


def get_gradient_predivide_factor(param_dict):
    return get_scalar_param(param_dict, GRADIENT_PREDIVIDE_FACTOR,
                            GRADIENT_PREDIVIDE_FACTOR_DEFAULT)


def get_prescale_gradients(param_dict):
    return get_scalar_param(param_dict, PRESCALE_GRADIENTS, PRESCALE_GRADIENTS_DEFAULT)


def get_allreduce_always_fp32(param_dict):
    return get_scalar_param(param_dict, ALLREDUCE_ALWAYS_FP32, ALLREDUCE_ALWAYS_FP32_DEFAULT)


def get_progressive_layer_drop(param_dict):
    d = param_dict.get(PROGRESSIVE_LAYER_DROP, {})
    enabled = get_scalar_param(d, PLD_ENABLED, PLD_ENABLED_DEFAULT)
    theta = get_scalar_param(d, PLD_THETA, PLD_THETA_DEFAULT)
    gamma = get_scalar_param(d, PLD_GAMMA, PLD_GAMMA_DEFAULT)
    return enabled, theta, gamma


def get_mesh_shape(param_dict):
    """TPU extension: explicit mesh axis sizes {"data": -1, "model": 1, "pipe": 1}.

    -1 for the data axis means "whatever is left over" after model/pipe.
    """
    d = param_dict.get(MESH, {})
    shape = {
        MESH_PIPE_AXIS: d.get(MESH_PIPE_AXIS, 1),
        MESH_DATA_AXIS: d.get(MESH_DATA_AXIS, -1),
        MESH_SEQ_AXIS: d.get(MESH_SEQ_AXIS, 1),
        MESH_MODEL_AXIS: d.get(MESH_MODEL_AXIS, 1),
    }
    if d.get(MESH_ALLOW_PARTIAL, False):
        shape[MESH_ALLOW_PARTIAL] = True
    return shape


class DeepSpeedResilienceConfig:
    """"resilience" ds_config section: atomic checkpoints + watchdog.

    Everything defaults safe-and-on for the commit path (atomic, fsync,
    verify) and off for the opt-in behaviors (auto-resume, watchdog,
    retention GC).
    """

    def __init__(self, param_dict):
        d = param_dict.get(RESILIENCE, {})
        wd = d.get(RESILIENCE_WATCHDOG, {})
        self.atomic_checkpoints = bool(d.get(RESILIENCE_ATOMIC,
                                             RESILIENCE_ATOMIC_DEFAULT))
        self.fsync = bool(d.get(RESILIENCE_FSYNC, RESILIENCE_FSYNC_DEFAULT))
        self.keep_checkpoint_tags = int(d.get(RESILIENCE_KEEP_TAGS,
                                              RESILIENCE_KEEP_TAGS_DEFAULT))
        self.verify_on_load = bool(d.get(RESILIENCE_VERIFY_ON_LOAD,
                                         RESILIENCE_VERIFY_ON_LOAD_DEFAULT))
        self.auto_resume = bool(d.get(RESILIENCE_AUTO_RESUME,
                                      RESILIENCE_AUTO_RESUME_DEFAULT))
        self.async_commit = bool(d.get(RESILIENCE_ASYNC_COMMIT,
                                       RESILIENCE_ASYNC_COMMIT_DEFAULT))
        self.watchdog_enabled = bool(wd.get(WATCHDOG_ENABLED,
                                            WATCHDOG_ENABLED_DEFAULT))
        self.watchdog_max_skipped_steps = int(
            wd.get(WATCHDOG_MAX_SKIPPED, WATCHDOG_MAX_SKIPPED_DEFAULT))
        self.watchdog_max_nan_losses = int(
            wd.get(WATCHDOG_MAX_NAN, WATCHDOG_MAX_NAN_DEFAULT))
        self.watchdog_stall_timeout = float(
            wd.get(WATCHDOG_STALL_TIMEOUT, WATCHDOG_STALL_TIMEOUT_DEFAULT))
        self.watchdog_action = wd.get(WATCHDOG_ACTION,
                                      WATCHDOG_ACTION_DEFAULT)
        if self.watchdog_action not in ("abort", "continue"):
            raise ValueError(
                f'resilience.watchdog.{WATCHDOG_ACTION} must be "abort" or '
                f'"continue", got {self.watchdog_action!r}')
        self.watchdog_emergency_dir = wd.get(WATCHDOG_EMERGENCY_DIR,
                                             WATCHDOG_EMERGENCY_DIR_DEFAULT)
        sup = d.get(RESILIENCE_SUPERVISOR, {})
        self.supervisor_heartbeat_timeout_steps = int(
            sup.get(SUPERVISOR_HEARTBEAT_TIMEOUT,
                    SUPERVISOR_HEARTBEAT_TIMEOUT_DEFAULT))
        self.supervisor_max_transient_retries = int(
            sup.get(SUPERVISOR_MAX_TRANSIENT_RETRIES,
                    SUPERVISOR_MAX_TRANSIENT_RETRIES_DEFAULT))
        self.supervisor_retry_backoff_steps = int(
            sup.get(SUPERVISOR_RETRY_BACKOFF,
                    SUPERVISOR_RETRY_BACKOFF_DEFAULT))
        self.supervisor_max_recovery_attempts = int(
            sup.get(SUPERVISOR_MAX_RECOVERY_ATTEMPTS,
                    SUPERVISOR_MAX_RECOVERY_ATTEMPTS_DEFAULT))
        self.supervisor_max_restarts = int(
            sup.get(SUPERVISOR_MAX_RESTARTS, SUPERVISOR_MAX_RESTARTS_DEFAULT))
        self.supervisor_checkpoint_every_steps = int(
            sup.get(SUPERVISOR_CHECKPOINT_EVERY,
                    SUPERVISOR_CHECKPOINT_EVERY_DEFAULT))
        if self.supervisor_heartbeat_timeout_steps < 1:
            raise ValueError(
                f"resilience.supervisor.{SUPERVISOR_HEARTBEAT_TIMEOUT} must "
                f"be >= 1 step (a zero window would declare every peer dead "
                f"on its first in-flight step), got "
                f"{self.supervisor_heartbeat_timeout_steps}")
        for label, val in (
                (SUPERVISOR_MAX_TRANSIENT_RETRIES,
                 self.supervisor_max_transient_retries),
                (SUPERVISOR_RETRY_BACKOFF,
                 self.supervisor_retry_backoff_steps),
                (SUPERVISOR_CHECKPOINT_EVERY,
                 self.supervisor_checkpoint_every_steps)):
            if val < 0:
                raise ValueError(
                    f"resilience.supervisor.{label} must be >= 0, got {val}")
        for label, val in (
                (SUPERVISOR_MAX_RECOVERY_ATTEMPTS,
                 self.supervisor_max_recovery_attempts),
                (SUPERVISOR_MAX_RESTARTS, self.supervisor_max_restarts)):
            if val < 1:
                raise ValueError(
                    f"resilience.supervisor.{label} must be >= 1 (the "
                    f"supervisor needs at least one recovery attempt to "
                    f"recover at all), got {val}")
        integ = d.get(RESILIENCE_INTEGRITY, {})
        self.integrity_enabled = bool(integ.get(INTEGRITY_ENABLED,
                                                INTEGRITY_ENABLED_DEFAULT))
        self.integrity_window = int(integ.get(INTEGRITY_WINDOW,
                                              INTEGRITY_WINDOW_DEFAULT))
        self.integrity_z_threshold = float(
            integ.get(INTEGRITY_Z_THRESHOLD, INTEGRITY_Z_THRESHOLD_DEFAULT))
        self.integrity_min_history = int(
            integ.get(INTEGRITY_MIN_HISTORY, INTEGRITY_MIN_HISTORY_DEFAULT))
        self.integrity_confirm_steps = int(
            integ.get(INTEGRITY_CONFIRM_STEPS,
                      INTEGRITY_CONFIRM_STEPS_DEFAULT))
        self.integrity_clear_steps = int(
            integ.get(INTEGRITY_CLEAR_STEPS, INTEGRITY_CLEAR_STEPS_DEFAULT))
        self.integrity_vote_every_steps = int(
            integ.get(INTEGRITY_VOTE_EVERY, INTEGRITY_VOTE_EVERY_DEFAULT))
        self.integrity_dup_check_every_steps = int(
            integ.get(INTEGRITY_DUP_CHECK_EVERY,
                      INTEGRITY_DUP_CHECK_EVERY_DEFAULT))
        self.integrity_quarantine_after = int(
            integ.get(INTEGRITY_QUARANTINE_AFTER,
                      INTEGRITY_QUARANTINE_AFTER_DEFAULT))
        if self.integrity_window < 2:
            raise ValueError(
                f"resilience.integrity.{INTEGRITY_WINDOW} must be >= 2 "
                f"steps (a shorter window has no variance to score "
                f"against), got {self.integrity_window}")
        if self.integrity_z_threshold <= 0:
            raise ValueError(
                f"resilience.integrity.{INTEGRITY_Z_THRESHOLD} must be "
                f"> 0 (0 would flag every step as corrupt), got "
                f"{self.integrity_z_threshold}")
        for label, val, lo in (
                (INTEGRITY_MIN_HISTORY, self.integrity_min_history, 1),
                (INTEGRITY_CONFIRM_STEPS, self.integrity_confirm_steps, 1),
                (INTEGRITY_CLEAR_STEPS, self.integrity_clear_steps, 1),
                (INTEGRITY_QUARANTINE_AFTER,
                 self.integrity_quarantine_after, 1),
                (INTEGRITY_VOTE_EVERY,
                 self.integrity_vote_every_steps, 0),
                (INTEGRITY_DUP_CHECK_EVERY,
                 self.integrity_dup_check_every_steps, 0)):
            if val < lo:
                raise ValueError(
                    f"resilience.integrity.{label} must be >= {lo}, "
                    f"got {val}")


def get_resilience_config(param_dict):
    return DeepSpeedResilienceConfig(param_dict)


def get_pipeline_config(param_dict):
    d = param_dict.get(PIPELINE, {})
    schedule = str(d.get(PIPELINE_SCHEDULE, PIPELINE_SCHEDULE_DEFAULT)).lower()
    from deepspeed_tpu.runtime.pipe.schedule import KNOWN_SCHEDULES

    if schedule not in KNOWN_SCHEDULES:
        raise ValueError(
            f"pipeline.{PIPELINE_SCHEDULE} must be one of "
            f"{list(KNOWN_SCHEDULES)}, got {schedule!r}")
    virtual_stages = int(d.get(PIPELINE_VIRTUAL_STAGES,
                               PIPELINE_VIRTUAL_STAGES_DEFAULT))
    if virtual_stages < 1:
        raise ValueError(
            f"pipeline.{PIPELINE_VIRTUAL_STAGES} must be >= 1, "
            f"got {virtual_stages}")
    stashing = d.get(PIPELINE_STASH, PIPELINE_STASH_DEFAULT)
    if isinstance(stashing, str):
        stashing = stashing.lower()
    if stashing not in (True, False, "auto"):
        raise ValueError(
            f'pipeline.{PIPELINE_STASH} must be true, false or "auto", '
            f"got {stashing!r}")
    stash_budget = int(d.get(PIPELINE_STASH_BUDGET,
                             PIPELINE_STASH_BUDGET_DEFAULT))
    if stash_budget < 0:
        raise ValueError(
            f"pipeline.{PIPELINE_STASH_BUDGET} must be >= 0 bytes "
            f"(0 = unbounded), got {stash_budget}")
    return {
        PIPELINE_STAGES: d.get(PIPELINE_STAGES, PIPELINE_STAGES_DEFAULT),
        PIPELINE_PARTITION: d.get(PIPELINE_PARTITION, PIPELINE_PARTITION_DEFAULT),
        PIPELINE_SEED_LAYERS: d.get(PIPELINE_SEED_LAYERS, PIPELINE_SEED_LAYERS_DEFAULT),
        PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL: d.get(
            PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL,
            PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT),
        PIPELINE_SCHEDULE: schedule,
        PIPELINE_VIRTUAL_STAGES: virtual_stages,
        PIPELINE_STASH: stashing,
        PIPELINE_STASH_BUDGET: stash_budget,
    }


def get_telemetry_config(param_dict):
    """"telemetry" ds_config section: tracing + metrics stream + MFU.

    Everything defaults OFF (the master ``enabled`` switch) — telemetry
    is opt-in observability, and disarmed must cost exactly nothing on
    the step path."""
    d = param_dict.get(TELEMETRY, {})
    capacity = int(d.get(TELEMETRY_TRACE_CAPACITY,
                         TELEMETRY_TRACE_CAPACITY_DEFAULT))
    if capacity < 256:
        raise ValueError(
            f"telemetry.{TELEMETRY_TRACE_CAPACITY} must be >= 256 events "
            f"(got {capacity}); a smaller ring drops spans mid-step and "
            f"the trace replay refuses to run on a holey stream")
    peak = float(d.get(TELEMETRY_PEAK_TFLOPS,
                       TELEMETRY_PEAK_TFLOPS_DEFAULT))
    if peak < 0:
        raise ValueError(
            f"telemetry.{TELEMETRY_PEAK_TFLOPS} must be >= 0 TFLOPS "
            f"(0 = auto-detect from the device kind), got {peak}")
    return {
        TELEMETRY_ENABLED: bool(d.get(TELEMETRY_ENABLED,
                                      TELEMETRY_ENABLED_DEFAULT)),
        TELEMETRY_TRACE: bool(d.get(TELEMETRY_TRACE,
                                    TELEMETRY_TRACE_DEFAULT)),
        TELEMETRY_TRACE_CAPACITY: capacity,
        TELEMETRY_METRICS_JSONL: d.get(TELEMETRY_METRICS_JSONL,
                                       TELEMETRY_METRICS_JSONL_DEFAULT),
        TELEMETRY_METRICS_FSYNC: bool(d.get(TELEMETRY_METRICS_FSYNC,
                                            TELEMETRY_METRICS_FSYNC_DEFAULT)),
        TELEMETRY_MFU: bool(d.get(TELEMETRY_MFU, TELEMETRY_MFU_DEFAULT)),
        TELEMETRY_MEMORY: bool(d.get(TELEMETRY_MEMORY,
                                     TELEMETRY_MEMORY_DEFAULT)),
        TELEMETRY_PEAK_TFLOPS: peak,
    }


class DeepSpeedConfig:
    def __init__(self, json_file_or_dict, mpu=None, param_dict=None, world_size=None):
        if param_dict is None:
            if isinstance(json_file_or_dict, dict):
                self._param_dict = json_file_or_dict
            else:
                with open(json_file_or_dict, "r") as f:
                    self._param_dict = json.load(
                        f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        else:
            self._param_dict = param_dict

        if world_size is not None:
            self.world_size = world_size
        elif mpu is None:
            self.world_size = int(os.environ.get("WORLD_SIZE", "1"))
        else:
            self.world_size = mpu.get_data_parallel_world_size()

        self.elasticity_enabled = elasticity_enabled(self._param_dict)
        if self.elasticity_enabled:
            final_batch_size, valid_gpus, micro_batch_size = compute_elastic_config(
                ds_config=self._param_dict,
                target_deepspeed_version=__version__,
                world_size=self.world_size)
            elastic_dict = self._param_dict["elasticity"]
            ensure_immutable_elastic_config(elastic_dict)
            ignore_non_elastic = elastic_dict.get(IGNORE_NON_ELASTIC_BATCH_INFO,
                                                  IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
            if not ignore_non_elastic:
                batch_params = [TRAIN_BATCH_SIZE, TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                                GRADIENT_ACCUMULATION_STEPS]
                if any(p in self._param_dict for p in batch_params):
                    raise ElasticityConfigError(
                        "One or more batch-related parameters were found in your "
                        f"ds_config ({batch_params}). These parameters *cannot* be "
                        "used with elasticity; they are computed from the elastic "
                        f"config. Set {IGNORE_NON_ELASTIC_BATCH_INFO}:true to "
                        "suppress this error")
            gas = final_batch_size // (micro_batch_size * self.world_size)
            self._param_dict[TRAIN_BATCH_SIZE] = final_batch_size
            self._param_dict[TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch_size
            self._param_dict[GRADIENT_ACCUMULATION_STEPS] = gas
            logger.info(
                f"Elasticity: final batch size {final_batch_size}, "
                f"micro batch {micro_batch_size}, gas {gas}, valid world sizes {valid_gpus}")

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    def _initialize_params(self, param_dict):
        self.train_batch_size = get_train_batch_size(param_dict)
        self.train_micro_batch_size_per_gpu = get_train_micro_batch_size_per_gpu(param_dict)
        self.gradient_accumulation_steps = get_gradient_accumulation_steps(param_dict)
        self.steps_per_print = get_steps_per_print(param_dict)
        self.dump_state = get_dump_state(param_dict)

        self.disable_allgather = get_disable_allgather(param_dict)
        self.allreduce_always_fp32 = get_allreduce_always_fp32(param_dict)
        self.prescale_gradients = get_prescale_gradients(param_dict)
        self.gradient_predivide_factor = get_gradient_predivide_factor(param_dict)
        self.sparse_gradients_enabled = get_sparse_gradients_enabled(param_dict)

        self.zero_config = DeepSpeedZeroConfig(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = \
            DeepSpeedActivationCheckpointingConfig(param_dict)
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(param_dict)

        self.fp16_enabled = get_fp16_enabled(param_dict)
        self.bf16_enabled = get_bf16_enabled(param_dict)
        self.amp_enabled = get_amp_enabled(param_dict)
        self.amp_params = get_amp_params(param_dict)
        self.loss_scale = get_loss_scale(param_dict)
        self.initial_dynamic_scale = get_initial_dynamic_scale(param_dict)
        self.dynamic_loss_scale_args = get_dynamic_loss_scale_args(param_dict)

        self.optimizer_name = get_optimizer_name(param_dict)
        if self.optimizer_name is not None and \
                self.optimizer_name.lower() in DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = get_optimizer_params(param_dict)
        self.optimizer_legacy_fusion = get_optimizer_legacy_fusion(param_dict)

        self.zero_allow_untested_optimizer = get_allow_untested_optimizer(param_dict)

        self.scheduler_name = get_scheduler_name(param_dict)
        self.scheduler_params = get_scheduler_params(param_dict)

        self.wall_clock_breakdown = get_wall_clock_breakdown(param_dict)
        self.memory_breakdown = get_memory_breakdown(param_dict)
        self.tensorboard_enabled = get_tensorboard_enabled(param_dict)
        self.tensorboard_output_path = get_tensorboard_output_path(param_dict)
        self.tensorboard_job_name = get_tensorboard_job_name(param_dict)

        self.gradient_clipping = get_gradient_clipping(param_dict)
        self.sparse_attention = get_sparse_attention(param_dict)
        self.checkpoint_tag_validation_mode = \
            get_checkpoint_tag_validation_mode(get_checkpoint_params(param_dict))

        self.pld_enabled, self.pld_theta, self.pld_gamma = \
            get_progressive_layer_drop(param_dict)

        self.mesh_shape = get_mesh_shape(param_dict)
        self.pipeline = get_pipeline_config(param_dict)
        self.resilience = get_resilience_config(param_dict)
        self.telemetry = get_telemetry_config(param_dict)

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self):
        """The 6-case triangulation (reference: config.py:675)."""
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        # all three provided
        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            return
        # global + micro -> derive gas
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        # global + gas -> derive micro
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        # micro + gas -> derive global
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        # global only
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        # micro only
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs "
                "to be provided")

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()

    def _do_error_check(self):
        assert self.train_micro_batch_size_per_gpu, \
            f"DeepSpeedConfig: {TRAIN_MICRO_BATCH_SIZE_PER_GPU} is not defined"
        assert self.gradient_accumulation_steps, \
            f"DeepSpeedConfig: {GRADIENT_ACCUMULATION_STEPS} is not defined"
        if self.zero_enabled:
            from deepspeed_tpu.runtime.zero.constants import \
                MAX_STAGE_ZERO_OPTIMIZATION

            assert self.zero_optimization_stage <= MAX_STAGE_ZERO_OPTIMIZATION, \
                (f"DeepSpeedConfig: Max supported ZeRO stage is "
                 f"{MAX_STAGE_ZERO_OPTIMIZATION} (3 = param sharding, an "
                 f"extension beyond the reference snapshot's cap of 2)")

    def _do_warning_check(self):
        fp16_enabled = self.fp16_enabled or self.zero_enabled
        vocabulary_size = self._param_dict.get("vocabulary_size", None)
        if vocabulary_size and vocabulary_size % TENSOR_CORE_ALIGN_SIZE != 0:
            logger.warning(
                f"DeepSpeedConfig: vocabulary size {vocabulary_size} is not aligned "
                f"to {TENSOR_CORE_ALIGN_SIZE}; may be suboptimal for MXU tiling")
        if self.optimizer_params is not None and \
                MAX_GRAD_NORM in self.optimizer_params and \
                self.optimizer_params[MAX_GRAD_NORM] > 0:
            if fp16_enabled:
                logger.warning(
                    f"DeepSpeedConfig: In FP16 mode, DeepSpeed will pass "
                    f"{MAX_GRAD_NORM}:{self.optimizer_params[MAX_GRAD_NORM]} to the "
                    f"fp16 wrapper; set gradient_clipping instead")

    def print(self, name):
        logger.info(f"{name}:")
        for key, value in sorted(self.__dict__.items()):
            if key != "_param_dict":
                logger.info(f"  {key} {value}")
        logger.info(f"  json = {json.dumps(self._param_dict, sort_keys=True, indent=2)}")
