"""LR schedules with the reference's config surface.

Reference: deepspeed/runtime/lr_schedules.py (LRRangeTest :301, OneCycle :408,
WarmupLR :677, WarmupDecayLR :761, add_tuning_arguments :54).

In the TPU build a scheduler is a host-side object the engine queries each
optimizer step; the value is fed into the jitted update as a scalar argument
(so no recompilation per step).  Each scheduler also exposes ``lr_at(step)``
— a pure function usable inside jit for fully-fused schedules.
"""
import argparse
import math

from deepspeed_tpu.utils.logging import logger

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

EDGE_VALUE = "edge_value"
MID_VALUE = "mid_value"

CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"

CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


def add_tuning_arguments(parser):
    group = parser.add_argument_group("Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    # LR range test
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    # OneCycle
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    # Warmup
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    return parser


def parse_arguments():
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    lr_sched_args, unknown_args = parser.parse_known_args()
    return lr_sched_args, unknown_args


def override_lr_range_test_params(args, params):
    if hasattr(args, LR_RANGE_TEST_MIN_LR) and args.lr_range_test_min_lr is not None:
        params[LR_RANGE_TEST_MIN_LR] = args.lr_range_test_min_lr
    if hasattr(args, LR_RANGE_TEST_STEP_RATE) and args.lr_range_test_step_rate is not None:
        params[LR_RANGE_TEST_STEP_RATE] = args.lr_range_test_step_rate
    if hasattr(args, LR_RANGE_TEST_STEP_SIZE) and args.lr_range_test_step_size is not None:
        params[LR_RANGE_TEST_STEP_SIZE] = args.lr_range_test_step_size
    if hasattr(args, LR_RANGE_TEST_STAIRCASE) and args.lr_range_test_staircase is not None:
        params[LR_RANGE_TEST_STAIRCASE] = args.lr_range_test_staircase


def override_1cycle_params(args, params):
    for key in [CYCLE_FIRST_STEP_SIZE, CYCLE_FIRST_STAIR_COUNT, CYCLE_SECOND_STEP_SIZE,
                CYCLE_SECOND_STAIR_COUNT, DECAY_STEP_SIZE, CYCLE_MIN_LR, CYCLE_MAX_LR,
                DECAY_LR_RATE, CYCLE_MIN_MOM, CYCLE_MAX_MOM, DECAY_MOM_RATE]:
        if hasattr(args, key) and getattr(args, key) is not None:
            params[key] = getattr(args, key)


def override_warmupLR_params(args, params):
    for key in [WARMUP_MIN_LR, WARMUP_MAX_LR, WARMUP_NUM_STEPS]:
        if hasattr(args, key) and getattr(args, key) is not None:
            params[key] = getattr(args, key)


def override_params(args, params):
    override_lr_range_test_params(args, params)
    override_1cycle_params(args, params)
    override_warmupLR_params(args, params)


def get_config_from_args(args):
    if not hasattr(args, LR_SCHEDULE) or args.lr_schedule is None:
        return None, "--{} not specified on command line".format(LR_SCHEDULE)
    if args.lr_schedule not in VALID_LR_SCHEDULES:
        return None, "{} is not supported LR schedule".format(args.lr_schedule)
    config = {"type": args.lr_schedule, "params": {}}
    if args.lr_schedule == LR_RANGE_TEST:
        override_lr_range_test_params(args, config["params"])
    elif args.lr_schedule == ONE_CYCLE:
        override_1cycle_params(args, config["params"])
    else:
        override_warmupLR_params(args, config["params"])
    return config, None


class _LRSchedulerBase:
    """Host-side scheduler.  Also usable as pure fn via lr_at(step)."""

    def __init__(self, optimizer=None, last_batch_iteration=-1):
        self.optimizer = optimizer  # engine object or None; kept for API parity
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        raise NotImplementedError

    def get_lr(self):
        if self.last_batch_iteration < 0:
            logger.warning("Attempting to get learning rate from scheduler before it has started")
            return [0.0]
        return [self.lr_at(self.last_batch_iteration)]

    def get_last_lr(self):
        assert getattr(self, "_last_lr", None) is not None, "need to call step() first"
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        lr = self.lr_at(self.last_batch_iteration)
        self._last_lr = [lr]
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(lr)
        return lr

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_LRSchedulerBase):
    """LR range test (Smith): lr = min_lr * (1 + step/size * rate), optionally staircase.

    Reference: lr_schedules.py:301-405.
    """

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        if lr_range_test_min_lr <= 0:
            raise ValueError(f"invalid min_lr {lr_range_test_min_lr}")
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        step = max(0, step)
        if self.staircase:
            interval = float(step // self.step_size)
        else:
            interval = float(step) / self.step_size
        return self.min_lr * (1 + self.step_rate * interval)


class OneCycle(_LRSchedulerBase):
    """1-cycle policy: linear up over first phase, linear down over second,
    then (optional) decay.  Momentum cycles inversely.

    Reference: lr_schedules.py:408-674.
    """

    def __init__(self, optimizer=None, cycle_min_lr=1e-3, cycle_max_lr=1e-2,
                 decay_lr_rate=0., cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 cycle_momentum=True, cycle_min_mom=0.8, cycle_max_mom=0.9,
                 decay_mom_rate=0., last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_step_size = cycle_first_step_size
        self.second_step_size = cycle_second_step_size \
            if cycle_second_step_size is not None else cycle_first_step_size
        self.first_stair_count = cycle_first_stair_count
        self.second_stair_count = cycle_second_stair_count \
            if cycle_second_stair_count is not None else cycle_first_stair_count
        self.decay_step_size = decay_step_size
        self.total_cycle_size = self.first_step_size + self.second_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def _staircase_interval(self, step_size, stair_count, progress):
        if stair_count in (0, -1) or stair_count is None:
            return progress / step_size
        stair_size = step_size / stair_count
        return math.floor(progress / stair_size) * stair_size / step_size

    def lr_at(self, step):
        step = max(0, step)
        if step < self.total_cycle_size:
            if step < self.first_step_size:  # ramp up
                frac = self._staircase_interval(self.first_step_size,
                                                self.first_stair_count, step)
                return self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * min(1.0, frac)
            # ramp down
            progress = step - self.first_step_size
            frac = self._staircase_interval(self.second_step_size,
                                            self.second_stair_count, progress)
            return self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * min(1.0, frac)
        # decay phase
        if self.decay_step_size > 0:
            decay_steps = (step - self.total_cycle_size) // self.decay_step_size
        else:
            decay_steps = step - self.total_cycle_size
        return self.cycle_min_lr / (1.0 + decay_steps * self.decay_lr_rate) \
            if self.decay_lr_rate > 0 else self.cycle_min_lr

    def mom_at(self, step):
        if not self.cycle_momentum:
            return self.cycle_max_mom
        step = max(0, step)
        if step < self.total_cycle_size:
            if step < self.first_step_size:  # momentum goes down while lr goes up
                frac = float(step) / self.first_step_size
                return self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * min(1.0, frac)
            progress = step - self.first_step_size
            frac = float(progress) / self.second_step_size
            return self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * min(1.0, frac)
        if self.decay_step_size > 0:
            decay_steps = (step - self.total_cycle_size) // self.decay_step_size
        else:
            decay_steps = step - self.total_cycle_size
        return self.cycle_max_mom * (1.0 + decay_steps * self.decay_mom_rate) \
            if self.decay_mom_rate > 0 else self.cycle_max_mom

    def get_mom(self):
        return [self.mom_at(max(0, self.last_batch_iteration))]


class WarmupLR(_LRSchedulerBase):
    """Linear warmup from min_lr to max_lr over warmup_num_steps, then constant.

    Reference: lr_schedules.py:677-758.
    """

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = warmup_num_steps
        self.inverse_log_warm_up = 1.0 / math.log(max(2, warmup_num_steps))

    def _get_gamma(self, step):
        if step < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(step + 1)
        return 1.0

    def lr_at(self, step):
        step = max(0, step)
        gamma = self._get_gamma(step)
        return self.min_lr + (self.max_lr - self.min_lr) * gamma


class WarmupDecayLR(WarmupLR):
    """WarmupLR followed by linear decay to 0 at total_num_steps.

    Reference: lr_schedules.py:761-809.
    """

    def __init__(self, optimizer=None, total_num_steps=1000, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000, last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning(f"total_num_steps {total_num_steps} is less than "
                           f"warmup_num_steps {warmup_num_steps}")

    def _get_gamma(self, step):
        if step < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(step + 1)
        return max(0.0, float(self.total_num_steps - step) /
                   float(max(1.0, self.total_num_steps - self.warmup_num_steps)))


SCHEDULER_REGISTRY = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}
