"""Resilient-training runtime: atomic checkpoints, auto-resume, watchdog.

Long multi-host runs die for boring reasons — a preempted TPU-VM killed
mid-`np.savez`, a flaky NFS write, a loss-scale death spiral, a hung
collective.  This package makes those survivable:

- ``atomic``: write-to-temp + manifest (per-file size/checksum) + fsync +
  atomic rename, ``latest`` pointer updated last, retention GC.
- ``watchdog``: consecutive-overflow / NaN-loss / wall-clock-stall
  detection with callbacks that can abort cleanly or back off.
- ``chaos``: fault-injection hooks (kill mid-write, corrupt a leaf,
  poison grads) used by tests/unit/test_resilience.py to prove recovery.
- ``coordination``: the multi-host agree/broadcast discipline the engine
  save/load paths share (fail together, never wedge peers in a barrier).
- ``reshard``: topology-elastic resume — every checkpoint carries a
  topology manifest + exact data position, and
  ``load_checkpoint(elastic=True)`` reshards it onto ANY mesh (new zero
  axis, remapped pipeline chunks, schedule downgrades DISARM-warned),
  with ``compute_elastic_config`` preserving the global batch.
- ``supervisor``: the self-healing loop that wires the above together —
  step-clock heartbeat failure detection, coordinated dead verdicts,
  and a bounded retry / rollback / elastic-restart ladder with MTTR
  and goodput accounting.
- ``integrity``: the silent-corruption defense — device-side step
  sentinels (EMA/z-score), a cross-replica checksum vote that convicts
  the corrupted rank by minority, a duplicate-compute sentinel
  micro-step, and the ``corrupt`` verdict the supervisor answers with
  rollback-and-skip / rank quarantine.
"""
from deepspeed_tpu.runtime.resilience.atomic import (MANIFEST_NAME,
                                                     CheckpointCorrupt,
                                                     atomic_tag, gc_tags,
                                                     is_emergency_tag,
                                                     is_preempt_tag,
                                                     is_suspect_tag,
                                                     list_tags, load_manifest,
                                                     read_latest,
                                                     read_topology,
                                                     resume_candidates,
                                                     select_resume_tag,
                                                     verify_tag, write_latest,
                                                     write_manifest)
from deepspeed_tpu.runtime.resilience.integrity import (IntegrityConfig,
                                                        IntegrityMonitor,
                                                        classify_digests)
from deepspeed_tpu.runtime.resilience.supervisor import (SupervisorConfig,
                                                         SupervisorGaveUp,
                                                         TrainingSupervisor,
                                                         TransientStepFault)
from deepspeed_tpu.runtime.resilience.watchdog import (GracefulPreemption,
                                                       TrainingWatchdog,
                                                       WatchdogAlarm,
                                                       WatchdogEvent,
                                                       chain_signal_handlers)

__all__ = [
    "MANIFEST_NAME", "CheckpointCorrupt", "atomic_tag", "gc_tags",
    "is_emergency_tag", "is_preempt_tag", "is_suspect_tag", "list_tags",
    "load_manifest", "read_latest", "read_topology", "resume_candidates",
    "select_resume_tag", "verify_tag", "write_latest", "write_manifest",
    "GracefulPreemption", "TrainingWatchdog", "WatchdogAlarm",
    "WatchdogEvent", "chain_signal_handlers",
    "SupervisorConfig", "SupervisorGaveUp", "TrainingSupervisor",
    "TransientStepFault",
    "IntegrityConfig", "IntegrityMonitor", "classify_digests",
]
