"""Atomic, verified checkpoint commit + auto-resume tag selection.

Layout on disk (per save_dir)::

    save_dir/
      latest                  <- plain-text tag name, updated ATOMICALLY last
      global_step10/
        manifest.json         <- per-file {bytes, sha256} + step/world meta
        model_states.npz      <- engine payload (any files, any names)
        metadata.pkl
      .tmp-global_step20/     <- in-flight write; never trusted by loads

Commit protocol (crash-safe at every point):

1. all payload files are written into ``.tmp-<tag>``;
2. ``manifest.json`` (sizes + sha256 of every payload file) is written and
   fsync'd;
3. every payload file is fsync'd, then the temp dir itself;
4. ``os.replace(.tmp-<tag>, <tag>)`` — the one atomic step;
5. the ``latest`` pointer is rewritten via write-temp + fsync + rename.

A crash before (4) leaves only a ``.tmp-`` dir (ignored, GC'd later); a
crash between (4) and (5) leaves a valid tag that auto-resume still finds
by scanning.  ``verify_tag`` replays the manifest against the files, so
truncated or bit-rotten payloads are detected before they're loaded.
"""
import hashlib
import io
import json
import os
import shutil
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from deepspeed_tpu.runtime.resilience import chaos
from deepspeed_tpu.utils.logging import logger

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1
LATEST_NAME = "latest"
TMP_PREFIX = ".tmp-"
# files above this are hashed as independent chunks in a thread pool
# (hashlib releases the GIL, so the manifest pass scales with host cores
# instead of being pinned at single-core sha256 throughput); the manifest
# records the chunk size so verification replays identically
CHUNK_BYTES = 1 << 26
# below this total payload the pool costs more in thread scheduling than
# the hashing it parallelizes (~20 ms/save measured on a loaded 2-core
# host vs ~3 ms of serial sha256) — hash serially
PARALLEL_MIN_BYTES = 32 << 20


class CheckpointCorrupt(RuntimeError):
    """A tag failed manifest verification."""


def _fsync_path(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def file_checksum(path, algo="sha256", chunk=1 << 20):
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _hash_range(path, offset, nbytes, algo="sha256"):
    """Digest of one byte range of ``path`` (a chunk job)."""
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        f.seek(offset)
        remaining = nbytes
        while remaining > 0:
            block = f.read(min(1 << 20, remaining))
            if not block:
                break
            h.update(block)
            remaining -= len(block)
    return h.digest()


def chunked_checksum(path, size=None, chunk_bytes=CHUNK_BYTES,
                     algo="sha256", pool=None):
    """sha256 over the concatenated digests of ``chunk_bytes``-sized
    chunks (S3-multipart style).  With a pool, chunks hash in parallel."""
    if size is None:
        size = os.path.getsize(path)
    offsets = list(range(0, size, chunk_bytes)) or [0]
    jobs = [(off, min(chunk_bytes, size - off)) for off in offsets]
    if len(jobs) > 1:
        if pool is not None:
            digests = list(pool.map(
                lambda j: _hash_range(path, j[0], j[1], algo), jobs))
        else:
            workers = min(len(jobs), max(2, os.cpu_count() or 1))
            with ThreadPoolExecutor(workers) as own:
                digests = list(own.map(
                    lambda j: _hash_range(path, j[0], j[1], algo), jobs))
    else:
        digests = [_hash_range(path, off, n, algo) for off, n in jobs]
    outer = hashlib.new(algo)
    for d in digests:
        outer.update(d)
    return outer.hexdigest()


def _checksum_records(triples):
    """{rel: {bytes, sha256[, chunk_bytes]}} for (rel, full, size) triples.

    All chunk jobs from all files share one thread pool, so many small
    files (pipeline per-layer checkpoints) and few huge files (fused
    model_states) both parallelize."""
    out = {}
    rest = []
    for rel, full, size in triples:
        pre = _take_precomputed(full, size)
        if pre is not None:  # hashed while being written (savez_hashed)
            # chunk_bytes recorded so verify-on-load replays the digest
            # chunk-parallel instead of serially re-hashing the payload
            out[rel] = {"bytes": size, "chunk_bytes": CHUNK_BYTES,
                        "sha256": pre}
        else:
            rest.append((rel, full, size))
    triples = rest
    small = [(rel, full, size) for rel, full, size in triples
             if size <= CHUNK_BYTES]
    big = [(rel, full, size) for rel, full, size in triples
           if size > CHUNK_BYTES]
    workers = max(2, os.cpu_count() or 1)
    njobs = len(small) + sum(-(-size // CHUNK_BYTES) for _, _, size in big)
    total = sum(size for _, _, size in triples)
    if njobs > 1 and (big or total >= PARALLEL_MIN_BYTES):
        with ThreadPoolExecutor(min(workers, njobs)) as pool:
            small_digs = pool.map(
                lambda t: _hash_range(t[1], 0, t[2]), small)
            for rel, full, size in big:
                out[rel] = {"bytes": size, "chunk_bytes": CHUNK_BYTES,
                            "sha256": chunked_checksum(full, size,
                                                       pool=pool)}
            for (rel, full, size), dig in zip(small, small_digs):
                out[rel] = {"bytes": size, "sha256": dig.hex()}
    else:
        for rel, full, size in triples:
            out[rel] = {"bytes": size, "sha256": file_checksum(full)}
    return out


# digests computed on-the-fly during payload writes, consumed (and
# validated against the on-disk size) by the next write_manifest over the
# same file — saves a full re-read + serial hash pass at commit time
_precomputed = {}
_precomputed_lock = threading.Lock()


class _TeeHashWriter:
    """Write-only file that hashes everything written, in a background
    thread so hashing overlaps the (CPU-bound) serialization.  Declares
    itself unseekable so zipfile streams with data descriptors instead of
    seeking back to patch headers — the digest covers the final on-disk
    bytes, byte-for-byte.

    The digest uses the same CHUNK_BYTES chunked scheme as
    :func:`chunked_checksum` (S3-multipart style), so verify-on-load can
    replay it chunk-parallel across host cores instead of being pinned to
    single-core sha256 on multi-GB payloads."""

    # bound on bytes parked in the hasher queue (backpressure so a slow
    # hasher can't balloon RSS by the whole checkpoint)
    _MAX_QUEUED = 64 << 20

    def __init__(self, path):
        self.path = path
        self.f = open(path, "wb")
        self._chunk_hash = hashlib.sha256()
        self._chunk_fill = 0
        self._chunk_digests = []
        self.nbytes = 0
        self._q = deque()
        self._queued = 0
        self._cv = threading.Condition()
        self._done = False
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _hash_update(self, buf):
        """Feed the rolling chunk hasher, closing chunks at CHUNK_BYTES
        boundaries exactly as chunked_checksum's replay slices them."""
        view = memoryview(buf)
        while view:
            take = min(CHUNK_BYTES - self._chunk_fill, len(view))
            self._chunk_hash.update(view[:take])  # releases the GIL
            self._chunk_fill += take
            view = view[take:]
            if self._chunk_fill == CHUNK_BYTES:
                self._chunk_digests.append(self._chunk_hash.digest())
                self._chunk_hash = hashlib.sha256()
                self._chunk_fill = 0

    def _drain(self):
        while True:
            with self._cv:
                while not self._q and not self._done:
                    self._cv.wait()
                if not self._q and self._done:
                    return
                buf = self._q.popleft()
                self._queued -= len(buf)
                self._cv.notify_all()
            self._hash_update(buf)

    # numpy's zipfile_factory treats anything with .read as a file object
    def read(self, *_a):
        raise io.UnsupportedOperation("write-only stream")

    def seekable(self):
        return False

    def write(self, b):
        # the zip stream hands us whole serialized chunks (MBs); enqueue
        # the object itself — bytes are immutable, so no defensive copy,
        # and this path stays memory-bandwidth-neutral
        data = b if isinstance(b, bytes) else bytes(b)
        with self._cv:
            while self._queued >= self._MAX_QUEUED:
                self._cv.wait()
            self._q.append(data)
            self._queued += len(data)
            self._cv.notify_all()
        self.nbytes += len(data)
        return self.f.write(data)

    def flush(self):
        self.f.flush()

    def close(self):
        if self._closed:
            return
        self._closed = True
        with self._cv:
            self._done = True
            self._cv.notify_all()
        self._thread.join()
        self.f.close()
        digests = list(self._chunk_digests)
        if self._chunk_fill or not digests:  # trailing partial / empty file
            digests.append(self._chunk_hash.digest())
        outer = hashlib.sha256()
        for d in digests:
            outer.update(d)
        with _precomputed_lock:
            _precomputed[os.path.realpath(self.path)] = (
                self.nbytes, outer.hexdigest())


def savez_hashed(path, **arrays):
    """np.savez into ``path`` with the sha256 of the on-disk bytes computed
    concurrently with the write and stashed for the next manifest pass.
    Falls back to a plain np.savez (manifest re-reads the file) if this
    numpy can't write a zip to an unseekable stream."""
    import numpy as np

    w = _TeeHashWriter(path)
    ok = False
    fallback = False
    try:
        np.savez(w, **arrays)
        ok = True
    except (TypeError, AttributeError, io.UnsupportedOperation) as e:
        # capability errors only (numpy/zipfile rejecting the unseekable
        # stream) — real I/O errors (ENOSPC, EIO) propagate untouched,
        # with the finally ensuring the fd + hasher thread still shut down
        logger.warning(f"streaming-hash savez unavailable ({e}); "
                       f"falling back to plain np.savez")
        fallback = True
    finally:
        w.close()
        if not ok:  # a partial write's digest must never reach a manifest
            with _precomputed_lock:
                _precomputed.pop(os.path.realpath(path), None)
    if fallback:
        np.savez(path, **arrays)


def _take_precomputed(full, size):
    with _precomputed_lock:
        got = _precomputed.pop(os.path.realpath(full), None)
    if got is not None and got[0] == size:
        return got[1]
    return None


def _walk_payload(dirpath):
    """All files under dirpath except the manifest, as relative paths."""
    out = []
    for root, _dirs, names in os.walk(dirpath):
        for name in names:
            rel = os.path.relpath(os.path.join(root, name), dirpath)
            if rel != MANIFEST_NAME:
                out.append(rel)
    return sorted(out)


def write_manifest(dirpath, meta=None, fsync=True):
    """Scan dirpath's files and write manifest.json (sizes + sha256)."""
    triples = []
    for rel in _walk_payload(dirpath):
        full = os.path.join(dirpath, rel)
        triples.append((rel, full, os.path.getsize(full)))
    files = _checksum_records(triples)
    manifest = {"version": MANIFEST_VERSION, "files": files}
    manifest.update(meta or {})
    mpath = os.path.join(dirpath, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    return manifest


def load_manifest(tag_dir):
    mpath = os.path.join(tag_dir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        return None
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        logger.warning(f"unreadable manifest at {mpath}: {e}")
        return None


def verify_tag(tag_dir, check_checksums=True):
    """Replay the manifest against the files; (ok, reason).

    A tag without a manifest (pre-resilience layout) verifies as ok with a
    warning — old checkpoints stay loadable, they just aren't protected.
    """
    if not os.path.isdir(tag_dir):
        return False, "missing directory"
    manifest = load_manifest(tag_dir)
    if manifest is None:
        if os.path.isfile(os.path.join(tag_dir, MANIFEST_NAME)):
            return False, "corrupt manifest"
        logger.warning(f"{tag_dir}: no manifest (pre-resilience checkpoint); "
                       f"integrity not verifiable")
        return True, "no manifest"
    files = manifest.get("files", {})
    for rel, want in files.items():
        full = os.path.join(tag_dir, rel)
        if not os.path.isfile(full):
            return False, f"missing file {rel}"
        size = os.path.getsize(full)
        if size != want.get("bytes"):
            return False, (f"size mismatch on {rel}: "
                           f"{size} != {want.get('bytes')}")
        if check_checksums:
            cb = want.get("chunk_bytes")
            if cb:
                digest = chunked_checksum(full, size, chunk_bytes=cb)
            else:
                digest = file_checksum(full)
            if digest != want.get("sha256"):
                return False, f"checksum mismatch on {rel}"
    extra = set(_walk_payload(tag_dir)) - set(files)
    if extra:
        # extra files are suspicious but not fatal (e.g. editor droppings);
        # the manifested payload is intact
        logger.warning(f"{tag_dir}: unmanifested files present: "
                       f"{sorted(extra)[:4]}")
    return True, "ok"


class atomic_tag:
    """Context manager for one atomic tag write.

    with atomic_tag(save_dir, tag, meta={"global_steps": n}) as tmp:
        ... write payload files into tmp ...
    # on clean exit the tag is committed + fsync'd and (optionally) the
    # 'latest' pointer updated; on exception the temp dir is removed and
    # save_dir is untouched.
    """

    def __init__(self, save_dir, tag, meta=None, update_latest=True,
                 fsync=True):
        self.save_dir = save_dir
        self.tag = str(tag)
        if "/" in self.tag or os.sep in self.tag or self.tag in ("", ".",
                                                                 ".."):
            raise ValueError(
                f"checkpoint tag {self.tag!r} must be a single path "
                f"component — the atomic layout (tag dirs + 'latest' "
                f"pointer + resume scan) is flat; encode hierarchy in the "
                f"save directory instead, or set "
                f"resilience.atomic_checkpoints=false for the legacy "
                f"nested layout")
        self.meta = dict(meta or {})
        self.update_latest = update_latest
        self.fsync = fsync
        self.tmp = os.path.join(save_dir, f"{TMP_PREFIX}{self.tag}")
        self.final = os.path.join(save_dir, self.tag)

    def __enter__(self):
        os.makedirs(self.save_dir, exist_ok=True)
        if os.path.isdir(self.tmp):  # stale tmp from a previous crash
            shutil.rmtree(self.tmp)
        os.makedirs(self.tmp)
        return self.tmp

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            shutil.rmtree(self.tmp, ignore_errors=True)
            return False
        try:
            self._commit()
        except BaseException:
            shutil.rmtree(self.tmp, ignore_errors=True)
            raise
        return False

    def _commit(self):
        self._seal()
        self._publish()

    def _seal(self, progress_cb=None):
        """Durability phase: manifest + fsync of every payload file and the
        temp dir itself.  This is the payload-size-dependent part of the
        commit — the only part an async commit moves off the training
        thread.  ``progress_cb`` (if given) is called after each fsync'd
        file so a slow disk keeps signaling liveness."""
        self.meta.setdefault("tag", self.tag)
        chaos.point("before_manifest")
        write_manifest(self.tmp, self.meta, fsync=self.fsync)
        if progress_cb is not None:
            progress_cb()
        if self.fsync:
            for rel in _walk_payload(self.tmp):
                _fsync_path(os.path.join(self.tmp, rel))
                if progress_cb is not None:
                    progress_cb()
            _fsync_path(self.tmp)

    def _publish(self):
        """Visibility phase: the atomic rename (+ latest-pointer-last).
        O(1) in payload size — the only piece of an async commit that runs
        on the training thread."""
        chaos.point("before_rename")
        if os.path.isdir(self.final):
            # tag overwrite needs two renames (os.replace can't swap
            # non-empty dirs).  The old copy is parked under a name the
            # resume scan still treats as a committed tag, so a crash
            # between the renames never leaves zero copies of this tag —
            # auto-resume falls back to '<tag>.replaced'
            doomed = os.path.join(self.save_dir, f"{self.tag}.replaced")
            if os.path.isdir(doomed):
                shutil.rmtree(doomed)
            os.replace(self.final, doomed)
            try:
                chaos.point("between_swap")
                os.replace(self.tmp, self.final)
            except BaseException:
                os.replace(doomed, self.final)  # soft failure: restore old
                raise
            shutil.rmtree(doomed, ignore_errors=True)
        else:
            os.replace(self.tmp, self.final)
        if self.fsync:
            _fsync_path(self.save_dir)
        chaos.point("before_latest")
        if self.update_latest:
            write_latest(self.save_dir, self.tag, fsync=self.fsync)


class PendingCommit:
    """One in-flight ASYNC checkpoint commit.

    Split of responsibilities (the async analog of ``atomic_tag``):

    - background thread (``start``): temp-dir setup, ``write_fn(tmp)``
      (the engine's payload writer over an already-host-resident
      snapshot), manifest + streaming-hash bookkeeping, fsync of every
      file — ALL the payload-size-dependent work;
    - foreground (``finalize``, called from the training thread once
      ``ready()``): the atomic rename + latest-pointer-last, O(1) in
      payload size.

    Crash-safety is inherited from the atomic layout: until ``finalize``
    runs, only a ``.tmp-`` dir exists (ignored by loads, GC'd later), so
    a kill at ANY point — mid-write, mid-fsync, before or during the
    rename — never yields a torn tag or a ``latest`` pointer at
    unverified bytes.  A background failure (including an armed chaos
    kill) is re-raised by ``finalize``/``wait`` on the calling thread
    after removing the temp dir.

    ``heartbeat`` (optional callable) is invoked by the background thread
    after each written/fsync'd file so a slow disk keeps feeding the
    TrainingWatchdog instead of being misdiagnosed as a training stall.
    """

    def __init__(self, commit, write_fn, heartbeat=None):
        assert isinstance(commit, atomic_tag)
        self.commit = commit
        self.write_fn = write_fn
        self.heartbeat = heartbeat
        self.error = None
        self.finalized = False
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"ckpt-commit-{commit.tag}", daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _beat(self):
        if self.heartbeat is not None:
            self.heartbeat()

    def _run(self):
        try:
            self._beat()
            self.commit.__enter__()
            self.write_fn(self.commit.tmp)
            self._beat()
            self.commit._seal(progress_cb=self._beat)
            self._beat()
        except BaseException as e:  # noqa: B036 - surfaced via finalize()
            self.error = e
            shutil.rmtree(self.commit.tmp, ignore_errors=True)
        finally:
            self._done.set()

    def ready(self):
        """True once the background durability work has finished (well or
        badly) — i.e. ``finalize`` will not block."""
        return self._done.is_set()

    def wait(self, timeout=None):
        """Block until the background work finishes; True if it did."""
        return self._done.wait(timeout)

    def finalize(self):
        """Publish the sealed tag: atomic rename + latest-pointer-last.

        Runs on the CALLING (training) thread; blocks until the
        background seal completes if it has not already.  Re-raises any
        background error (the temp dir is already cleaned up), and cleans
        up + re-raises on a publish-side failure, so save_dir is either
        'previous checkpoint intact' or 'new tag fully committed'."""
        self._done.wait()
        if self.finalized:
            return
        if self.error is not None:
            raise self.error  # repeat finalize calls keep raising
        try:
            self.commit._publish()
        except BaseException:
            shutil.rmtree(self.commit.tmp, ignore_errors=True)
            raise
        finally:
            self.finalized = True


class FollowerCommit:
    """Placeholder pending commit for non-leader ranks of a multi-host
    async save: npz-family backends write payload on process 0 only, and
    only process 0 publishes — followers hold this so every rank runs
    the same finalize choreography (the all_agree phases) in lockstep."""

    error = None
    finalized = False

    def start(self):
        return self

    def ready(self):
        return True

    def wait(self, timeout=None):
        return True

    def finalize(self):
        self.finalized = True


def write_latest(save_dir, tag, fsync=True):
    """Atomically (re)write the 'latest' pointer."""
    tmp = os.path.join(save_dir, f"{TMP_PREFIX}{LATEST_NAME}")
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, os.path.join(save_dir, LATEST_NAME))
    if fsync:
        _fsync_path(save_dir)


def read_latest(save_dir):
    path = os.path.join(save_dir, LATEST_NAME)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return f.read().strip() or None


def looks_like_tag(tag_dir):
    """True for directories that are plausibly checkpoint tags: an atomic
    tag (manifest, possibly corrupt) or a legacy tag (metadata.pkl).
    Keeps retention GC and the resume scan from touching unrelated
    directories a user parked next to their checkpoints (logs/,
    tensorboard/, ...)."""
    return (os.path.exists(os.path.join(tag_dir, MANIFEST_NAME))
            or os.path.isfile(os.path.join(tag_dir, "metadata.pkl")))


def _list_tag_entries(save_dir):
    """[(tag, manifest-or-None)], newest first (manifest step, then
    mtime).  One manifest parse per tag per scan — resume ordering, GC,
    and the emergency check all read from this."""
    if not os.path.isdir(save_dir):
        return []
    entries = []
    for name in os.listdir(save_dir):
        if name.startswith(TMP_PREFIX):
            continue
        tag_dir = os.path.join(save_dir, name)
        if not os.path.isdir(tag_dir) or not looks_like_tag(tag_dir):
            continue
        manifest = load_manifest(tag_dir)
        step = manifest.get("global_steps", -1) if manifest else -1
        entries.append((step, os.path.getmtime(tag_dir), name, manifest))
    entries.sort(key=lambda e: e[:3], reverse=True)
    return [(name, manifest) for _s, _m, name, manifest in entries]


def list_tags(save_dir):
    """Committed tag names, newest first (manifest step, then mtime)."""
    return [name for name, _manifest in _list_tag_entries(save_dir)]


def _emergency_from_manifest(tag, manifest):
    if manifest is not None and "emergency" in manifest:
        return bool(manifest["emergency"])
    return str(tag).startswith("emergency_")


def _suspect_from_manifest(tag, manifest):
    """True for tags whose manifest records ``integrity_clean: false`` —
    committed INSIDE an unresolved numerical-integrity anomaly window
    (ISSUE 13).  The payload bytes verify fine (the checksums protect
    the write path, not the numbers), but the NUMBERS are suspect, so
    auto-resume must fall back past them the same way it falls back
    past corrupt tags.  Absent stamp (integrity disarmed / older tags)
    = not suspect."""
    return manifest is not None and manifest.get("integrity_clean") is False


def _resume_rank(tag, manifest):
    """Resume-candidate ordering class: healthy tags first, then
    integrity-suspect tags, then the watchdog's emergency snapshots
    (known possibly-diverged state — last resort, unchanged from the
    pre-integrity ordering)."""
    if _emergency_from_manifest(tag, manifest):
        return 2
    if _suspect_from_manifest(tag, manifest):
        return 1
    return 0


def read_topology(tag_dir):
    """The tag's topology manifest (mesh/zero/pipe/schedule layout the
    writing run used — see resilience/reshard.py), readable by tooling
    without unpickling any payload.  None for pre-elastic checkpoints."""
    manifest = load_manifest(tag_dir)
    if manifest is None:
        return None
    return manifest.get("topology")


def is_preempt_tag(save_dir, tag):
    """True for graceful-preemption snapshots (manifest ``preempt``
    flag).  Unlike emergency tags these hold HEALTHY state — they update
    ``latest`` and resume first like any normal tag; the flag only
    records why the run stopped."""
    manifest = load_manifest(os.path.join(save_dir, str(tag)))
    return bool(manifest.get("preempt")) if manifest else False


def is_suspect_tag(save_dir, tag):
    """True for tags committed inside an unresolved integrity-anomaly
    window (manifest ``integrity_clean: false``).  The payload verifies;
    the NUMBERS are suspect — auto-resume prefers any clean tag."""
    return _suspect_from_manifest(
        tag, load_manifest(os.path.join(save_dir, str(tag))))


def is_emergency_tag(save_dir, tag):
    """True for the watchdog's pre-abort snapshots: the manifest's
    ``emergency`` flag when present, else (legacy non-atomic layout writes
    no manifest) the ``emergency_`` tag-name convention."""
    return _emergency_from_manifest(
        tag, load_manifest(os.path.join(save_dir, str(tag))))


def resume_candidates(save_dir):
    """Ordered resume candidates: every committed tag newest-first
    (manifest step, then mtime).  A tag is only visible here after its
    atomic rename, so a crash between rename and the ``latest`` update
    still resumes from the newer committed tag instead of the stale
    pointer.  The ``latest``-pointed tag is appended if the scan somehow
    missed it (e.g. a tag dir swapped out underneath us).

    Tags whose manifest carries ``emergency: true`` (the watchdog's
    final pre-abort snapshot — possibly of a diverged state) sort after
    every normal tag: a restart prefers the last healthy checkpoint and
    only falls back to an emergency tag when nothing else is intact.
    Tags stamped ``integrity_clean: false`` (committed inside an
    unresolved silent-corruption anomaly window, ISSUE 13) sort after
    every clean tag for the same reason — the bytes verify, the numbers
    are suspect."""
    entries = _list_tag_entries(save_dir)
    latest = read_latest(save_dir)
    if latest is not None and latest not in [n for n, _m in entries]:
        entries.append((latest,
                        load_manifest(os.path.join(save_dir, latest))))
    return [name for name, _manifest in
            sorted(entries, key=lambda e: _resume_rank(*e))]


def select_resume_tag(save_dir, check_checksums=True):
    """Newest tag that passes verification, falling back past corrupt ones.
    Returns the tag name or None."""
    for tag in resume_candidates(save_dir):
        ok, reason = verify_tag(os.path.join(save_dir, tag),
                                check_checksums=check_checksums)
        if ok:
            return tag
        logger.warning(f"auto-resume: skipping checkpoint tag {tag!r} "
                       f"({reason})")
    return None


def gc_tags(save_dir, keep, protect=()):
    """Retention: drop stale tmp dirs always; keep the newest ``keep``
    verified tags (0 = keep everything).  Tags in ``protect`` and the tag
    ``latest`` points to are never removed.

    A tag failing a cheap (size-only) verification never consumes a
    retention slot — otherwise bit-rotten newer tags would crowd out the
    intact older checkpoint that auto-resume needs — and is removed, since
    it can never be resumed from.  Emergency tags (manifest
    ``emergency: true``, the watchdog's pre-abort snapshot of a possibly
    diverged state) neither consume slots nor get removed: retention must
    keep the healthy checkpoints resume prefers, and the postmortem
    snapshot is kept for the operator."""
    if not os.path.isdir(save_dir):
        return []
    removed = []
    for name in os.listdir(save_dir):
        if name.startswith(TMP_PREFIX):
            # tmp tag dirs AND the '.tmp-latest' pointer file a crash
            # inside write_latest can strand
            full = os.path.join(save_dir, name)
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
            else:
                try:
                    os.remove(full)
                except OSError:
                    continue
            removed.append(name)
    if not keep or keep <= 0:
        return removed
    keepers = set(protect)
    latest = read_latest(save_dir)
    if latest:
        keepers.add(latest)
    for tag, manifest in _list_tag_entries(save_dir):
        if tag in keepers:
            continue
        full = os.path.join(save_dir, tag)
        if _emergency_from_manifest(tag, manifest):
            continue
        if len(keepers) < keep:
            ok, reason = verify_tag(full, check_checksums=False)
            if ok:
                keepers.add(tag)
                continue
            logger.warning(f"checkpoint GC: tag {tag!r} fails verification "
                           f"({reason}); not counted toward retention")
        shutil.rmtree(full, ignore_errors=True)
        removed.append(tag)
        logger.info(f"checkpoint GC: removed old tag {tag!r}")
    return removed
