"""The transport seam: one interface between the resilience stack and
however peers actually run.

Every distributed-failure proof in this repo (FleetRouter replicas,
supervisor SimHost peers, integrity votes) runs against the same small
set of channels — a step-clock heartbeat bus, a command submit/result
channel, a dead-verdict ack vote, per-peer request journals, and a KV
handoff blob channel.  This module makes that set an explicit contract
(:class:`Transport`) with two implementations:

- :class:`InProcessTransport` — the existing deterministic in-process
  clock, unchanged behind the seam: peers are the supervisor's
  ``SimHost`` state machines (chaos ``kill_ranks`` /
  ``silence_heartbeat`` consulted exactly as before), commands execute
  synchronously in the local process, and the dead-verdict vote is
  trivially unanimous (every simulated survivor shares this process).
  Tier-1 stays bit-identical and wall-clock-free.
- :class:`ProcessTransport` — real worker processes behind the same
  seam: ranks ``1..world-1`` are spawned OS processes
  (``transport_worker.py`` — stdlib-only, no jax import, so spawn is
  milliseconds) speaking JSON lines over stdin/stdout pipes.  Liveness
  is DETECTED, never bookkept: a SIGKILLed worker stops answering the
  step-clock beat, its pipe EOFs, and the per-peer
  :class:`PeerLiveness` stall detector (the PR-12
  ``TrainingWatchdog``) marks it suspect; the supervisor's step-clock
  lag classifier and the ``coordination`` collectives then reach the
  same coordinated dead verdict the in-process sim reaches — but for a
  genuinely dead process.

Scope honesty: under :class:`ProcessTransport` the training/serving
engines still execute in rank 0 (this process) — the workers are the
fleet's HOST bus: they beat the clock, ack verdicts, execute journal
writes and hold handoff blobs.  Moving engine execution itself behind
``submit`` is the remaining ROADMAP item; what this seam buys today is
that peer death, verdict agreement and journal-backed recovery run
against real processes with real kill(2) semantics.

The step loop methods here are pure host work (graftlint holds this
file to the hot-path bar): no jax import, no device traffic, ever.
"""
import base64
import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque

from deepspeed_tpu.runtime.resilience import chaos
from deepspeed_tpu.runtime.resilience.watchdog import (ACTION_CONTINUE,
                                                       TrainingWatchdog)
from deepspeed_tpu.utils.logging import logger

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "transport_worker.py")


class TransportPeerLost(RuntimeError):
    """A command was sent to (or awaited from) a peer that died first."""


def execute_op(payload, state):
    """Execute one submitted command against a peer's ``state`` dict —
    the op table both transports implement.  ``transport_worker.py``
    carries a stdlib-only copy of this table (it must not import
    deepspeed_tpu: worker spawn has to stay jax-free and fast); the
    transport conformance suite pins the two to identical results.

    Ops: ``echo`` (payload back), ``sum`` (fold ``xs``), ``journal``
    (append one record to the peer's journal file, fsynced — the
    zero-lost-requests contract rides this), ``sleep`` (wedge the peer:
    stall-detector food), ``crash`` (die mid-protocol).
    """
    op = payload.get("op")
    if op == "echo":
        return dict(payload)
    if op == "sum":
        return {"op": "sum", "value": sum(payload.get("xs") or [])}
    if op == "journal":
        path = state.get("journal_path")
        if not path:
            return {"op": "journal", "error": "no journal armed"}
        # append-only fsynced request journal, NOT a checkpoint: the
        # zero-lost-requests replay contract rides every record landing
        # before the ack, torn tails are tolerated by the replayer
        with open(path, "a") as f:  # graftlint: disable=raw-ckpt-write
            f.write(json.dumps(payload.get("record")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        state["journal_count"] = state.get("journal_count", 0) + 1
        return {"op": "journal", "count": state["journal_count"]}
    if op == "sleep":
        time.sleep(float(payload.get("seconds", 0.0)))
        return {"op": "sleep"}
    if op == "handoff":
        blob = base64.b64decode(payload.get("blob", ""))
        state.setdefault("blobs", {})[payload.get("key")] = blob
        return handoff_ack(payload.get("key"), blob)
    if op == "crash":
        raise TransportPeerLost("peer crashed on command (op=crash)")
    return {"op": op, "error": "unknown op"}


def handoff_ack(key, blob):
    """The KV-handoff receipt both transports return: content digest +
    byte count, so a conformance test can pin byte-exact delivery."""
    return {"key": key, "sha256": hashlib.sha256(blob).hexdigest(),
            "nbytes": len(blob)}


class PeerLiveness:
    """Per-peer wall-clock liveness on top of the step-clock beats.

    One PR-12 ``TrainingWatchdog`` stall detector per peer: a received
    beat is forward progress (``observe_serving_step``), a missed one
    is a poll (``check_stall``) — a peer silent past
    ``stall_timeout_s`` of WALL time becomes suspect, independent of
    how fast the step clock ticks.  Suspicion clears on the next beat
    (a GC pause is not a death); the verdict itself belongs to the
    supervisor/router ladder, never to this detector."""

    def __init__(self, ranks, *, stall_timeout_s, clock=time.monotonic):
        self._wds = {
            r: TrainingWatchdog(stall_timeout=stall_timeout_s,
                                default_action=ACTION_CONTINUE,
                                clock=clock)
            for r in ranks}
        self.suspected = {}             # rank -> step first suspected

    def on_beat(self, rank, step):
        wd = self._wds.get(rank)
        if wd is None:
            return
        wd.observe_serving_step(step)
        self.suspected.pop(rank, None)

    def poll(self, rank, step):
        """Missed-beat poll; True once the stall detector suspects the
        peer dead (at least one full stall window with no beat)."""
        wd = self._wds.get(rank)
        if wd is None:
            return False
        if wd.check_stall(step) is not None:
            self.suspected.setdefault(rank, step)
        return rank in self.suspected

    def drop(self, rank):
        self._wds.pop(rank, None)
        self.suspected.pop(rank, None)


class Transport:
    """The seam contract.  ``world`` peers, rank 0 always the LOCAL
    process (it runs this code; killing it is not observable from
    inside).  Implementations provide:

    - ``heartbeat_tick(wall_step) -> {rank: last_beat_step}`` — drive
      the step-clock heartbeat bus one tick and report every peer's
      last observed beat; the caller's lag classifier (supervisor
      ``_heartbeat_tick``, router transport tick) turns lag into
      stale/dead suspicion.
    - ``vote_dead(dead, wall_step) -> bool`` — the process-level ack
      round of the dead verdict: every SURVIVING peer must agree before
      recovery acts (the jax ``coordination`` collectives carry the
      same discipline at the device layer).
    - ``submit``/``request``/``poll_results`` — the command channel.
    - ``journal_path(rank)`` — where that peer's request journal lives
      (the migration/recovery source of truth; survives the peer).
    - ``handoff(dst, blob)`` — the KV-handoff blob channel, acked with
      a content digest.
    - ``kill(rank)`` — hard-down a peer for real (tests/chaos): the
      in-process sim flips a flag, the process transport SIGKILLs.
    """

    world = 1
    kind = "abstract"

    def start(self):
        return self

    def close(self):
        pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def heartbeat_tick(self, wall_step):
        raise NotImplementedError

    def alive(self, rank):
        raise NotImplementedError

    def kill(self, rank):
        raise NotImplementedError

    def mark_dead(self, rank):
        """A coordinated verdict was acted on: stop expecting beats
        from (and sending work to) this peer; reap what there is to
        reap.  Detection must never call this — verdicts only."""

    def vote_dead(self, dead, wall_step):
        raise NotImplementedError

    def submit(self, rank, payload):
        raise NotImplementedError

    def request(self, rank, payload, timeout=None):
        raise NotImplementedError

    def poll_results(self, max_results=None):
        raise NotImplementedError

    def journal_path(self, rank):
        return None

    def handoff(self, dst, blob, key=None):
        raise NotImplementedError

    def describe(self):
        return {"kind": self.kind, "world": self.world,
                "alive": [r for r in range(self.world) if self.alive(r)]}


class InProcessTransport(Transport):
    """The deterministic in-process clock behind the seam — tier-1's
    transport.  Peers are ``SimHost`` state machines (pass the
    supervisor's own ``hosts`` list to share state, or a ``world`` to
    build one): each ``heartbeat_tick`` advances them exactly as the
    pre-seam supervisor loop did, chaos ``kill_ranks``/
    ``silence_heartbeat`` included, so supervised behavior is
    bit-identical.  Commands execute synchronously in this process
    through the same op table the worker implements."""

    kind = "in-process"

    def __init__(self, hosts=None, world=None, journal_dir=None):
        if hosts is None:
            from deepspeed_tpu.runtime.resilience.supervisor import SimHost

            assert world is not None and world >= 1, world
            hosts = [SimHost(r, local=(r == 0)) for r in range(world)]
        self.hosts = list(hosts)
        self.world = len(self.hosts)
        self._by_rank = {h.rank: h for h in self.hosts}
        self._journal_dir = journal_dir
        self._states = {}               # rank -> op-table state dict
        self._results = deque()
        self._seq = 0
        self._blobs = {}                # (rank, key) -> handoff blob

    def _state(self, rank):
        st = self._states.get(rank)
        if st is None:
            st = {"journal_path": self.journal_path(rank)}
            self._states[rank] = st
        return st

    def heartbeat_tick(self, wall_step):
        beats = {}
        for h in self.hosts:
            h.tick(wall_step)
            beats[h.rank] = h.last_beat
        return beats

    def alive(self, rank):
        h = self._by_rank.get(rank)
        return bool(h is not None and h.alive)

    def kill(self, rank):
        h = self._by_rank.get(rank)
        if h is not None:
            h.alive = False

    def mark_dead(self, rank):
        self.kill(rank)

    def vote_dead(self, dead, wall_step):
        """Trivially unanimous: every simulated survivor IS this
        process, so the ack round cannot disagree with itself.  The
        supervisor's ``coordination`` calls carry the (single-process
        passthrough) device-layer agreement discipline alongside."""
        return True

    def submit(self, rank, payload):
        if not self.alive(rank):
            raise TransportPeerLost(f"in-process peer {rank} is down")
        self._seq += 1
        seq = self._seq
        self._results.append(
            (rank, seq, execute_op(dict(payload), self._state(rank))))
        return seq

    def request(self, rank, payload, timeout=None):
        seq = self.submit(rank, payload)
        for i, (r, s, res) in enumerate(self._results):
            if r == rank and s == seq:
                del self._results[i]
                return res
        raise TransportPeerLost(f"in-process result {seq} vanished")

    def poll_results(self, max_results=None):
        out = []
        while self._results and (max_results is None
                                 or len(out) < max_results):
            out.append(self._results.popleft())
        return out

    def journal_path(self, rank):
        if self._journal_dir is None:
            return None
        os.makedirs(str(self._journal_dir), exist_ok=True)
        return os.path.join(str(self._journal_dir),
                            f"transport_rank{rank}.jsonl")

    def handoff(self, dst, blob, key=None):
        if not self.alive(dst):
            raise TransportPeerLost(f"in-process peer {dst} is down")
        key = key if key is not None else f"h{self._seq}"
        ack = execute_op({"op": "handoff", "key": key,
                          "blob": base64.b64encode(bytes(blob))
                          .decode("ascii")}, self._state(dst))
        self._blobs[(dst, key)] = bytes(blob)
        return ack


class ProcessTransport(Transport):
    """Real worker processes behind the seam.

    Ranks ``1..world-1`` are spawned ``transport_worker.py`` processes
    (stdlib-only — no jax, so spawn is milliseconds, and a worker can
    be SIGKILLed without wedging any collective).  Protocol: JSON
    lines, parent stdin -> worker, worker stdout -> a reader thread per
    worker that files beats/results/vote-acks and flags pipe EOF.

    Liveness is three independent signals, all DETECTED:

    - **step-clock lag** — ``heartbeat_tick(w)`` broadcasts the step
      and waits up to ``beat_grace_s`` for each live peer's beat; a
      peer that does not answer keeps its old ``last_beat``, and the
      caller's lag classifier does the rest (same math as the sim).
    - **pipe EOF** — a SIGKILLed worker's stdout EOFs within
      milliseconds; the tick stops waiting on it immediately (no grace
      burn), so a real death converges at step-clock speed.
    - **wall-clock stall** — :class:`PeerLiveness` (one PR-12 watchdog
      per peer) suspects a peer that is alive-but-wedged (a worker
      stuck in a ``sleep`` op answers no beats yet holds its pipe
      open).

    ``vote_dead`` runs the process-level ack round: every surviving
    worker must ack the dead set within the grace window, or the
    verdict fails and the caller retries next tick — no one-sided
    verdicts.  Chaos: an armed ``kill_process_ranks`` plan SIGKILLs
    the target for REAL from inside ``heartbeat_tick`` (the
    genuinely-dead-process e2e; nothing simulated about the verdict
    that follows)."""

    kind = "process"

    def __init__(self, world, *, journal_dir=None, beat_grace_s=5.0,
                 stall_timeout_s=None, python=None):
        assert world >= 1, world
        self.world = int(world)
        self._journal_dir = journal_dir
        self.beat_grace_s = float(beat_grace_s)
        self.stall_timeout_s = float(
            stall_timeout_s if stall_timeout_s is not None
            else 2.0 * beat_grace_s)
        self._python = python or sys.executable
        self._procs = {}                # rank -> Popen
        self._readers = {}
        self._eof = {}
        self._dead = set()              # verdicts acted on (mark_dead)
        self._beat = {}                 # rank -> newest beat step seen
        self._last_beat = {0: 0}
        self._votes = {}                # (rank, step) -> agree bool
        # exactly-once result delivery: _result_map is the single store,
        # _result_order its arrival order; request() pops its key from
        # the map, so poll_results (which walks the order deque and
        # skips keys no longer in the map) can never hand the same
        # result out twice — pinned by the transport conformance suite
        # against InProcessTransport
        self._result_map = {}           # (rank, seq) -> payload
        self._result_order = deque()    # (rank, seq) arrival order
        self._cond = threading.Condition()
        self._seq = 0
        self._local_state = {"journal_path": None}
        self._started = False
        self.liveness = PeerLiveness(
            range(1, self.world), stall_timeout_s=self.stall_timeout_s)

    # -- lifecycle ------------------------------------------------------
    def start(self):
        if self._started:
            return self
        self._started = True
        self._local_state["journal_path"] = self.journal_path(0)
        for rank in range(1, self.world):
            env = dict(os.environ)
            env.update(DSTPU_TR_RANK=str(rank),
                       DSTPU_TR_WORLD=str(self.world),
                       DSTPU_TR_JOURNAL=self.journal_path(rank) or "")
            proc = subprocess.Popen(
                [self._python, _WORKER], env=env,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True, bufsize=1)
            self._procs[rank] = proc
            self._eof[rank] = False
            t = threading.Thread(target=self._reader, args=(rank, proc),
                                 daemon=True)
            t.start()
            self._readers[rank] = t
        return self

    def close(self):
        for rank, proc in list(self._procs.items()):
            if proc.poll() is None:
                try:
                    self._send(rank, {"t": "exit"})
                except TransportPeerLost:
                    pass
        deadline = time.monotonic() + 2.0
        for rank, proc in list(self._procs.items()):
            try:
                proc.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        for t in self._readers.values():
            t.join(timeout=2.0)

    def _reader(self, rank, proc):
        """One thread per worker: files protocol messages under the
        condition variable, flags EOF when the pipe dies (the fastest
        honest death signal a SIGKILL leaves behind)."""
        for line in proc.stdout:
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            t = msg.get("t")
            with self._cond:
                if t == "beat":
                    step = int(msg.get("step", 0))
                    if step > self._beat.get(rank, -1):
                        self._beat[rank] = step
                elif t == "result":
                    key = (rank, int(msg.get("seq", -1)))
                    self._result_map[key] = msg.get("payload")
                    self._result_order.append(key)
                elif t == "vote_ack":
                    self._votes[(rank, int(msg.get("step", -1)))] = \
                        bool(msg.get("agree"))
                self._cond.notify_all()
        with self._cond:
            self._eof[rank] = True
            self._cond.notify_all()

    def _send(self, rank, msg):
        proc = self._procs.get(rank)
        if proc is None or proc.stdin is None or proc.poll() is not None:
            raise TransportPeerLost(f"peer {rank} process is gone")
        try:
            proc.stdin.write(json.dumps(msg) + "\n")
            proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError) as e:
            raise TransportPeerLost(f"peer {rank} pipe broke: {e}")

    def _live_peers(self):
        return [r for r in range(1, self.world)
                if r not in self._dead and not self._eof.get(r, True)]

    # -- heartbeat bus --------------------------------------------------
    def heartbeat_tick(self, wall_step):
        w = int(wall_step)
        self._last_beat[0] = w          # rank 0 runs this code: it beats
        if chaos.active() is not None:
            for rank in self._live_peers():
                if chaos.process_kill_due(rank, w):
                    self.kill(rank)
        live = self._live_peers()
        for rank in live:
            try:
                self._send(rank, {"t": "step", "step": w})
            except TransportPeerLost:
                pass                    # EOF flag will carry the news
        deadline = time.monotonic() + self.beat_grace_s
        with self._cond:
            while True:
                pending = [r for r in live
                           if self._beat.get(r, -1) < w
                           and not self._eof.get(r, True)]
                remaining = deadline - time.monotonic()
                if not pending or remaining <= 0:
                    break
                self._cond.wait(min(0.05, remaining))
        for rank in range(1, self.world):
            if rank in self._dead:
                continue
            if self._beat.get(rank, -1) >= w:
                self._last_beat[rank] = w
                self.liveness.on_beat(rank, w)
            else:
                self.liveness.poll(rank, w)
        return dict(self._last_beat)

    def alive(self, rank):
        if rank == 0:
            return True
        if rank in self._dead or self._eof.get(rank, True):
            return False
        proc = self._procs.get(rank)
        return proc is not None and proc.poll() is None

    def kill(self, rank):
        """SIGKILL the peer — a REAL death: nothing is bookkept here;
        the beat bus, pipe EOF and stall detector must detect it and
        the caller's verdict machinery must agree on it."""
        proc = self._procs.get(rank)
        if proc is not None and proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
            logger.warning(
                "transport: SIGKILLed worker rank %d (pid %d)",
                rank, proc.pid)

    def mark_dead(self, rank):
        self._dead.add(rank)
        self.liveness.drop(rank)
        proc = self._procs.get(rank)
        if proc is not None:
            try:
                proc.wait(timeout=1.0)      # reap the zombie
            except subprocess.TimeoutExpired:
                proc.kill()

    def vote_dead(self, dead, wall_step):
        """Process-level verdict ack: every surviving worker must agree
        the ``dead`` set is dead, within the grace window.  A missing
        or dissenting ack fails the vote — the caller retries next tick
        rather than act one-sided."""
        w = int(wall_step)
        dead = sorted(int(r) for r in dead)
        voters = [r for r in self._live_peers() if r not in dead]
        for rank in voters:
            try:
                self._send(rank, {"t": "vote", "step": w, "dead": dead})
            except TransportPeerLost:
                pass
        deadline = time.monotonic() + self.beat_grace_s
        with self._cond:
            while True:
                missing = [r for r in voters
                           if (r, w) not in self._votes
                           and not self._eof.get(r, True)]
                remaining = deadline - time.monotonic()
                if not missing or remaining <= 0:
                    break
                self._cond.wait(min(0.05, remaining))
            return all(self._votes.get((r, w), False) for r in voters
                       if not self._eof.get(r, True))

    # -- command channel ------------------------------------------------
    def submit(self, rank, payload):
        if rank == 0:
            self._seq += 1
            with self._cond:
                self._result_map[(0, self._seq)] = execute_op(
                    dict(payload), self._local_state)
                self._result_order.append((0, self._seq))
            return self._seq
        if not self.alive(rank):
            raise TransportPeerLost(f"peer {rank} is down")
        self._seq += 1
        self._send(rank, {"t": "submit", "seq": self._seq,
                          "payload": payload})
        return self._seq

    def request(self, rank, payload, timeout=None):
        seq = self.submit(rank, payload)
        if rank == 0:
            with self._cond:
                return self._result_map.pop((0, seq))
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.beat_grace_s)
        with self._cond:
            while (rank, seq) not in self._result_map:
                remaining = deadline - time.monotonic()
                if self._eof.get(rank, True):
                    raise TransportPeerLost(
                        f"peer {rank} died before answering seq {seq}")
                if remaining <= 0:
                    raise TransportPeerLost(
                        f"peer {rank} did not answer seq {seq} within "
                        f"{timeout if timeout is not None else self.beat_grace_s:g}s")
                self._cond.wait(min(0.05, remaining))
            return self._result_map.pop((rank, seq))

    def poll_results(self, max_results=None):
        out = []
        with self._cond:
            while self._result_order and (max_results is None
                                          or len(out) < max_results):
                key = self._result_order.popleft()
                if key in self._result_map:     # not consumed by request()
                    out.append((key[0], key[1],
                                self._result_map.pop(key)))
        return out

    # -- journals / handoff --------------------------------------------
    def journal_path(self, rank):
        if self._journal_dir is None:
            return None
        os.makedirs(str(self._journal_dir), exist_ok=True)
        return os.path.join(str(self._journal_dir),
                            f"transport_rank{rank}.jsonl")

    def handoff(self, dst, blob, key=None):
        blob = bytes(blob)
        key = key if key is not None else f"h{self._seq}"
        if dst == 0:
            return handoff_ack(key, blob)
        ack = self.request(dst, {
            "op": "handoff", "key": key,
            "blob": base64.b64encode(blob).decode("ascii")})
        return ack

    def describe(self):
        d = super().describe()
        d["pids"] = {r: p.pid for r, p in self._procs.items()}
        d["suspected"] = dict(self.liveness.suspected)
        return d
