"""Worker main for :class:`ProcessTransport` (transport.py).

Spawned once per peer rank with ``DSTPU_TR_{RANK,WORLD,JOURNAL}`` set.
STDLIB ONLY — importing deepspeed_tpu (and through it jax) would make
every spawn pay a multi-second import and pin the worker to the
parent's accelerator runtime; the whole point of the seam is that a
peer is a cheap real OS process that can be SIGKILLed mid-protocol.

Protocol: JSON lines.  stdin commands ->

- ``{"t": "step", "step": N}``     -> ``{"t": "beat", "rank": r, "step": N}``
- ``{"t": "submit", "seq": S, "payload": P}``
                                   -> ``{"t": "result", "seq": S,
                                         "rank": r, "payload": <op result>}``
- ``{"t": "vote", "step": N, "dead": [...]}``
                                   -> ``{"t": "vote_ack", "rank": r,
                                         "step": N, "agree": true}``
  (a live worker always agrees a set it is NOT in is dead: its own
  liveness is exactly what answering proves; a dead worker cannot ack,
  which is what makes the vote mean something)
- ``{"t": "exit"}``                -> clean exit 0

The op table mirrors ``transport.execute_op`` — a hand-kept stdlib
copy; the transport conformance suite (tests/unit/test_transport.py)
pins the two implementations to identical results, so drift fails
tier-1 rather than lurking.
"""
import base64
import hashlib
import json
import os
import sys
import time

RANK = int(os.environ.get("DSTPU_TR_RANK", "0"))
JOURNAL = os.environ.get("DSTPU_TR_JOURNAL") or None

_state = {"journal_path": JOURNAL, "journal_count": 0, "blobs": {}}


def _execute_op(payload):
    op = payload.get("op")
    if op == "echo":
        return dict(payload)
    if op == "sum":
        return {"op": "sum", "value": sum(payload.get("xs") or [])}
    if op == "journal":
        path = _state["journal_path"]
        if not path:
            return {"op": "journal", "error": "no journal armed"}
        # append-only fsynced journal, NOT a checkpoint (mirrors
        # transport.execute_op — see its suppression note)
        with open(path, "a") as f:  # graftlint: disable=raw-ckpt-write
            f.write(json.dumps(payload.get("record")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        _state["journal_count"] += 1
        return {"op": "journal", "count": _state["journal_count"]}
    if op == "sleep":
        time.sleep(float(payload.get("seconds", 0.0)))
        return {"op": "sleep"}
    if op == "handoff":
        blob = base64.b64decode(payload.get("blob", ""))
        _state["blobs"][payload.get("key")] = blob
        return {"key": payload.get("key"),
                "sha256": hashlib.sha256(blob).hexdigest(),
                "nbytes": len(blob)}
    if op == "crash":
        os._exit(3)
    return {"op": op, "error": "unknown op"}


def _emit(msg):
    sys.stdout.write(json.dumps(msg) + "\n")
    sys.stdout.flush()


def main():
    for line in sys.stdin:
        try:
            msg = json.loads(line)
        except ValueError:
            continue
        t = msg.get("t")
        if t == "step":
            _emit({"t": "beat", "rank": RANK,
                   "step": int(msg.get("step", 0))})
        elif t == "submit":
            _emit({"t": "result", "seq": int(msg.get("seq", -1)),
                   "rank": RANK,
                   "payload": _execute_op(msg.get("payload") or {})})
        elif t == "vote":
            dead = [int(r) for r in (msg.get("dead") or [])]
            _emit({"t": "vote_ack", "rank": RANK,
                   "step": int(msg.get("step", -1)),
                   "agree": RANK not in dead})
        elif t == "exit":
            return 0
    return 0                    # parent closed stdin: clean shutdown


if __name__ == "__main__":
    sys.exit(main())
