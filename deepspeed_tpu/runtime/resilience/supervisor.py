"""Self-healing elastic training: the supervisor that owns the loop.

Serving already treats a dying replica as a ROUTINE event (PR 9
reliability, PR 11 FleetRouter); a training run, by contrast, died on
any rank fault and waited for a human.  Every recovery primitive it
needs already exists — topology manifests + ``load_checkpoint(
elastic=True)`` + ``fast_forward`` (reshard.py), ``compute_elastic_
config`` (elasticity/), the ``any_flag``/``all_agree`` coordination
discipline, the watchdog, atomic committed tags.  This module wires
them into the automatic detect -> verdict -> recover loop:

- **Detection** — step-clock heartbeats: every (simulated) host posts
  its wall step each tick; a peer silent past ``heartbeat_timeout_
  steps`` is suspected dead.  A stale-but-within-window peer means the
  collective step cannot complete, so the local rank does NOT step
  (that tick is honest downtime, never a half-committed batch).  The
  watchdog's stall/NaN streaks and any exception escaping a step feed
  the same classifier.
- **Verdict** — suspicion is ORed across hosts (``any_flag``) and the
  recovery decision is agreed (``all_agree``) BEFORE anyone acts, so no
  rank wedges peers in a collective; elastic restarts additionally
  agree on the smallest surviving world (``min_int``) and the resume
  tag (``broadcast_tag``).
- **Response ladder** (the PR-11 circuit-breaker discipline): transient
  step faults retry IN PLACE from live state with bounded backoff
  (``retry_backoff_steps`` x (strike - 1) — first retry immediate,
  ``max_transient_retries`` strikes escalate); persistent faults (watchdog NaN/overflow streaks, step
  crashes, exhausted retries) trigger a coordinated ROLLBACK to the
  last committed tag; SILENT faults — finite-but-wrong numbers caught
  by the integrity sentinels / cross-replica vote
  (runtime/resilience/integrity.py, ISSUE 13) — take the ``corrupt``
  rung between them: rollback to the last integrity-CLEAN published
  tag PLUS a PaLM-style skip of the offending data window, escalating
  to rank QUARANTINE (elastic restart without the convicted rank) on
  repeat offenders; lost capacity (dead verdict) triggers an ELASTIC
  RESTART onto the surviving mesh — new engine from ``engine_factory``
  at the largest valid elastic world, ``load_checkpoint(elastic=True)``
  from the last committed tag, ``fast_forward`` to the exact sample
  offset.  Zero samples are lost or replayed in the committed
  trajectory, and post-recovery losses are bit-identical to an
  uninterrupted run on the target mesh resumed from that tag (for a
  corrupt verdict: to an uninterrupted run that skipped the same
  window).
- **Accounting** — a ``recovery`` telemetry lane (failure / verdict /
  rollback / restart instants + downtime spans), MTTR and
  goodput-samples-per-wall-step in ``engine.telemetry_report()
  ["recovery"]``, restart-count/backoff state in ``_last_metrics``.

Single-host simulation: peers are :class:`SimHost` state machines on
the supervisor's step clock (the PR-11 in-process-replica pattern), so
the whole failure matrix — kill mid-step, kill mid-rollback, kill
mid-restart, chained double failure, heartbeat silence — is
tier-1-testable with ``chaos.arm(kill_ranks=...)``.  On real
multi-process runs the sim collapses to the local host: peer-death
detection rides the watchdog stall detector (a dead peer wedges the
collective, the stall fires) and the coordination collectives above;
the step-clock heartbeat bus is the deterministic stand-in tier 1 can
drive.
"""
import logging
from dataclasses import dataclass

from deepspeed_tpu.runtime.resilience import chaos
from deepspeed_tpu.runtime.resilience.coordination import (all_agree,
                                                           any_flag,
                                                           broadcast_tag,
                                                           min_int)
from deepspeed_tpu.runtime.resilience.watchdog import (GracefulPreemption,
                                                       WatchdogAlarm)
from deepspeed_tpu.utils.logging import log_dist, logger

# incident kinds (the failure taxonomy; docs/tutorials/fault_tolerance.md)
KIND_TRANSIENT = "transient"       # step fault, live state intact
KIND_WATCHDOG = "watchdog"         # NaN/overflow streak / stall escalation
KIND_CRASH = "crash"               # exception/interrupt escaping a step
KIND_PEER_STALL = "peer_stall"     # peer silent, within heartbeat window
KIND_CORRUPT = "corrupt"           # silent-corruption verdict (ISSUE 13):
#                                    finite-but-wrong numbers caught by the
#                                    integrity sentinels / cross-replica
#                                    vote — between transient and dead
KIND_HOST_LOST = "host_lost"       # coordinated dead verdict

# recovery actions (the ladder rungs)
RECOVERY_RETRY = "retry-in-place"
RECOVERY_ROLLBACK = "rollback"
RECOVERY_ROLLBACK_SKIP = "rollback-and-skip"   # + skip the anomalous data
#                                                window (PaLM-style)
RECOVERY_QUARANTINE = "quarantine"             # elastic restart WITHOUT the
#                                                repeat-offender rank
RECOVERY_RESTART = "elastic-restart"


class TransientStepFault(RuntimeError):
    """A step fault that left live state intact (data fetch hiccup,
    flaky interconnect read, chaos ``fail_step_transient``): the bottom
    rung of the ladder — retry in place, no checkpoint load."""


class SupervisorGaveUp(RuntimeError):
    """The bounded ladder is exhausted (or recovery is impossible: no
    committed tag, no valid elastic world, restarts over budget).  The
    run is down for real; a human owns it again."""


@dataclass(frozen=True)
class SupervisorConfig:
    """Detection windows + the retry/backoff ladder, all in STEPS (the
    supervisor runs on a step clock; see the config-block twins in
    runtime/constants.py for the ds_config spelling)."""
    heartbeat_timeout_steps: int = 3
    max_transient_retries: int = 2
    retry_backoff_steps: int = 1
    max_recovery_attempts: int = 3
    max_restarts: int = 4
    checkpoint_every_steps: int = 1

    @staticmethod
    def from_engine(engine):
        """Read the ``resilience.supervisor`` ds_config block off a live
        engine (validated at config parse time)."""
        r = engine._resilience
        return SupervisorConfig(
            heartbeat_timeout_steps=r.supervisor_heartbeat_timeout_steps,
            max_transient_retries=r.supervisor_max_transient_retries,
            retry_backoff_steps=r.supervisor_retry_backoff_steps,
            max_recovery_attempts=r.supervisor_max_recovery_attempts,
            max_restarts=r.supervisor_max_restarts,
            checkpoint_every_steps=r.supervisor_checkpoint_every_steps)


class SimHost:
    """One simulated peer host on the supervisor's step clock.

    Pure heartbeat state machine: each tick it posts its wall step
    unless an armed chaos plan killed it (``kill_ranks`` — permanent)
    or silenced it (``silence_heartbeat`` — alive but unreachable).
    Host 0 is the LOCAL process and always beats (it is the one running
    this code; killing it is not simulable in-process)."""

    def __init__(self, rank, local=False):
        self.rank = rank
        self.local = local
        self.alive = True
        self.last_beat = 0

    def tick(self, wall_step):
        if self.alive and not self.local \
                and chaos.active() is not None \
                and chaos.rank_dead(self.rank, wall_step):
            self.alive = False
        if not self.alive:
            return
        if not self.local and chaos.active() is not None \
                and chaos.heartbeat_silenced(self.rank, wall_step):
            return
        self.last_beat = wall_step


class TrainingSupervisor:
    """Owns the train loop; turns rank/host failure into a
    bounded-downtime event instead of a dead run.

    ``engine_factory(world)`` builds an engine for a data-parallel
    world of that size (the config must carry an ``elasticity`` block
    so every world resolves to the SAME global batch).
    ``data_factory(engine)`` returns a fresh deterministic iterator of
    micro-batches in that engine's shape, positioned at sample 0 — the
    supervisor fast-forwards it to the exact committed offset after
    every rollback/restart.  ``save_dir`` holds the committed tags the
    ladder recovers to.
    """

    def __init__(self, engine_factory, data_factory, *, save_dir,
                 world_size=None, config=None, transport=None):
        self.engine_factory = engine_factory
        self.data_factory = data_factory
        self.save_dir = save_dir
        self.wall_step = 0
        self.restarts = 0
        self.rollbacks = 0
        self.commit_failures = 0
        self.transient_retries = 0
        self._strikes = 0
        self._backoff_until = 0
        self.last_committed_tag = None
        self._last_committed_step = -1
        self._last_saved_step = -1
        # numerical integrity (ISSUE 13): the corrupt rung's bookkeeping
        self.last_clean_tag = None      # last PUBLISHED integrity-clean tag
        self.corrupt_verdicts = 0
        self.quarantines = 0
        self.skipped_samples = 0        # data deliberately skipped, total
        self._offenses = {}             # rank -> corrupt-verdict count
        # async commit cadence (ROADMAP PR-12 follow-up): the tag whose
        # seal is in flight — a rollback target only once PUBLISHED
        self._pending_published = None
        self.loss_history = []      # (global_step, loss) committed; device
        #                             values until _materialize_history
        self._history_floats = 0    # prefix already folded to floats
        self.incidents = []         # closed + open incident dicts
        self.verdicts = []          # coordinated dead verdicts reached
        self._open_incident = None
        self._downtime_t0 = 0.0

        engine = engine_factory(world_size)
        if config is None:
            config = SupervisorConfig.from_engine(engine)
        elif isinstance(config, dict):
            config = SupervisorConfig(**config)
        self.config = config
        self.world = int(world_size if world_size is not None
                         else engine.dp_world_size)
        if transport is not None and transport.world != self.world:
            raise ValueError(
                f"transport world {transport.world} != supervisor world "
                f"{self.world} — the heartbeat bus and the engine's dp "
                f"world must agree or the lag classifier misreads peers")
        self.hosts = [SimHost(r, local=(r == 0)) for r in range(self.world)]
        # the transport seam (ISSUE 16): every heartbeat/verdict goes
        # through it.  The default is the in-process clock SHARING this
        # supervisor's SimHost list — bit-identical to the pre-seam
        # loop, wall-clock-free, tier-1's transport.  A ProcessTransport
        # here puts real SIGKILL-able worker processes behind the same
        # detection -> verdict -> recovery machinery.
        if transport is None:
            from deepspeed_tpu.runtime.resilience.transport import (
                InProcessTransport)

            transport = InProcessTransport(hosts=self.hosts)
        self.transport = transport.start()
        self._attach(engine)
        self.data_iter = data_factory(engine)

    # ------------------------------------------------------------------
    # arming / engine attachment
    # ------------------------------------------------------------------
    def _attach(self, engine):
        """Bind a (new) engine: arm the supervised-step hook points on
        it (the engine warns DISARMED naming blockers when it cannot),
        cache the elastic world set, and rewire the ``recovery``
        telemetry lane onto its tracer."""
        self.engine = engine
        self.armed = bool(engine._arm_supervisor(self))
        self._elastic = self._elastic_worlds(engine) if self.armed else None
        self._tracer = getattr(engine, "_tracer", None)
        self._lane_recovery = 0
        if self._tracer is not None:
            self._lane_recovery = self._tracer.lane("recovery")
            for name in ("failure", "retry", "dead_verdict", "rollback",
                         "elastic_restart", "recovered", "commit_failed",
                         "corrupt_verdict", "quarantine"):
                self._tracer.intern(name, args=("wall_step",))
            self._tracer.intern("downtime", args=("wall_steps",))
            self._tracer.intern("data_skipped", args=("samples",))

    @staticmethod
    def _elastic_worlds(engine):
        """(final_batch, sorted valid world sizes) from the engine's
        elasticity config, or None when elasticity is not enabled (the
        engine's ``_arm_supervisor`` already warned that elastic restart
        is disarmed in that case)."""
        from deepspeed_tpu.elasticity import (compute_elastic_config,
                                              elasticity_enabled)

        pd = engine._config._param_dict
        if not elasticity_enabled(pd):
            return None
        from deepspeed_tpu.version import __version__

        final, valid = compute_elastic_config(pd, __version__)
        return int(final), sorted(int(v) for v in valid)

    def _instant(self, name, a0=0):
        if self._tracer is not None:
            self._tracer.instant(name, self._lane_recovery, a0=int(a0))

    # ------------------------------------------------------------------
    # the supervised loop
    # ------------------------------------------------------------------
    def run(self, num_steps, *, max_wall_steps=None):
        """Drive supervised training until ``num_steps`` optimizer steps
        have committed (or the ladder gives up).  Returns the (possibly
        replaced-by-restart) engine."""
        limit = max_wall_steps if max_wall_steps is not None \
            else num_steps * 16 + 64
        while self.engine.global_steps < num_steps:
            if self.wall_step >= limit:
                raise SupervisorGaveUp(
                    f"supervised run spent {self.wall_step} wall steps on "
                    f"{self.engine.global_steps}/{num_steps} committed "
                    f"steps — recovery is not converging")
            self.tick()
        return self.engine

    def tick(self):
        """One supervisor wall step: heartbeats, verdicts, then (when
        the collective is healthy and no backoff is pending) one
        supervised training step."""
        self.wall_step += 1
        if not self.armed:
            # unsupervised passthrough: bit-identical steps, zero extra
            # compiles (the disarmed pin) — no chaos consults, no
            # recovery, no heartbeat bus
            loss = self.engine.train_batch(data_iter=self.data_iter)
            self._note_committed(loss)
            return
        w = self.wall_step
        stale, dead = self._heartbeat_tick(w)
        if dead:
            if self._verdict(dead, w):
                self._elastic_restart(dead)
            else:
                # suspicion without agreement (a transport ack vote can
                # time out on a wedged survivor): the collective step
                # still cannot complete — honest downtime, retry the
                # verdict next tick
                self._open(KIND_PEER_STALL, w)
            return
        if stale:
            # a silent-but-within-window peer: the collective step could
            # not complete — honest downtime, never a half-stepped batch
            self._open(KIND_PEER_STALL, w)
            return
        if w < self._backoff_until:
            return                      # waiting out the retry backoff
        self.supervised_step()

    def supervised_step(self):
        """One training step under the classifier: transient faults feed
        the in-place retry ladder, watchdog alarms and crashes feed the
        coordinated rollback, preemption passes through untouched."""
        w = self.wall_step
        try:
            if chaos.active() is not None \
                    and chaos.consume_transient_fault(w):
                raise TransientStepFault(
                    f"chaos: transient step fault at wall step {w}")
            loss = self.engine.train_batch(data_iter=self.data_iter)
        except TransientStepFault as e:
            self._on_step_fault(e, KIND_TRANSIENT)
            return
        except WatchdogAlarm as e:
            self._on_step_fault(e, KIND_WATCHDOG)
            return
        except GracefulPreemption:
            raise                       # the graceful shutdown path owns it
        except chaos.ChaosInterrupt as e:
            self._on_step_fault(e, KIND_CRASH)
            return
        except Exception as e:  # lint: allow-broad-except — classify and
            # recover is the supervisor's whole job; unknown faults take
            # the persistent (rollback) rung, never a silent swallow
            self._on_step_fault(e, KIND_CRASH)
            return
        self._strikes = 0
        # the corrupt rung (ISSUE 13) decides BEFORE the step commits:
        # a verdict at this boundary discards the step's result (loss
        # never enters the committed trajectory, the cadence commit
        # never runs) — otherwise a corruption landing at a commit
        # boundary could be snapshotted into a tag stamped clean and
        # become the very rollback target the recovery flees to
        if self._integrity_tick():
            return
        self._note_committed(loss)

    # ------------------------------------------------------------------
    # detection
    # ------------------------------------------------------------------
    def _heartbeat_tick(self, w):
        """Drive the transport's heartbeat bus one step-clock tick and
        classify each peer's lag; returns ``(stale_ranks, dead_ranks)``
        — stale peers are silent but within the heartbeat window, dead
        peers are past it.  The default in-process transport shares
        ``self.hosts`` (each tick advances the SimHost machines exactly
        as the pre-seam loop did); a process transport returns the real
        beats its workers answered — same classifier, real silence."""
        timeout = self.config.heartbeat_timeout_steps
        beats = self.transport.heartbeat_tick(w)
        stale, dead = [], []
        for h in self.hosts:
            if h.rank in beats:
                h.last_beat = max(h.last_beat, beats[h.rank])
            lag = w - h.last_beat
            if lag <= 0:
                continue
            if lag > timeout:
                dead.append(h.rank)
            else:
                stale.append(h.rank)
        return stale, dead

    def _verdict(self, dead, w):
        """Coordinated dead verdict: OR local suspicion across hosts
        (``any_flag`` — one rank's evidence preempts everyone), then
        agree on acting (``all_agree``) so every rank leaves the
        collective step loop together — no rank wedges in a barrier —
        and the TRANSPORT runs its process-level ack round
        (``vote_dead``): every surviving peer must ack the dead set
        before recovery acts.  The in-process transport's vote is
        trivially unanimous (every simulated survivor shares this
        process) and the jax collectives are passthroughs at
        process_count()==1, so tier-1 behavior is unchanged; under a
        ProcessTransport a wedged survivor failing to ack fails the
        verdict and the supervisor retries next tick rather than act
        one-sided."""
        suspected = any_flag(bool(dead))
        if not suspected:
            return False
        agreed, _ = all_agree(True)
        agreed = bool(agreed) and bool(
            self.transport.vote_dead(sorted(dead), w))
        self.verdicts.append({"wall_step": w, "dead": sorted(dead),
                              "agreed": bool(agreed)})
        if not agreed:
            log_dist(
                f"supervisor: dead suspicion for rank(s) {sorted(dead)} "
                f"at wall step {w} did NOT reach a coordinated verdict "
                f"(transport ack vote failed) — retrying next tick",
                ranks=[0], level=logging.WARNING)
            return False
        self._instant("dead_verdict", a0=w)
        log_dist(
            f"supervisor: coordinated DEAD verdict at wall step {w} for "
            f"rank(s) {sorted(dead)} (silent past "
            f"{self.config.heartbeat_timeout_steps}-step heartbeat window)",
            ranks=[0], level=logging.WARNING)
        return bool(agreed)

    # ------------------------------------------------------------------
    # the response ladder
    # ------------------------------------------------------------------
    def _on_step_fault(self, exc, kind):
        w = self.wall_step
        self._open(kind, w)
        self._strikes += 1
        logger.warning(f"supervisor: {kind} step fault at wall step {w} "
                       f"(strike {self._strikes}): {exc}")
        if kind == KIND_TRANSIENT \
                and self._strikes <= self.config.max_transient_retries:
            self.transient_retries += 1
            self._backoff_until = w + 1 \
                + self.config.retry_backoff_steps * (self._strikes - 1)
            self._instant("retry", a0=w)
            # a transient fault raised from INSIDE train_batch (a real
            # loader hiccup) may have consumed part of the gas window —
            # reseat the stream at the engine's exact committed sample
            # offset so the retry replays the whole batch: zero samples
            # lost or replayed, whatever the fault consumed
            self._reseat_live()
            return
        self._rollback(reason=kind)

    def _integrity_tick(self):
        """The corrupt rung's decision point, at every healthy step
        boundary BEFORE that step commits: the integrity monitor folds
        sentinel + vote evidence into at most one verdict per incident
        (integrity.IntegrityMonitor.decide — cheap early-outs; device
        work only on the vote/dup cadences), and a verdict picks its
        recovery — quarantine for a repeat-offender rank,
        rollback-and-skip otherwise.  Returns True when a verdict fired
        (the caller then discards the step's commit)."""
        mon = getattr(self.engine, "_integrity", None)
        if mon is None:
            return False
        verdict = mon.decide(self.engine, self.wall_step)
        if verdict is None:
            return False
        self._on_corrupt(mon, verdict)
        return True

    def _on_corrupt(self, mon, verdict):
        w = self.wall_step
        self.corrupt_verdicts += 1
        self._open(KIND_CORRUPT, w)
        inc = self._open_incident
        culprits = list(verdict.get("culprits") or [])
        for r in culprits:
            self._offenses[r] = self._offenses.get(r, 0) + 1
        if inc is not None:
            inc.update({
                "kind": KIND_CORRUPT, "culprits": sorted(culprits),
                "source": verdict.get("source"),
                "tie": bool(verdict.get("tie")),
                "anomaly_step": verdict.get("anomaly_step"),
                "detection_latency_steps": verdict.get("latency_steps"),
                "offense_counts": dict(self._offenses),
            })
        self._instant("corrupt_verdict", a0=w)
        log_dist(
            f"supervisor: CORRUPT verdict at wall step {w} "
            f"(source={verdict.get('source')}, "
            f"culprits={sorted(culprits) or 'none'}, "
            f"tie={bool(verdict.get('tie'))}, detection latency "
            f"{verdict.get('latency_steps')} step(s))",
            ranks=[0], level=logging.WARNING)
        # repeat offenders get quarantined: the rank keeps producing
        # corrupt replicas, so rolling back onto it again is wasted
        # goodput — restart elastically WITHOUT it.  Host 0 is the local
        # process (not quarantinable in the single-process sim), and the
        # rung needs elasticity + restart budget; otherwise fall through
        # to rollback-and-skip (a tie never counts an offense: the vote
        # refused a rank verdict)
        repeat = sorted(
            r for r in culprits
            if r != 0 and self._offenses.get(r, 0)
            >= mon.config.quarantine_after)
        try:
            if repeat and self._elastic is not None \
                    and self.restarts < self.config.max_restarts:
                self.quarantines += 1
                if inc is not None:
                    inc["quarantined"] = repeat
                log_dist(
                    f"supervisor: QUARANTINING repeat-offender rank(s) "
                    f"{repeat} ({self._offenses}) — elastic restart "
                    f"without them", ranks=[0], level=logging.WARNING)
                self._elastic_restart(repeat, reason=KIND_CORRUPT)
            else:
                self._rollback(reason=KIND_CORRUPT, skip_data=True)
        finally:
            # re-arm the monitor whatever the recovery did (even a
            # SupervisorGaveUp must not wedge a later operator-driven
            # resume behind a latched verdict)
            mon.resolve(recovered=True)

    def _drain_pending_commit(self):
        """Async-cadence satellite (ROADMAP PR-12 follow-up): before any
        verdict-driven recovery, drain the pending seal — a sealed-but-
        unpublished tag either publishes here (becoming the freshest
        rollback target via on_commit_published) or fails here (the
        previous PUBLISHED tag stays the target; counted like any
        commit failure, never fatal)."""
        eng = self.engine
        if not callable(getattr(eng, "pending_commit", None)) \
                or not eng.pending_commit():
            return
        try:
            eng.wait_pending_commit()
        except Exception as e:  # lint: allow-broad-except — a failed
            # seal/publish must not abort the recovery already running;
            # the rollback target stays the last published tag
            self.commit_failures += 1
            self._pending_published = None
            logger.warning(
                f"supervisor: pending async commit failed while draining "
                f"before recovery ({type(e).__name__}: {e}) — rollback "
                f"target stays {self.last_committed_tag!r}")
            self._instant("commit_failed", a0=self.wall_step)

    def _skip_and_reseat(self, pos_before):
        """Rollback-and-skip (PaLM-style): the engine is freshly rolled
        back to a clean tag; advance the DATA stream past everything
        consumed up to the fault, so the anomalous window is never
        trained on again.  The skip is loud (incident ledger + warning
        + ``data_skipped`` instant) and persists in every later
        checkpoint via ``engine.samples_skipped`` — honest goodput
        accounting, not silent sample loss."""
        from deepspeed_tpu.runtime.resilience.reshard import (data_position,
                                                              fast_forward)

        gs = int(self.engine.global_steps)
        self.loss_history = [(g, l) for g, l in self.loss_history
                             if g <= gs]
        self._history_floats = min(self._history_floats,
                                   len(self.loss_history))
        at_tag = int(data_position(self.engine)["samples_consumed"])
        skip = int(pos_before["samples_consumed"]) - at_tag
        if skip > 0:
            self.engine.samples_skipped += skip
            self.skipped_samples += skip
            inc = self._open_incident
            if inc is not None:
                inc["skipped_samples"] = skip
                inc["skip_from_sample"] = at_tag
                inc["skip_to_sample"] = at_tag + skip
            self._instant("data_skipped", a0=skip)
            log_dist(
                f"supervisor: SKIPPING the anomalous data window — "
                f"samples [{at_tag}, {at_tag + skip}) ({skip} samples) "
                f"will never be trained on (PaLM-style rollback-and-"
                f"skip; recorded in the incident ledger and in every "
                f"later checkpoint's data_position)",
                ranks=[0], level=logging.WARNING)
        it = self.data_factory(self.engine)
        self.data_iter = fast_forward(it, data_position(self.engine),
                                      self.engine)

    def _rollback(self, reason, skip_data=False):
        """Coordinated rollback: every rank agrees to enter recovery,
        the tag is re-broadcast (ranks must not roll back to different
        tags), and the load + exact-sample data reseat is retried
        through kill-mid-rollback chaos up to ``max_recovery_attempts``.
        A ``corrupt`` verdict targets the last integrity-CLEAN published
        tag (a suspect tag holds the corruption it is fleeing) and skips
        the anomalous data window; every other reason targets the last
        published tag and replays."""
        self._drain_pending_commit()
        all_agree(True)     # recovery barrier: enter together or not at all
        from deepspeed_tpu.runtime.resilience.reshard import data_position

        pos_before = data_position(self.engine)
        corrupt = reason == KIND_CORRUPT
        tag = broadcast_tag(self.last_clean_tag if corrupt
                            else self.last_committed_tag)
        if tag is None:
            raise SupervisorGaveUp(
                f"persistent {reason} fault with NO "
                f"{'integrity-clean ' if corrupt else ''}committed tag to "
                f"roll back to — "
                + ("every committed tag was stamped inside the anomaly "
                   "window" if corrupt and self.last_committed_tag
                   else "commit cadence (checkpoint_every_steps) never "
                        "fired before the first failure"))
        inc = self._open_incident
        if inc is not None:
            inc["recovery"] = RECOVERY_ROLLBACK_SKIP if skip_data \
                else RECOVERY_ROLLBACK
            inc["tag"] = tag
        last_err = None
        for _attempt in range(self.config.max_recovery_attempts):
            try:
                chaos.point("before_rollback_load")
                _path, client = self.engine.load_checkpoint(
                    self.save_dir, tag=tag, elastic=True)
                if skip_data:
                    self._skip_and_reseat(pos_before)
                else:
                    self._reseat_data(client)
                break
            except chaos.ChaosInterrupt as e:
                # a kill landing mid-rollback: the committed tag on disk
                # is untouched (loads never mutate it) — pay a wall step
                # and retry the same recovery
                last_err = e
                self.wall_step += 1
                continue
        else:
            raise SupervisorGaveUp(
                f"rollback to {tag!r} failed "
                f"{self.config.max_recovery_attempts} times; last error: "
                f"{last_err}")
        self.rollbacks += 1
        self._strikes = 0
        self._backoff_until = 0
        if skip_data:
            self._rebase_commit_tracking(tag)
        self._instant("rollback", a0=self.wall_step)
        log_dist(f"supervisor: rolled back to committed tag {tag!r} "
                 f"({reason}{', data window skipped' if skip_data else ''}"
                 f") at wall step {self.wall_step}", ranks=[0],
                 level=logging.WARNING)

    def _rebase_commit_tracking(self, tag):
        """After a rollback-AND-SKIP the replayed steps train on
        DIFFERENT data (the window moved), so tags committed past the
        landing tag are stale — rebase the cadence so the replay
        re-commits them (the atomic tag-overwrite path makes that safe),
        and never leave a stale suspect tag as the rollback target."""
        gs = int(self.engine.global_steps)
        self.last_committed_tag = tag
        self.last_clean_tag = tag
        self._last_committed_step = gs
        self._last_saved_step = gs
        self._pending_published = None

    def _elastic_restart(self, dead, reason=KIND_HOST_LOST):
        """Lost (or quarantined) capacity: restart onto the surviving
        mesh.  The new world is the largest valid elastic world that
        fits the survivors, agreed fleet-wide (``min_int``); the new
        engine loads elastically and the data stream is fast-forwarded
        to the exact committed sample offset.  ``reason=KIND_CORRUPT``
        is the QUARANTINE rung: the dead list is a repeat-offender rank
        the integrity vote convicted — the restart loads the last
        integrity-CLEAN tag and skips the anomalous data window, same
        as rollback-and-skip."""
        w = self.wall_step
        corrupt = reason == KIND_CORRUPT
        self._drain_pending_commit()
        from deepspeed_tpu.runtime.resilience.reshard import data_position

        pos_before = data_position(self.engine)
        self._open(reason, w)
        inc = self._open_incident
        for h in self.hosts:
            if h.rank in dead:
                h.alive = False
                # the verdict was reached and is being acted on: only
                # now may the transport stop expecting beats and reap
                # what there is to reap (detection never bookkeeps)
                self.transport.mark_dead(h.rank)
        survivors = [h for h in self.hosts if h.alive]
        if self._elastic is None:
            raise SupervisorGaveUp(
                f"rank(s) {sorted(dead)} "
                f"{'quarantined' if corrupt else 'lost'} but elastic "
                f"restart is DISARMED (no elasticity config) — cannot "
                f"reshard onto {len(survivors)} survivors")
        if self.restarts >= self.config.max_restarts:
            raise SupervisorGaveUp(
                f"rank(s) {sorted(dead)} lost after {self.restarts} elastic "
                f"restarts (max_restarts={self.config.max_restarts})")
        _final, valid = self._elastic
        fits = [v for v in valid if v <= len(survivors)]
        if not fits:
            raise SupervisorGaveUp(
                f"no valid elastic world fits {len(survivors)} surviving "
                f"host(s) (valid: {valid})")
        new_world = min_int(max(fits))
        tag = broadcast_tag(self.last_clean_tag if corrupt
                            else self.last_committed_tag)
        if tag is None:
            raise SupervisorGaveUp(
                f"rank(s) {sorted(dead)} "
                f"{'quarantined' if corrupt else 'lost'} before any "
                f"{'integrity-clean ' if corrupt else ''}committed tag — "
                f"nothing to restart from")
        if inc is not None:
            inc.update({"kind": reason,
                        "recovery": RECOVERY_QUARANTINE if corrupt
                        else RECOVERY_RESTART,
                        "dead": sorted(dead), "tag": tag,
                        "world_from": self.world, "world_to": new_world,
                        "verdict_step": w})
        last_err = None
        for _attempt in range(self.config.max_recovery_attempts):
            try:
                chaos.point("before_restart_load")
                engine = self.engine_factory(new_world)
                init_it = self.data_factory(engine)
                engine.init_from_batch(next(init_it))
                _path, client = engine.load_checkpoint(
                    self.save_dir, tag=tag, elastic=True)
                break
            except chaos.ChaosInterrupt as e:
                # kill mid-elastic-restart: discard the half-built world
                # (its committed tag is untouched), pay a wall step, retry
                last_err = e
                self.wall_step += 1
                continue
        else:
            raise SupervisorGaveUp(
                f"elastic restart onto world {new_world} from {tag!r} "
                f"failed {self.config.max_recovery_attempts} times; last "
                f"error: {last_err}")
        old = self.engine
        self._attach(engine)
        # the restart instant rides the NEW engine's tracer: the old
        # engine's lane dies with it, and the survivor's exported trace
        # must narrate the incident that created it (a0 = verdict step)
        self._instant("elastic_restart", a0=w)
        if corrupt:
            self._instant("quarantine", a0=w)
            self._skip_and_reseat(pos_before)
            self._rebase_commit_tracking(tag)
        else:
            self._reseat_data(client)
        old.close_telemetry()       # release chaos observers/streams; the
        # dead-world engine is dropped for GC — its devices are "gone"
        self.hosts = survivors[:new_world]
        self.world = new_world
        self.restarts += 1
        self._strikes = 0
        self._backoff_until = 0
        # dp rank indices RENUMBER on the shrunken world: an offense
        # count keyed by the old index would pre-load whichever host
        # inherits it toward quarantine — the ledger keeps the history
        # (incidents record offense_counts at verdict time), the live
        # counter starts over
        self._offenses = {}
        log_dist(
            f"supervisor: elastic restart complete — world "
            f"{inc['world_from'] if inc else '?'} -> {new_world}, resumed "
            f"from {tag!r} at the exact committed sample offset", ranks=[0],
            level=logging.WARNING)

    def _reseat_live(self):
        """Fresh deterministic stream fast-forwarded to the LIVE
        engine's committed sample offset (retry-in-place: no checkpoint
        was loaded, the engine's own counters are the truth)."""
        from deepspeed_tpu.runtime.resilience.reshard import (data_position,
                                                              fast_forward)

        it = self.data_factory(self.engine)
        self.data_iter = fast_forward(it, data_position(self.engine),
                                      self.engine)

    def _reseat_data(self, client):
        """Fresh deterministic stream, fast-forwarded to the committed
        sample offset the loaded tag recorded — zero samples lost or
        replayed in the committed trajectory.  Loss history recorded
        past the tag was rolled back with the state, so it is pruned:
        ``loss_history`` is the COMMITTED trajectory."""
        from deepspeed_tpu.runtime.resilience.reshard import fast_forward

        gs = int(self.engine.global_steps)
        self.loss_history = [(g, l) for g, l in self.loss_history if g <= gs]
        self._history_floats = min(self._history_floats,
                                   len(self.loss_history))
        it = self.data_factory(self.engine)
        self.data_iter = fast_forward(it, client.get("data_position"),
                                      self.engine)

    # ------------------------------------------------------------------
    # commit + accounting
    # ------------------------------------------------------------------
    # device-held loss_history tail above this length gets folded to
    # floats (one batched device_get of long-COMPLETED steps, so it
    # never blocks on in-flight compute) — bounds live device buffers
    # for arbitrarily long runs
    _HISTORY_DEVICE_TAIL = 64

    def _note_committed(self, loss):
        gs = int(self.engine.global_steps)
        # the loss stays a DEVICE value: a float() here would block the
        # host on the step's device compute every tick, serializing the
        # steady-state loop (the per-iteration sync the host-sync bar
        # forbids) — committed_losses() materializes lazily, batched
        self.loss_history.append((gs, loss))
        if len(self.loss_history) - self._history_floats \
                >= self._HISTORY_DEVICE_TAIL:
            self._materialize_history()
        inc = self._open_incident
        if inc is not None:
            inc["recovered_step"] = self.wall_step
            inc["mttr_steps"] = self.wall_step - inc["fail_step"]
            inc.setdefault("recovery", RECOVERY_RETRY)
            self._open_incident = None
            self._instant("recovered", a0=self.wall_step)
            if self._tracer is not None:
                self._tracer.complete("downtime", self._lane_recovery,
                                      self._downtime_t0,
                                      a0=inc["mttr_steps"])
        self._maybe_commit(gs)

    def _maybe_commit(self, gs):
        every = self.config.checkpoint_every_steps
        if not self.armed or every <= 0 or gs % every \
                or gs <= self._last_saved_step:
            return
        # commit cadence follows the engine's resilience.async_commit
        # config (ROADMAP PR-12 follow-up, lifted restriction): a SYNC
        # commit is a rollback target the moment save returns; an ASYNC
        # one only once its foreground publish lands (on_commit_published
        # — the supervisor tracks only PUBLISHED tags, and recoveries
        # drain the pending seal first)
        mon = getattr(self.engine, "_integrity", None)
        clean = bool(mon.clean()) if mon is not None else True
        try:
            self.engine.save_checkpoint(self.save_dir)
        except Exception as e:  # lint: allow-broad-except — a failed
            # commit (disk full, kill mid-write) must not kill the run
            # the supervisor exists to keep alive: the atomic writer
            # guarantees no torn tag became visible, live state is
            # intact, so training continues and the NEXT cadence
            # boundary retries — the cost is a staler rollback target,
            # counted loudly in commit_failures
            self.commit_failures += 1
            logger.warning(
                f"supervisor: checkpoint commit at step {gs} failed "
                f"({type(e).__name__}: {e}) — training continues, "
                f"rollback target stays {self.last_committed_tag!r} "
                f"({self.commit_failures} commit failure(s) so far)")
            self._instant("commit_failed", a0=self.wall_step)
            return
        self._last_saved_step = gs
        tag = f"global_step{gs}"
        if callable(getattr(self.engine, "pending_commit", None)) \
                and self.engine.pending_commit():
            # async seal in flight: NOT a rollback target yet
            self._pending_published = {"tag": tag, "global_steps": gs,
                                       "integrity_clean": clean}
            return
        self._record_published(tag, gs, clean)

    def _record_published(self, tag, gs, clean):
        """A tag became durable-visible (sync save returned, or an async
        publish landed): it is now a rollback target; integrity-clean
        tags additionally become the corrupt rung's target."""
        self.last_committed_tag = tag
        self._last_committed_step = max(self._last_committed_step, int(gs))
        if clean:
            self.last_clean_tag = tag
        self._pending_published = None

    def on_commit_failed(self, exc):
        """Engine hook: an ASYNC commit's seal or publish failed at a
        step boundary.  Same contract as a failed synchronous commit —
        count it, keep the previous PUBLISHED tag as the rollback
        target, never kill (or roll back) the run over an IO failure."""
        self.commit_failures += 1
        pending = self._pending_published
        self._pending_published = None
        logger.warning(
            f"supervisor: async checkpoint commit"
            f"{' of ' + repr(pending['tag']) if pending else ''} failed at "
            f"the step boundary ({type(exc).__name__}: {exc}) — training "
            f"continues, rollback target stays "
            f"{self.last_committed_tag!r} ({self.commit_failures} commit "
            f"failure(s) so far)")
        self._instant("commit_failed", a0=self.wall_step)

    def on_commit_published(self, info):
        """Engine hook: an ASYNC checkpoint commit finished its
        foreground publish (rename + latest).  Only now does the tag
        become a rollback target — and its integrity stamp is the one
        fixed at COMMIT time (a window that opened after the snapshot
        does not taint it, and one that closed since does not clean
        it)."""
        tag = info.get("tag")
        gs = info.get("global_steps")
        if tag is None or gs is None:
            return
        if info.get("save_dir") != self.save_dir:
            # a user-driven save to some OTHER directory (an export, a
            # side snapshot) is not a recovery target: _rollback only
            # ever loads from self.save_dir, so recording this tag
            # would point the ladder at a tag that does not exist there
            return
        if int(gs) >= self._last_committed_step:
            self._record_published(str(tag), int(gs),
                                   bool(info.get("integrity_clean", True)))

    def _open(self, kind, w):
        """Open (or escalate) the current incident; instants + the
        downtime span anchor ride the ``recovery`` telemetry lane."""
        inc = self._open_incident
        if inc is None:
            inc = {"kind": kind, "fail_step": w}
            self._open_incident = inc
            self.incidents.append(inc)
            self._instant("failure", a0=w)
            if self._tracer is not None:
                self._downtime_t0 = self._tracer.begin()
        elif kind != KIND_PEER_STALL and inc["kind"] == KIND_PEER_STALL:
            inc["kind"] = kind      # stall escalated to a harder verdict

    def on_engine_step(self, engine):
        """Engine-side hook (every ``_observe_step_outcome``): surface
        restart-count/backoff ladder state in ``_last_metrics`` so the
        step stream carries recovery posture alongside loss scale."""
        m = engine._last_metrics
        if isinstance(m, dict):
            m = dict(m)
            m["recovery_restarts"] = self.restarts
            m["recovery_rollbacks"] = self.rollbacks
            m["recovery_retries"] = self.transient_retries
            m["recovery_backoff_steps"] = max(
                0, self._backoff_until - self.wall_step)
            engine._last_metrics = m

    def _materialize_history(self):
        """Fold device-held losses into plain floats with ONE batched
        ``device_get`` (the fetched steps completed long ago, so this
        does not block in-flight compute).  Runs amortized every
        ``_HISTORY_DEVICE_TAIL`` commits and at read time — a long run
        never pins more than the tail's worth of device buffers."""
        import jax

        vals = jax.device_get([l for _, l in self.loss_history])
        self.loss_history = [
            (g, v if v is None or isinstance(v, float) else float(v))
            for (g, _), v in zip(self.loss_history, vals)]
        self._history_floats = len(self.loss_history)

    def committed_losses(self):
        """The committed ``(global_step, float loss)`` trajectory,
        materialized HERE — never on the per-step hot path
        (``loss_history`` holds device values until folded)."""
        self._materialize_history()
        return list(self.loss_history)

    def report(self):
        """The ``recovery`` section of ``engine.telemetry_report()``:
        incident ledger, MTTR, downtime spans, and
        goodput-samples-per-wall-step (committed samples over EVERY wall
        step, blocked/backoff/recovery ticks included — the honest
        denominator, as in the PR-9 goodput accounting)."""
        mttrs = [i["mttr_steps"] for i in self.incidents
                 if i.get("mttr_steps") is not None]
        gs = int(self.engine.global_steps)
        batch = int(self.engine.train_batch_size())
        wall = max(1, self.wall_step)
        return {
            "armed": self.armed,
            "world": self.world,
            "transport": self.transport.describe(),
            "alive_hosts": sum(1 for h in self.hosts if h.alive),
            "restarts": self.restarts,
            "rollbacks": self.rollbacks,
            "commit_failures": self.commit_failures,
            "transient_retries": self.transient_retries,
            "strikes": self._strikes,
            "backoff_steps_remaining": max(
                0, self._backoff_until - self.wall_step),
            "wall_steps": self.wall_step,
            "committed_steps": gs,
            "committed_samples": gs * batch,
            "goodput_samples_per_wall_step": gs * batch / wall,
            # numerical integrity (ISSUE 13): skipped data is an honest
            # goodput cost — those samples were consumed from the stream
            # but never trained on, and the ledger says so
            "corrupt_verdicts": self.corrupt_verdicts,
            "quarantines": self.quarantines,
            "skipped_samples": self.skipped_samples,
            "offense_counts": dict(self._offenses),
            "last_clean_tag": self.last_clean_tag,
            "mttr_steps": {
                "mean": sum(mttrs) / len(mttrs) if mttrs else None,
                "max": max(mttrs) if mttrs else None,
                "closed_incidents": len(mttrs),
            },
            "downtime_spans": [
                (i["fail_step"], i.get("recovered_step"))
                for i in self.incidents],
            "downtime_wall_steps": sum(mttrs),
            "incidents": [dict(i) for i in self.incidents],
            "verdicts": [dict(v) for v in self.verdicts],
            "last_committed_tag": self.last_committed_tag,
        }
