"""Multi-host coordination primitives for checkpoint save/load.

One discipline, shared by every multi-process checkpoint phase: a rank
that fails must still reach the next collective — raising first would
leave peers wedged in a barrier with no timeout.  So errors are swallowed
locally, success flags are allgathered (the allgather is itself a
barrier), and all ranks agree on the outcome before anyone proceeds or
raises.  Both helpers are safe no-ops on single-process runs.
"""

# fixed-size buffer for broadcasting a checkpoint tag name across hosts
# (collectives need identical shapes everywhere); tags are also directory
# names, so NAME_MAX caps them at 255 bytes anyway — longer ones must be
# skipped by the caller rather than truncated mid-codepoint
TAG_BCAST_BYTES = 512


def all_agree(ok):
    """Allgather a local success flag; ``(agreed, n_failed)``.

    ``agreed`` is True iff EVERY process reported success.  Single
    process: ``(bool(ok), 0 or 1)`` with no collective.
    """
    import jax

    if jax.process_count() == 1:
        return bool(ok), 0 if ok else 1
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([bool(ok)], np.int32))
    return bool(int(np.min(flags))), int(len(flags) - np.sum(flags))


def any_flag(flag):
    """Allgather-OR of a local boolean; True when ANY process set it.

    The preemption counterpart of :func:`all_agree`: a SIGTERM (or chaos
    preempt trigger) may land on one host first, but the emergency save
    it forces is collective — every rank must enter it together, so the
    local flags are OR-combined at the step boundary.  Single process:
    passthrough with no collective.
    """
    import jax

    if jax.process_count() == 1:
        return bool(flag)
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([bool(flag)], np.int32))
    return bool(int(np.max(flags)))


def min_int(value):
    """Allgather an int and return the fleet-wide MINIMUM.

    The elastic-restart agreement primitive: after a dead verdict every
    surviving host computes the new world size from the peers IT can
    still see; the fleet must restart at the smallest world any survivor
    derived, or ranks would build incompatible meshes and wedge in the
    first collective.  Single process: passthrough with no collective.
    """
    import jax

    if jax.process_count() == 1:
        return int(value)
    import numpy as np
    from jax.experimental import multihost_utils

    vals = multihost_utils.process_allgather(
        np.asarray([int(value)], np.int64))
    return int(np.min(vals))


def gather_ints(arr):
    """Allgather an integer ndarray across host processes; returns the
    stacked ``[process_count, *arr.shape]`` table.

    The integrity vote's agreement primitive (ISSUE 13): every process
    folds its addressable replicas' checksums on device, then ALL
    processes enter this gather together — the all_agree discipline, so
    a corrupted rank can lose the vote without any host wedging a peer
    in a barrier.  Single process: ``arr[None]`` with no collective.
    """
    import jax
    import numpy as np

    arr = np.ascontiguousarray(np.asarray(arr, dtype=np.int64))
    if jax.process_count() == 1:
        return arr[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr))


def broadcast_tag(name):
    """Broadcast a tag name (or None) from process 0 to every host.

    Returns the tag string, or None when process 0 passed a falsy value
    (the 'no more candidates' sentinel).  Single process: passthrough.
    """
    import jax

    if jax.process_count() == 1:
        return name or None
    import numpy as np
    from jax.experimental import multihost_utils

    buf = np.zeros(TAG_BCAST_BYTES, np.uint8)
    raw = str(name or "").encode()
    buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    return np.asarray(out, np.uint8).tobytes().rstrip(b"\0").decode() \
        or None
