"""Training watchdog: overflow streaks, NaN losses, wall-clock stalls.

The loss scaler recovers from isolated overflows by halving; what it can't
recover from is a *streak* — scale pinned at ``min_scale`` with every step
skipped, or a NaN loss that no scale change fixes, or a step that simply
never finishes (hung collective, wedged host).  The watchdog turns those
into explicit events:

    wd = TrainingWatchdog(max_skipped_steps=20, max_nan_losses=3,
                          stall_timeout=600)
    wd.add_callback(lambda event: "abort")   # or "continue" to back off

Engines call ``observe_step`` after every optimizer step; an event whose
callbacks vote abort makes ``observe_step`` raise :class:`WatchdogAlarm`
*after* the engine has written an emergency checkpoint.  With no callbacks
the configured default action applies.
"""
import time
from typing import Any, NamedTuple

from deepspeed_tpu.utils.logging import logger

EVENT_OVERFLOW_STREAK = "overflow_streak"
EVENT_NAN_LOSS = "nan_loss"
EVENT_STALL = "stall"
EVENT_INTEGRITY = "silent_corruption"

ACTION_ABORT = "abort"
ACTION_CONTINUE = "continue"


class WatchdogEvent(NamedTuple):
    kind: str        # one of the EVENT_* names
    step: int        # global step when detected
    message: str
    details: Any     # dict of streak counters / timings


class WatchdogAlarm(RuntimeError):
    """Raised out of the training loop when an event's verdict is abort."""

    def __init__(self, event: WatchdogEvent):
        super().__init__(event.message)
        self.event = event


class GracefulPreemption(RuntimeError):
    """Raised out of the training loop AFTER a coordinated emergency
    checkpoint committed (or was skipped with a warning) in response to
    a preemption signal — engine.request_preemption(), an installed
    SIGTERM handler, or a chaos ``preempt_after_steps`` plan.  Catching
    it and exiting 0 is the expected shutdown path on preemptible pods;
    the run resumes elastically via load_checkpoint(auto_resume=True)."""

    def __init__(self, message, tag=None, save_dir=None):
        super().__init__(message)
        self.tag = tag
        self.save_dir = save_dir


# per-signal registry for chain_signal_handlers: ONE dispatcher per
# signal fans out to every registered callback, then to whatever
# non-deepspeed handler was installed before the first registration.
# Bound methods are held as WEAKREFS, so an engine rebuilt per elastic
# restart (or drained and dropped) is never pinned process-global by
# its old SIGTERM hook — dead callbacks silently fall out of the chain.
_SIGNAL_CHAINS = {}     # signum -> {"prev": handler, "cbs": [ref],
#                                    "dispatcher": handler}


def chain_signal_handlers(callback, signals=None):
    """Register ``callback`` on each signal WITHOUT dropping what was
    there: one dispatcher per signal invokes every registered callback
    (newest first), then the prior non-deepspeed Python-level handler.
    ``signal.signal`` is last-wins, so a process that hosts both a
    training engine and a serving engine — or any client SIGTERM hook —
    would silently lose every handler but the final one registered;
    chaining makes ``install_preemption_handler`` safe to call from
    multiple engines in one process.  Re-registering the same callback
    is a no-op (no double-fire), bound methods are weakly referenced
    (a dead engine's hook is dropped, not invoked), and non-callable
    prior dispositions (SIG_DFL/SIG_IGN) are never chained.  Returns
    the list of signal numbers installed.  Main thread only (a Python
    signal-handler constraint)."""
    import signal as signal_mod
    import weakref

    try:
        ref = weakref.WeakMethod(callback)
    except TypeError:
        # plain functions/lambdas: hold strongly (their lifetime is the
        # caller's business, and a lambda has no __self__ to outlive)
        def ref(_cb=callback):
            return _cb

    sigs = tuple(signals) if signals else (signal_mod.SIGTERM,)
    for s in sigs:
        ent = _SIGNAL_CHAINS.get(s)
        current = signal_mod.getsignal(s)
        if ent is None or current is not ent["dispatcher"]:
            # first registration, or someone installed their own handler
            # over our dispatcher since: chain THAT as the new tail, and
            # CARRY the already-registered callbacks into the new chain
            # (they would otherwise be lost with the overridden
            # dispatcher).  The old entry is emptied, not shared: if the
            # foreign handler chained our old dispatcher as ITS tail,
            # that dispatcher now fires only its own pre-us prev —
            # every callback still fires exactly once.
            carried = []
            if ent is not None:
                carried, ent["cbs"] = ent["cbs"], []
            ent = {"prev": current, "cbs": carried}

            def _dispatch(signum, frame, _ent=ent):
                for r in list(_ent["cbs"]):
                    cb = r()
                    if cb is not None:
                        cb()
                if callable(_ent["prev"]):
                    _ent["prev"](signum, frame)

            ent["dispatcher"] = _dispatch
            _SIGNAL_CHAINS[s] = ent
            signal_mod.signal(s, _dispatch)
        live = [r() for r in ent["cbs"]]
        ent["cbs"] = [r for r, cb in zip(ent["cbs"], live) if cb is not None]
        if callback not in [cb for cb in live if cb is not None]:
            ent["cbs"].insert(0, ref)       # newest first
    return list(sigs)


class TrainingWatchdog:
    """Streak/stall detector.  Thresholds of 0 disable that detector."""

    def __init__(self, max_skipped_steps=0, max_nan_losses=0,
                 stall_timeout=0.0, default_action=ACTION_ABORT,
                 clock=time.monotonic):
        self.max_skipped_steps = int(max_skipped_steps)
        self.max_nan_losses = int(max_nan_losses)
        self.stall_timeout = float(stall_timeout)
        self.default_action = default_action
        self._clock = clock
        self._callbacks = []
        self.consecutive_skips = 0
        self.consecutive_nans = 0
        # the stall clock arms on the first completed step (or an explicit
        # heartbeat()) — step 1 includes tracing + XLA compilation, which
        # would otherwise read as a stall on any big model
        self.last_progress_time = None
        self.events = []  # every event ever fired (tests/inspection)

    def add_callback(self, cb):
        """cb(event) -> 'abort' | 'continue' | None (None = default)."""
        self._callbacks.append(cb)
        return cb

    # -- observations ---------------------------------------------------
    def observe_step(self, step, loss=None, overflow=False):
        """Feed one completed optimizer step; fires any triggered events.

        Returns the list of fired events; raises WatchdogAlarm when the
        verdict for any of them is abort.
        """
        now = self._clock()
        fired = []
        if self.stall_timeout > 0 and self.last_progress_time is not None \
                and now - self.last_progress_time > self.stall_timeout:
            fired.append(WatchdogEvent(
                EVENT_STALL, step,
                f"step {step} took {now - self.last_progress_time:.1f}s "
                f"(stall_timeout={self.stall_timeout:g}s)",
                {"elapsed": now - self.last_progress_time}))
        self.last_progress_time = now

        self.consecutive_skips = self.consecutive_skips + 1 if overflow else 0
        if self.max_skipped_steps > 0 and \
                self.consecutive_skips >= self.max_skipped_steps:
            fired.append(WatchdogEvent(
                EVENT_OVERFLOW_STREAK, step,
                f"{self.consecutive_skips} consecutive overflow-skipped "
                f"steps — loss scale cannot recover",
                {"consecutive_skips": self.consecutive_skips}))

        # the finiteness check forces a host transfer of a device loss —
        # only pay for it when the detector can actually fire
        nan = self.max_nan_losses > 0 and loss is not None \
            and not _is_finite(loss)
        self.consecutive_nans = self.consecutive_nans + 1 if nan else 0
        if self.max_nan_losses > 0 and \
                self.consecutive_nans >= self.max_nan_losses:
            fired.append(WatchdogEvent(
                EVENT_NAN_LOSS, step,
                f"{self.consecutive_nans} consecutive non-finite losses",
                {"consecutive_nans": self.consecutive_nans,
                 "loss": None if loss is None else float(loss)}))

        self._dispatch(fired)
        return fired

    def observe_serving_step(self, step):
        """Serving-side analog of :meth:`observe_step`: stall detection
        only (serving has no loss scale or NaN-loss streaks — poisoned
        lanes are quarantined per request by the engine itself), with
        the same dispatch/abort semantics.  The inference engine calls
        it once per serving step, so a wedged decode dispatch or a
        chaos ``slow_serving_step`` trips the same stall machinery the
        training loop uses."""
        now = self._clock()
        fired = []
        if self.stall_timeout > 0 and self.last_progress_time is not None \
                and now - self.last_progress_time > self.stall_timeout:
            fired.append(WatchdogEvent(
                EVENT_STALL, step,
                f"serving step {step} took "
                f"{now - self.last_progress_time:.1f}s "
                f"(stall_timeout={self.stall_timeout:g}s)",
                {"elapsed": now - self.last_progress_time}))
        self.last_progress_time = now
        self._dispatch(fired)
        return fired

    def observe_integrity(self, step, verdict):
        """Feed a confirmed silent-corruption verdict from the integrity
        monitor (runtime/resilience/integrity.py) — the UNSUPERVISED
        escalation path: without a TrainingSupervisor there is no
        rollback ladder, so a corrupt verdict becomes a watchdog event
        with the usual abort/continue dispatch (abort still writes the
        engine's emergency checkpoint first — stamped integrity-suspect
        by the open anomaly window, so auto-resume prefers an older
        clean tag).  Supervised engines never call this: the supervisor
        owns the corrupt rung."""
        event = WatchdogEvent(
            EVENT_INTEGRITY, step,
            f"silent-corruption verdict at step {step} via "
            f"{verdict.get('source')}: "
            + (f"minority rank(s) {verdict.get('culprits')}"
               if verdict.get("culprits") else "no culprit (symmetric)"),
            dict(verdict))
        self._dispatch([event])
        return event

    def check_stall(self, step):
        """Poll for a stall without observing a step (e.g. from a monitor
        loop while train_batch blocks on a hung collective)."""
        now = self._clock()
        if self.last_progress_time is None:  # arm on first poll
            self.last_progress_time = now
            return None
        if self.stall_timeout <= 0 or \
                now - self.last_progress_time <= self.stall_timeout:
            return None
        event = WatchdogEvent(
            EVENT_STALL, step,
            f"no step completed for {now - self.last_progress_time:.1f}s "
            f"(stall_timeout={self.stall_timeout:g}s)",
            {"elapsed": now - self.last_progress_time})
        # re-arm before dispatch: a 'continue' verdict with a tight poll
        # loop must fire once per stall_timeout window, not once per poll
        self.last_progress_time = now
        self._dispatch([event])
        return event

    def heartbeat(self):
        """Mark forward progress without a full step observation."""
        self.last_progress_time = self._clock()

    # -- dispatch -------------------------------------------------------
    def _dispatch(self, fired):
        abort_event = None
        for event in fired:
            self.events.append(event)
            logger.warning(f"watchdog: {event.kind} at step {event.step}: "
                           f"{event.message}")
            # fail-safe: any single abort vote wins, no matter what other
            # callbacks return or in which order they were registered
            verdict = None
            for cb in self._callbacks:
                got = cb(event)
                if got == ACTION_ABORT:
                    verdict = ACTION_ABORT
                elif got == ACTION_CONTINUE and verdict is None:
                    verdict = ACTION_CONTINUE
            if verdict is None:
                verdict = self.default_action
            if verdict == ACTION_ABORT:
                # when a host-local stall and a globally-derived streak
                # (overflow/NaN, reduced identically on every host) abort
                # in the same dispatch, the alarm must carry the global
                # kind: engines skip the collective emergency save for
                # stall verdicts, and hosts disagreeing on the kind would
                # leave some in that save's barrier and some not
                if abort_event is None or (abort_event.kind == EVENT_STALL
                                           and event.kind != EVENT_STALL):
                    abort_event = event
            elif verdict == ACTION_CONTINUE:
                # back off: reset the streak that fired so the event
                # doesn't re-fire every subsequent step
                if event.kind == EVENT_OVERFLOW_STREAK:
                    self.consecutive_skips = 0
                elif event.kind == EVENT_NAN_LOSS:
                    self.consecutive_nans = 0
        if abort_event is not None:
            raise WatchdogAlarm(abort_event)


def _is_finite(x):
    import math

    try:
        return math.isfinite(float(x))
    except (TypeError, ValueError):
        return True
