"""Numerical-integrity defense: silent-corruption detection + vote.

PR 11/12 made LOUD faults (crashes, stalls, dead ranks) routine events,
but every detector in the stack keys on exceptions, heartbeats or
non-finite values — a flipped mantissa bit, a corrupted optimizer
shard, or a PaLM-style loss spike produces finite-but-WRONG numbers
that sail straight past the supervisor and get committed into
checkpoints.  This module makes those faults mechanically detectable:

- **Sentinels** — device-side step statistics (loss, global grad norm,
  update/param-norm ratio) computed INSIDE the existing step jits and
  riding the existing batched per-step fetch (no new host syncs — the
  hot-path lint bar applies), classified host-side by an EMA/z-score
  window.  Loss-scale overflow skips are excluded from the statistics:
  an overflow is the scaler doing its job, not corruption.
- **Cross-replica vote** — after the optimizer step, dp ranks hold
  replicated state (params, and fp32 master under stages <= 2); a
  cheap per-leaf XOR checksum of the raw bits is folded ON DEVICE
  under ``shard_map`` and ``all_gather``-agreed, so a corrupted rank
  is identified by *minority vote* — one small fetch per vote, no rank
  wedges (the collective is entered uniformly by every rank,
  rank-branch-collective clean).  A **duplicate-compute sentinel
  micro-step** (the same micro-batch replayed on every rank with the
  same rng, gradients checksum-compared) covers the pre-exchange
  window where per-rank gradients are legitimately different and
  replicated-state redundancy does not exist yet.
- **Verdicts** — the :class:`IntegrityMonitor` combines both into a
  ``corrupt`` verdict for the supervisor's response ladder (between
  ``transient`` and ``dead``): a vote minority names the culprit
  rank(s); a 2-way tie REFUSES a rank verdict (no quorum) and
  escalates to rollback; a persistent sentinel anomaly with a
  unanimous vote is symmetric corruption (bad data window / corrupted
  sharded state) — rollback-and-skip with no culprit.  An anomaly
  that clears before confirmation is counted as a false positive.

Physics honesty: the vote can only localize corruption in REPLICATED
state — ZeRO-sharded leaves have no redundancy, so a flipped bit in a
sharded optimizer shard propagates symmetrically through the parameter
all-gather and is caught by the sentinels (and rolled back), not
attributed to a rank.  That boundary is exactly why the sentinels and
the duplicate-compute check exist alongside the vote.

Disarmed discipline: ``engine._arm_integrity`` warns naming blockers
(dp == 1 -> sentinels-only, no vote; stage 3 / offload / 1-bit wire /
PipelineEngine -> named DISARMs); a disarmed run is bit-identical at
zero extra compiles (tier-1 pin).
"""
from collections import Counter
from dataclasses import dataclass

from deepspeed_tpu.utils.logging import logger

# verdict sources (the evidence class behind a corrupt verdict)
SOURCE_STATE_VOTE = "state-vote"
SOURCE_DUP_CHECK = "dup-check"
SOURCE_SENTINEL = "sentinel"

SENTINEL_NAMES = ("loss", "grad_norm", "update_ratio")


@dataclass(frozen=True)
class IntegrityConfig:
    """Detection windows + vote cadences (see the ``resilience.
    integrity`` config-block twins in runtime/constants.py)."""
    window: int = 32                 # EMA window (steps) for the z-score
    z_threshold: float = 6.0         # |z| past this = anomalous sentinel
    min_history: int = 4             # steps of stats before z fires
    confirm_steps: int = 2           # anomalous steps before a
    #                                  sentinel-only (no-culprit) verdict
    clear_steps: int = 2             # normal steps that close an
    #                                  unconfirmed anomaly = false positive
    vote_every_steps: int = 16       # background vote cadence (0 = only
    #                                  on sentinel anomaly)
    dup_check_every_steps: int = 0   # duplicate-compute cadence (0 = off)
    quarantine_after: int = 2        # corrupt verdicts on one rank before
    #                                  the supervisor quarantines it

    @staticmethod
    def from_resilience(res):
        return IntegrityConfig(
            window=res.integrity_window,
            z_threshold=res.integrity_z_threshold,
            min_history=res.integrity_min_history,
            confirm_steps=res.integrity_confirm_steps,
            clear_steps=res.integrity_clear_steps,
            vote_every_steps=res.integrity_vote_every_steps,
            dup_check_every_steps=res.integrity_dup_check_every_steps,
            quarantine_after=res.integrity_quarantine_after)


# ---------------------------------------------------------------------------
# digest classification (pure host — the vote's counting rule)
# ---------------------------------------------------------------------------

def classify_digests(rows):
    """Majority/minority classification of per-rank digest rows.

    ``rows``: one digest vector per dp rank (any hashable-convertible
    sequence).  Returns a dict:

    - ``unanimous``: every rank agrees;
    - ``minority``: ranks whose digests differ from the STRICT majority
      (empty when unanimous or tied);
    - ``tie``: no strict majority exists (e.g. a 1-1 or 2-2 split) — the
      vote REFUSES a rank verdict; the caller escalates to rollback.
    """
    keyed = [tuple(int(x) for x in r) for r in rows]
    counts = Counter(keyed)
    if len(counts) == 1:
        return {"unanimous": True, "minority": [], "tie": False}
    ordered = counts.most_common()
    if len(ordered) > 1 and ordered[0][1] == ordered[1][1]:
        return {"unanimous": False, "minority": [], "tie": True}
    majority = ordered[0][0]
    minority = [i for i, k in enumerate(keyed) if k != majority]
    return {"unanimous": False, "minority": minority, "tie": False}


# ---------------------------------------------------------------------------
# device-side checksum machinery
# ---------------------------------------------------------------------------

def _fold_words(x):
    """XOR-fold a leaf's raw bits to ONE uint32 word (single-bit-flip
    exact: any one flipped bit flips the digest).  Works for the dtypes a
    TrainState carries: 4-byte floats bitcast, sub-4-byte floats bitcast
    to their word size then widened, ints/bools value-cast (mod 2^32)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    flat = x.ravel()
    if flat.dtype == jnp.float32:
        w = jax.lax.bitcast_convert_type(flat, jnp.uint32)
    elif flat.dtype in (jnp.float16, jnp.bfloat16):
        w = jax.lax.bitcast_convert_type(flat, jnp.uint16).astype(jnp.uint32)
    elif jnp.issubdtype(flat.dtype, jnp.floating):
        # exotic widths (f64/f8 never reach TrainState today): value-cast
        # through f32 — deterministic, equal-on-equal, which is all the
        # cross-rank comparison needs
        w = jax.lax.bitcast_convert_type(flat.astype(jnp.float32),
                                         jnp.uint32)
    else:
        w = flat.astype(jnp.uint32)
    return jax.lax.reduce(w, np.uint32(0), jax.lax.bitwise_xor, (0,))


def _manual_only_spec(sharding):
    """Drop every non-'data' axis from a NamedSharding's spec (the
    partial-auto shard_map idiom: only manual axes may be named in
    in_specs; GSPMD keeps TP/pipe placement implicitly)."""
    from jax.sharding import PartitionSpec as P

    def keep(axis):
        if axis is None:
            return None
        axes = axis if isinstance(axis, tuple) else (axis,)
        kept = tuple(a for a in axes if a == "data")
        if not kept:
            return None
        return kept if len(kept) > 1 else kept[0]

    return P(*(keep(a) for a in sharding.spec))


def _spec_has_data(spec):
    for axis in spec:
        axes = axis if isinstance(axis, tuple) else (axis,)
        if axis is not None and "data" in axes:
            return True
    return False


def replicated_vote_leaves(engine):
    """(leaf_arrays, in_specs, names) of the live TrainState leaves that
    are REPLICATED over the data axis — the redundancy the cross-replica
    vote exploits.  ZeRO-sharded leaves (accum/opt under stage 2, params
    under stage 3) are excluded: they have no replica to disagree with."""
    import jax

    state, sh = engine.state, engine._shardings
    leaves = []
    specs = []
    names = []
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    sh_flat = jax.tree_util.tree_leaves(sh)
    assert len(flat) == len(sh_flat)
    for (path, leaf), sharding in zip(flat, sh_flat):
        if _spec_has_data(sharding.spec):
            continue
        leaves.append(leaf)
        specs.append(_manual_only_spec(sharding))
        names.append(jax.tree_util.keystr(path))
    return leaves, specs, names


def build_vote_jit(engine, specs):
    """The per-rank state-checksum collective: each dp rank XOR-folds its
    LOCAL copy of every replicated leaf, then ``all_gather`` agrees the
    digest table — [dp, nleaves] uint32, identical on every rank after
    the gather.  Entered uniformly by every rank (no rank-conditioned
    branch touches the collective: rank-branch-collective clean)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = engine.mesh

    def vote(leaves):
        digest = jnp.stack([_fold_words(l) for l in leaves])
        return jax.lax.all_gather(digest, "data")

    return jax.jit(jax.shard_map(
        vote, mesh=mesh, in_specs=(tuple(specs),), out_specs=P(),
        axis_names={"data"}, check_vma=False))


def gathered_vote_leaves(engine):
    """Stage-3 vote census: the replicated leaves (folded locally, same
    as :func:`replicated_vote_leaves`) PLUS the ZeRO-sharded PARAM
    leaves, which each rank will all_gather-assemble inside the vote jit
    and fold its OWN assembled copy of.  Returns ``(leaves, in_specs,
    names, gather_flags)``.

    What the gathered digest can and cannot see: every rank folds the
    same logical array, so a shard corrupted AT REST assembles
    identically everywhere — unanimous digests, invisible here (the
    sentinels own that case, exactly as the stage-2 exclusion argued).
    What DOES split the table is asymmetric divergence on the gather
    path itself — a rank whose interconnect/HBM read corrupts during
    assembly folds different bits than its peers, which is the
    corruption mode a stage-3 forward gather feeds straight into the
    matmuls.  Sharded optimizer moments stay excluded (same rationale,
    4x the gathered bytes for no added coverage)."""
    import jax

    leaves, specs, names = replicated_vote_leaves(engine)
    gather_flags = [False] * len(leaves)
    flat, _ = jax.tree_util.tree_flatten_with_path(engine.state.params)
    sh_flat = jax.tree_util.tree_leaves(engine._shardings.params)
    assert len(flat) == len(sh_flat)
    for (path, leaf), sharding in zip(flat, sh_flat):
        if not _spec_has_data(sharding.spec):
            continue  # replicated params are already in the local set
        leaves.append(leaf)
        specs.append(_manual_only_spec(sharding))
        names.append("params" + jax.tree_util.keystr(path) + " [gathered]")
        gather_flags.append(True)
    return leaves, specs, names, gather_flags


def build_gathered_vote_jit(engine, specs, gather_flags):
    """Stage-3 variant of :func:`build_vote_jit`: sharded param leaves
    are ``all_gather``-assembled over 'data' INSIDE the shard_map, then
    every rank XOR-folds the copy it assembled — per-rank digests of the
    full weights, agreed by the same trailing digest all_gather.  The
    assembly transient peaks at one full leaf per gather (the same
    working set a stage-3 forward gather holds), which is why this jit
    lives on the cadence path and never on the step path.  Entered
    uniformly by every rank (rank-branch-collective clean)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = engine.mesh
    flags = tuple(bool(f) for f in gather_flags)

    def vote(leaves):
        folded = []
        for leaf, gathered in zip(leaves, flags):
            if gathered:
                leaf = jax.lax.all_gather(leaf, "data")
            folded.append(_fold_words(leaf))
        digest = jnp.stack(folded)
        return jax.lax.all_gather(digest, "data")

    return jax.jit(jax.shard_map(
        vote, mesh=mesh, in_specs=(tuple(specs),), out_specs=P(),
        axis_names={"data"}, check_vma=False))


def state_vote(engine):
    """Run the cross-replica state vote; returns the classification dict
    of :func:`classify_digests` plus the raw digest table.  ONE
    straight-line device fetch per vote (cadence path, never per-step).

    Multi-host runs additionally fold the in-process digest table
    through ``coordination.gather_ints`` (an agreement collective every
    process enters — the all_agree discipline); single-process runs pass
    through."""
    import jax
    import numpy as np

    from deepspeed_tpu.runtime.resilience.coordination import gather_ints

    mon = engine._integrity
    if mon._vote_jit is None:
        if mon.vote_gathered:
            leaves, specs, names, flags = gathered_vote_leaves(engine)
            mon._vote_jit = build_gathered_vote_jit(engine, specs, flags)
        else:
            leaves, specs, names = replicated_vote_leaves(engine)
            mon._vote_jit = build_vote_jit(engine, specs)
        mon._vote_leaf_names = names
    if mon.vote_gathered:
        leaves = gathered_vote_leaves(engine)[0]
    else:
        leaves, _specs, _names = replicated_vote_leaves(engine)
    with jax.set_mesh(engine.mesh):
        table = mon._vote_jit(tuple(leaves))
    rows = np.asarray(jax.device_get(table), dtype=np.int64)
    rows = _agree_table(rows, gather_ints)
    out = classify_digests(rows)
    out["digests"] = rows
    return out


def _agree_table(rows, gather_ints):
    """Every host enters the digest agreement together (the device
    all_gather already made the table fleet-global, so peers must hold
    IDENTICAL copies); a host whose fetched copy disagrees is itself
    evidence of corruption on the host path and is logged loudly."""
    tables = gather_ints(rows)
    if tables.shape[0] > 1 and not (tables == tables[0]).all():
        logger.warning(
            "integrity: host processes fetched DIFFERENT copies of the "
            "replicated digest table — host-path corruption; proceeding "
            "with process 0's copy")
    return tables[0]


def build_dup_jit(engine, param_specs):
    """The duplicate-compute sentinel micro-step: every dp rank replays
    the SAME micro-batch with the SAME rng (no axis_index folding), so
    healthy ranks produce bit-identical gradients; the per-rank gradient
    checksums are all_gather-agreed like the state vote.  This is the
    pre-exchange cover: gradients on real data are legitimately
    different per rank, so only a replayed-identical micro can be
    checksum-compared."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = engine.mesh
    model = engine.module

    def dup(params, batch, rng):
        def loss_fn(p):
            loss, _ = model.loss(p, batch, rng, train=False)
            return loss.astype(jnp.float32)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        digest = jnp.stack([_fold_words(g) for g in
                            jax.tree_util.tree_leaves(grads)]
                           + [_fold_words(loss)])
        return jax.lax.all_gather(digest, "data")

    return jax.jit(jax.shard_map(
        dup, mesh=mesh, in_specs=(param_specs, P(), P()), out_specs=P(),
        axis_names={"data"}, check_vma=False))


def dup_check(engine):
    """Run the duplicate-compute check on the cached last micro-batch;
    returns the classification dict (or None when no micro has been
    seen yet).  One straight-line device fetch per check."""
    import jax
    import numpy as np

    from deepspeed_tpu.runtime.resilience.coordination import gather_ints

    mon = engine._integrity
    micro = mon._last_micro
    if micro is None:
        return None
    if mon._dup_jit is None:
        param_sh = engine._shardings.params
        specs = jax.tree_util.tree_map(_manual_only_spec, param_sh)
        mon._dup_jit = build_dup_jit(engine, specs)
    import numpy as onp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # the duplicate micro is REPLICATED — every rank replays the same
    # rows (the whole point: healthy ranks must produce identical bits)
    rep = NamedSharding(engine.mesh, P())
    batch_rep = jax.tree_util.tree_map(
        lambda x: jax.device_put(onp.asarray(x), rep), micro)
    with jax.set_mesh(engine.mesh):
        table = mon._dup_jit(engine.state.params, batch_rep,
                             engine.state.rng)
    rows = np.asarray(jax.device_get(table), dtype=np.int64)
    rows = _agree_table(rows, gather_ints)
    out = classify_digests(rows)
    out["digests"] = rows
    return out


# ---------------------------------------------------------------------------
# chaos fault materialization (test-only; no-op without an armed plan)
# ---------------------------------------------------------------------------

def build_flip_jit(engine, spec):
    """One-shot bit-flipper for ONE state leaf: where
    ``axis_index('data') == rank``, XOR one bit of one element of that
    rank's LOCAL copy/shard.  For replicated leaves this produces the
    physically-divergent "replicated" array that IS silent replica
    corruption (out_specs still claims replication — the lie under
    test); for sharded leaves it corrupts the one logical shard."""
    import jax
    import jax.numpy as jnp

    mesh = engine.mesh

    def flip(x, rank, element, mask):
        idx = jax.lax.axis_index("data")
        words = jax.lax.bitcast_convert_type(
            x.ravel().astype(jnp.float32), jnp.uint32)
        flipped = words.at[element].set(words[element] ^ mask)
        y = jax.lax.bitcast_convert_type(flipped, jnp.float32) \
            .reshape(x.shape).astype(x.dtype)
        return jnp.where(idx == rank, y, x)

    from jax.sharding import PartitionSpec as P

    return jax.jit(jax.shard_map(
        flip, mesh=mesh, in_specs=(spec, P(), P(), P()), out_specs=spec,
        axis_names={"data"}, check_vma=False))


def _flip_state_leaf(engine, tree_name, rank, leaf, element, bit):
    """Apply one armed bit flip to ``engine.state.<tree_name>`` leaf
    ``leaf`` (flatten order), element ``element``, bit ``bit`` of the
    fp32 word, on dp rank ``rank`` only."""
    import jax
    import numpy as np

    state = engine.state
    tree = getattr(state, tree_name)
    sh_tree = getattr(engine._shardings, tree_name)
    flat, treedef = jax.tree_util.tree_flatten(tree)
    sh_flat = jax.tree_util.tree_leaves(sh_tree)
    if not (0 <= leaf < len(flat)):
        logger.warning(f"chaos flip_bit: leaf {leaf} out of range for "
                       f"state.{tree_name} ({len(flat)} leaves); not "
                       f"injected")
        return False
    spec = _manual_only_spec(sh_flat[leaf])
    cache = getattr(engine, "_integrity_flip_jits", None)
    if cache is None:
        cache = engine._integrity_flip_jits = {}
    key = (tree_name, leaf)
    if key not in cache:
        cache[key] = build_flip_jit(engine, spec)
    with jax.set_mesh(engine.mesh):
        new_leaf = cache[key](flat[leaf], np.int32(rank), np.int32(element),
                              np.uint32(1 << bit))
    flat = list(flat)
    flat[leaf] = new_leaf
    new_tree = jax.tree_util.tree_unflatten(treedef, flat)
    setattr_kwargs = {tree_name: new_tree}
    engine.state = state._replace(**setattr_kwargs)
    logger.warning(f"chaos: flipped bit {bit} of state.{tree_name} leaf "
                   f"{leaf} element {element} on dp rank {rank} at step "
                   f"{engine.global_steps}")
    return True


def apply_chaos_faults(engine):
    """Materialize armed silent-corruption faults on the live state at a
    step boundary (called by ``_observe_step_outcome``; no-op without an
    armed plan).  PipelineEngine / pre-state engines are skipped: the
    injectors target the base engine's TrainState."""
    from deepspeed_tpu.runtime.resilience import chaos

    state = getattr(engine, "state", None)
    if state is None or not hasattr(state, "params") \
            or getattr(engine, "_shardings", None) is None:
        return
    for target, rank, leaf, element, bit in \
            chaos.consume_bit_flips(engine.global_steps):
        tree_name = "opt_state" if target == "opt" else "params"
        _flip_state_leaf(engine, tree_name, rank, leaf, element, bit)


# ---------------------------------------------------------------------------
# host-side monitor
# ---------------------------------------------------------------------------

class SentinelStat:
    """EMA mean/variance tracker with a z-score read — one per sentinel.
    Anomalous samples are NOT folded in (a spike must not drag the mean
    toward itself and mask a follow-on spike).

    The z denominator has a RELATIVE floor (5% of |mean|): healthy
    training trends smoothly, so the raw EMA std can collapse toward
    zero and turn ordinary early-run drift into a 30-sigma "anomaly".
    With the floor, firing at z_threshold=6 requires at least a ~30%
    jump — far under any real corruption spike (a flipped exponent bit
    moves these statistics by orders of magnitude), far over drift."""

    _REL_STD_FLOOR = 0.05

    def __init__(self, window):
        self.alpha = 2.0 / (max(2, int(window)) + 1.0)
        self.mean = 0.0
        self.var = 0.0
        self.count = 0

    def z(self, x):
        import math

        if not math.isfinite(x):
            return float("inf")
        if self.count == 0:
            return 0.0
        std = math.sqrt(max(self.var, 1e-24))
        floor = self._REL_STD_FLOOR * max(abs(self.mean), 1e-12)
        return (x - self.mean) / max(std, floor)

    def update(self, x):
        import math

        if not math.isfinite(x):
            return
        if self.count == 0:
            self.mean = x
        else:
            delta = x - self.mean
            self.mean += self.alpha * delta
            self.var = (1.0 - self.alpha) * (self.var
                                             + self.alpha * delta * delta)
        self.count += 1


class IntegrityMonitor:
    """Host-side brain of the integrity defense.

    The engine feeds it once per optimizer step (``observe_step``, the
    values riding the existing batched fetch); the supervisor drives
    ``decide`` after each committed step and calls ``resolve`` once a
    recovery lands.  Everything here is pure host bookkeeping — device
    work happens only inside the cadence-gated vote/dup-check jits."""

    # the DISARMED warnings for these flags live in the one place that
    # decides them — engine._arm_integrity names every blocker; this
    # constructor just records the outcome
    # graftlint: disable=disarmed-discipline
    def __init__(self, config, dp, sentinels_armed=True, vote_armed=True,
                 dup_armed=False, vote_gathered=False, tracer=None):
        self.config = config
        self.dp = int(dp)
        self.sentinels_armed = bool(sentinels_armed)
        self.vote_armed = bool(vote_armed)
        self.dup_armed = bool(dup_armed)
        self.vote_gathered = bool(vote_gathered)
        self.stats = {n: SentinelStat(config.window)
                      for n in SENTINEL_NAMES}
        self.anomaly_step = None      # first anomalous step of open window
        self.anomaly_streak = 0
        self.normal_streak = 0
        self.anomalies = 0
        self.false_positives = 0
        self.overflow_skips = 0
        self.votes = 0
        self.dup_checks = 0
        self.verdicts = []            # verdict dicts handed to the ladder
        self.detection_latencies = []
        self.last_observed_step = 0
        self._verdict_latch = False   # one verdict per incident until
        #                               resolve() closes it
        self._last_micro = None       # host micro cached for dup_check
        self._vote_jit = None
        self._dup_jit = None
        self._vote_leaf_names = None
        self._tracer = tracer
        self._lane = 0
        if tracer is not None:
            self._lane = tracer.lane("integrity")
            for name in ("anomaly", "vote", "dup_check", "verdict",
                         "false_positive", "overflow_skip_excluded"):
                tracer.intern(name, args=("step",))
            tracer.intern("detection_latency", args=("steps",))

    # -- engine-side feeds ----------------------------------------------
    def note_micro(self, micro):
        """Cache (a host reference to) the step's first micro-batch for
        the duplicate-compute check.  O(1) — no copy, no device work."""
        if self.dup_armed:
            self._last_micro = micro

    def _instant(self, name, a0=0):
        if self._tracer is not None:
            self._tracer.instant(name, self._lane, a0=int(a0))

    def observe_step(self, step, loss=None, grad_norm=None,
                     update_ratio=None, overflow=False):
        """Classify one completed optimizer step's sentinel values.

        Returns ``"overflow-skip"`` (excluded from statistics — the loss
        scaler legitimately skipped), ``"warmup"`` (not enough history),
        ``"anomaly"`` or ``"ok"``.  Anomalous samples never update the
        EMA window."""
        self.last_observed_step = int(step)
        if not self.sentinels_armed:
            return "ok"
        if overflow:
            # a loss-scale overflow skip: loss/grad stats of a skipped
            # step describe the SCALER's probe, not the model — excluded,
            # and explicitly distinguishable from silent corruption
            self.overflow_skips += 1
            self._instant("overflow_skip_excluded", a0=step)
            return "overflow-skip"
        samples = {"loss": loss, "grad_norm": grad_norm,
                   "update_ratio": update_ratio}
        import math

        ready = all(self.stats[n].count >= self.config.min_history
                    for n, v in samples.items() if v is not None)
        anomalous = any(v is not None and not math.isfinite(v)
                        for v in samples.values())
        zs = {}
        if ready and not anomalous:
            for n, v in samples.items():
                if v is None:
                    continue
                zs[n] = self.stats[n].z(v)
            # ONE-SIDED: corruption blows these statistics UP (loss
            # spike, gradient blow-up, oversized update); downward moves
            # are healthy training converging and must never fire
            anomalous = any(z > self.config.z_threshold
                            for z in zs.values())
        if anomalous:
            if self.anomaly_step is None:
                self.anomaly_step = int(step)
                self.anomalies += 1
                self._instant("anomaly", a0=step)
                logger.warning(
                    f"integrity: sentinel anomaly opened at step {step} "
                    f"(z-scores {({n: round(z, 1) for n, z in zs.items()})}"
                    f", threshold {self.config.z_threshold:g})")
            self.anomaly_streak += 1
            self.normal_streak = 0
            return "anomaly"
        for n, v in samples.items():
            if v is not None:
                self.stats[n].update(v)
        if self.anomaly_step is not None:
            self.normal_streak += 1
        return "ok" if ready else "warmup"

    # -- supervisor-side decisions --------------------------------------
    def _vote_now(self, engine, step):
        self.votes += 1
        self._instant("vote", a0=step)
        return state_vote(engine)

    def _dup_now(self, engine, step):
        self.dup_checks += 1
        self._instant("dup_check", a0=step)
        return dup_check(engine)

    def decide(self, engine, wall_step):
        """Combine sentinel state + vote evidence into at most one
        ``corrupt`` verdict per incident.  Returns None (healthy /
        still gathering evidence) or a verdict dict:
        ``{"verdict": "corrupt", "culprits": [ranks], "source": ...,
        "step", "anomaly_step", "latency_steps", "tie"}``."""
        if self._verdict_latch:
            return None
        step = int(engine.global_steps)
        cfg = self.config
        anomaly = self.anomaly_step is not None
        vote = None
        if self.vote_armed and (
                anomaly or (cfg.vote_every_steps
                            and step % cfg.vote_every_steps == 0)):
            vote = self._vote_now(engine, step)
        if vote is not None and vote["minority"]:
            return self._verdict(step, vote["minority"], SOURCE_STATE_VOTE)
        dup = None
        if self.dup_armed and (
                anomaly or (cfg.dup_check_every_steps
                            and step % cfg.dup_check_every_steps == 0)):
            dup = self._dup_now(engine, step)
        if dup is not None and dup["minority"]:
            return self._verdict(step, dup["minority"], SOURCE_DUP_CHECK)
        if vote is not None and vote["tie"]:
            # replicas disagree but no strict majority exists: the vote
            # REFUSES a rank verdict — escalate to rollback, quarantine
            # nobody (dp=2 always lands here when replicas split)
            return self._verdict(step, [], SOURCE_STATE_VOTE, tie=True)
        if dup is not None and dup["tie"]:
            return self._verdict(step, [], SOURCE_DUP_CHECK, tie=True)
        if not anomaly:
            return None
        if self.anomaly_streak >= cfg.confirm_steps:
            # persistent anomaly, unanimous replicas: symmetric silent
            # corruption (bad data window / sharded-state corruption) —
            # rollback-and-skip with no culprit
            return self._verdict(step, [], SOURCE_SENTINEL)
        if self.normal_streak >= cfg.clear_steps:
            self.false_positives += 1
            self._instant("false_positive", a0=step)
            logger.warning(
                f"integrity: anomaly opened at step {self.anomaly_step} "
                f"cleared on its own after {self.normal_streak} normal "
                f"step(s) — counted as a false positive (no recovery)")
            self._reset_window()
        return None

    def _verdict(self, step, culprits, source, tie=False):
        opened = self.anomaly_step if self.anomaly_step is not None \
            else step
        latency = max(0, int(step) - int(opened))
        self.detection_latencies.append(latency)
        self._verdict_latch = True
        verdict = {"verdict": "corrupt", "culprits": sorted(culprits),
                   "source": source, "step": int(step),
                   "anomaly_step": int(opened),
                   "latency_steps": latency, "tie": bool(tie)}
        self.verdicts.append(dict(verdict))
        self._instant("verdict", a0=step)
        self._instant("detection_latency", a0=latency)
        logger.warning(
            f"integrity: CORRUPT verdict at step {step} via {source} — "
            + (f"minority rank(s) {sorted(culprits)}" if culprits else
               ("2-way tie: no quorum, escalating to rollback" if tie
                else "no culprit (symmetric anomaly)"))
            + f"; detection latency {latency} step(s)")
        return verdict

    def resolve(self, recovered=True):
        """Close the open incident after the supervisor's recovery (or
        explicit operator dismissal) — re-arms verdicts."""
        self._reset_window()
        self._verdict_latch = False

    def _reset_window(self):
        self.anomaly_step = None
        self.anomaly_streak = 0
        self.normal_streak = 0

    def clean(self):
        """True when no anomaly window is open — the ``integrity_clean``
        stamp a checkpoint commit records in its tag manifest."""
        return self.anomaly_step is None and not self._verdict_latch

    def report(self):
        """The ``integrity`` section of ``engine.telemetry_report()``."""
        lat = self.detection_latencies
        return {
            "armed": True,
            "sentinels_armed": self.sentinels_armed,
            "vote_armed": self.vote_armed,
            "vote_mode": ("gathered" if self.vote_gathered
                          else "replicated") if self.vote_armed else None,
            "dup_check_armed": self.dup_armed,
            "dp": self.dp,
            "anomalies": self.anomalies,
            "false_positives": self.false_positives,
            "overflow_skips_excluded": self.overflow_skips,
            "open_anomaly_step": self.anomaly_step,
            "votes": self.votes,
            "dup_checks": self.dup_checks,
            "verdicts": [dict(v) for v in self.verdicts],
            "detection_latency_steps": {
                "mean": sum(lat) / len(lat) if lat else None,
                "max": max(lat) if lat else None,
                "last": lat[-1] if lat else None,
                "closed_verdicts": len(lat),
            },
            "sentinels": {
                n: {"mean": s.mean, "var": s.var, "count": s.count}
                for n, s in self.stats.items()},
        }
