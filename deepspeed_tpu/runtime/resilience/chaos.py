"""Fault-injection hooks for resilience testing.

A ``ChaosPlan`` armed via :func:`arm` lets tests kill a checkpoint write
mid-flight (after N leaf files, or at a named commit point), corrupt the
bytes of a just-written file, or poison gradients with NaN for a step
window — proving end-to-end that the atomic commit path and the watchdog
actually recover.  All hooks are no-ops when nothing is armed, so the
production code paths pay one ``is None`` check.

Never arm chaos outside tests.
"""
import os
import threading

from deepspeed_tpu.utils.logging import logger


class ChaosInterrupt(RuntimeError):
    """Simulated preemption: raised from inside a checkpoint write."""


class ChaosPlan:
    """Counters for one armed fault scenario (see :func:`arm`)."""

    def __init__(self, kill_after_files=None, kill_at_point=None,
                 corrupt_after_files=None, corrupt_nbytes=4,
                 nan_grad_steps=0, cancel_request_every=0,
                 preempt_after_steps=0, kill_serving_after_steps=0,
                 slow_serving_step_every=0, slow_serving_step_s=0.05,
                 poison_logits_at_step=0, burst_arrival_every=0,
                 burst_arrival_count=0, kill_replica_after_steps=0,
                 kill_replica=0, slow_replica_step_every=0,
                 slow_replica=0, slow_replica_step_s=0.05,
                 kill_ranks=(), fail_step_transient=0,
                 fail_step_transient_count=1, silence_heartbeat=None,
                 kill_once_at_point=None, flip_bits=(),
                 spike_loss_at_step=0, spike_loss_magnitude=64.0,
                 kill_process_ranks=()):
        self.kill_after_files = kill_after_files
        self.kill_at_point = kill_at_point
        self.kill_once_at_point = kill_once_at_point
        self.kill_ranks = tuple(tuple(p) for p in (kill_ranks or ()))
        self.kill_process_ranks = [tuple(p)
                                   for p in (kill_process_ranks or ())]
        self.fail_step_transient = fail_step_transient
        self.fail_step_transient_count = fail_step_transient_count
        self.silence_heartbeat = tuple(silence_heartbeat) \
            if silence_heartbeat else None
        self.corrupt_after_files = corrupt_after_files
        self.corrupt_nbytes = corrupt_nbytes
        self.nan_grad_steps = nan_grad_steps
        self.cancel_request_every = cancel_request_every
        self.preempt_after_steps = preempt_after_steps
        self.kill_serving_after_steps = kill_serving_after_steps
        self.slow_serving_step_every = slow_serving_step_every
        self.slow_serving_step_s = slow_serving_step_s
        self.poison_logits_at_step = poison_logits_at_step
        self.burst_arrival_every = burst_arrival_every
        self.burst_arrival_count = burst_arrival_count
        self.kill_replica_after_steps = kill_replica_after_steps
        self.kill_replica = kill_replica
        self.slow_replica_step_every = slow_replica_step_every
        self.slow_replica = slow_replica
        self.slow_replica_step_s = slow_replica_step_s
        # silent-corruption injectors (ISSUE 13): pending single-bit
        # flips as (target, rank, step, leaf, element, bit) tuples, and
        # the one-shot loss-spike window
        self.flip_bits = [tuple(f) for f in (flip_bits or ())]
        self.spike_loss_at_step = spike_loss_at_step
        self.spike_loss_magnitude = spike_loss_magnitude
        self.files_written = 0
        self.fired = []
        self._lock = threading.Lock()


_plan = None


def arm(**kwargs):
    """Arm a fault scenario.

    kill_after_files=N   raise ChaosInterrupt right after the Nth leaf file
                         of a checkpoint write lands (1-based).
    kill_at_point=NAME   raise ChaosInterrupt at a named commit point:
                         'before_manifest' | 'before_rename' | 'before_latest'.
    corrupt_after_files=N  flip bytes in the Nth written file (silent disk
                         corruption; the manifest checksum must catch it).
    nan_grad_steps=K     poison the gradient accumulator with NaN for the
                         next K optimizer steps (drives overflow/NaN streaks).
    cancel_request_every=N  have the serving scheduler cancel its youngest
                         running request every Nth step (request-churn
                         chaos for the continuous-batching engine).
    preempt_after_steps=N  deliver a graceful-preemption signal (the
                         SIGTERM analog) after N more optimizer steps:
                         the engine forces a synchronous emergency save
                         and raises GracefulPreemption.  Combine with
                         kill_at_point to model a hard kill landing
                         MID-preempt-save.
    kill_serving_after_steps=N  raise ChaosInterrupt MID-DECODE at serving
                         step N — after the decode dispatch, before any
                         host bookkeeping or journal commit: the host
                         crash the request journal must recover from.
    slow_serving_step_every=N, slow_serving_step_s=S  sleep S seconds in
                         every Nth serving step (wedged host / slow
                         device sim; the serving stall detector's food).
    poison_logits_at_step=N  inject NaN into the YOUNGEST running lane's
                         embedding at serving step N — its logits go
                         non-finite and the engine must quarantine that
                         request without touching its batch peers.
    burst_arrival_every=N, burst_arrival_count=K  release K extra request
                         arrivals every Nth serving step (thundering-herd
                         traffic; drivers query serving_burst()).
    kill_replica_after_steps=N, kill_replica=R  hard-down one FLEET
                         replica: raise ChaosInterrupt mid-decode on
                         EVERY step >= N of replica R (unlike the
                         one-shot kill_serving latch — a dead host fails
                         every retry, which is what the router's
                         circuit breaker must observe to mark it dead).
    slow_replica_step_every=N, slow_replica=R, slow_replica_step_s=S
                         sleep S seconds in every Nth step of fleet
                         replica R only (one wedged host in an otherwise
                         healthy fleet; feeds that replica's stall
                         detector without touching its peers).
    kill_ranks=((R, N), ...)  hard-down simulated TRAINING host R at
                         supervisor wall step N: it stops heartbeating
                         and stays down forever (a dead host fails every
                         retry — the supervisor's circuit breaker must
                         reach a coordinated dead verdict and restart
                         elastically on the survivors).  Multiple pairs
                         model chained failures (a second rank dying
                         during recovery from the first).
    fail_step_transient=N, fail_step_transient_count=K  raise a
                         TRANSIENT fault in the supervised step from
                         wall step N, for K consecutive attempts
                         (K=1: the first in-place retry succeeds —
                         no rollback; K > max_transient_retries:
                         the retry ladder exhausts and escalates to a
                         coordinated rollback).
    silence_heartbeat=(R, N, W)  simulated host R stops heartbeating for
                         W wall steps starting at step N WITHOUT dying —
                         a network partition / GC pause; shorter than
                         the heartbeat window it is honest downtime,
                         longer and the supervisor correctly declares
                         the unreachable host dead.
    kill_once_at_point=NAME  like kill_at_point but fires exactly once —
                         for killing a RECOVERY mid-flight (e.g.
                         'before_rollback_load' / 'before_restart_load')
                         while letting the supervisor's bounded retry
                         of that recovery then succeed.
    flip_bits=((target, rank, step, leaf, element, bit), ...)  pending
                         silent single-bit flips (usually armed via the
                         flip_bit()/corrupt_opt_state() helpers): flip
                         one bit of one element of one state leaf on ONE
                         dp rank's replica at a step boundary — finite-
                         but-wrong numbers the integrity sentinels and
                         cross-replica vote must catch (ISSUE 13).
    spike_loss_at_step=N, spike_loss_magnitude=M  one-shot PaLM-style
                         loss spike: the batch feeding step N is scaled
                         by M (anomalous data, symmetric across ranks —
                         rollback-and-skip territory, not quarantine).
    kill_process_ranks=((R, N), ...)  SIGKILL the REAL worker process
                         behind transport peer R at wall step N (the
                         ProcessTransport heartbeat tick consults this
                         and delivers kill(2) for real — nothing
                         simulated about the death or the verdict that
                         follows; the in-process transport's analog is
                         kill_ranks).  Each pair fires once.
    """
    global _plan
    _plan = ChaosPlan(**kwargs)
    return _plan


def disarm():
    global _plan
    _plan = None


def active():
    return _plan


def file_written(path):
    """Called by the atomic writer after each payload lands on disk.

    ``path`` may be a directory (the orbax backend writes a sharded tree);
    corruption then hits the largest file inside it.
    """
    if _plan is None:
        return
    with _plan._lock:
        _plan.files_written += 1
        n = _plan.files_written
    if _plan.corrupt_after_files is not None and n == _plan.corrupt_after_files:
        target = path
        if os.path.isdir(path):
            inner = [os.path.join(root, name)
                     for root, _dirs, names in os.walk(path)
                     for name in names]
            target = max(inner, key=os.path.getsize, default=None)
        if target is not None and os.path.isfile(target):
            corrupt_file(target, nbytes=_plan.corrupt_nbytes)
            _plan.fired.append(("corrupt", target))
        else:
            logger.warning(f"chaos: corrupt target {path} has no file; "
                           f"nothing corrupted")
    if _plan.kill_after_files is not None and n >= _plan.kill_after_files:
        _plan.fired.append(("kill_after_files", path))
        raise ChaosInterrupt(
            f"chaos: killed checkpoint write after {n} files ({path})")


# telemetry observers: called on chaos-relevant moments (commit points
# reached, injected faults firing) so armed tracers can drop instant
# events next to the spans they perturb.  Observers must be cheap,
# exception-free host work; they NEVER influence the chaos plan.
_observers = []


def add_observer(cb):
    """Register ``cb(kind, detail=None)``; returns cb (for removal)."""
    _observers.append(cb)
    return cb


def remove_observer(cb):
    try:
        _observers.remove(cb)
    except ValueError:
        pass


def _notify(kind, detail=None):
    for cb in _observers:
        cb(kind, detail)


def point(name):
    """Called by the atomic writer (and the supervisor's recovery paths)
    at named commit points."""
    _notify(f"point_{name}")
    if _plan is not None and _plan.kill_once_at_point == name:
        _plan.kill_once_at_point = None     # one-shot: the retry survives
        _plan.fired.append(("kill_once_at_point", name))
        raise ChaosInterrupt(f"chaos: one-shot kill at {name!r}")
    if _plan is not None and _plan.kill_at_point == name:
        _plan.fired.append(("kill_at_point", name))
        raise ChaosInterrupt(f"chaos: killed checkpoint commit at {name!r}")


def rank_dead(rank, step_index):
    """True when an armed ``kill_ranks`` plan has simulated host ``rank``
    hard-down at supervisor wall step ``step_index``.  Monotone: once a
    host's kill step passes it is dead on every later query (a downed
    host fails every retry — that is what distinguishes lost capacity
    from a transient fault)."""
    if _plan is None or not _plan.kill_ranks:
        return False
    for r, s in _plan.kill_ranks:
        if r == rank and step_index >= s:
            with _plan._lock:
                if ("kill_rank", (r, s)) not in _plan.fired:
                    _plan.fired.append(("kill_rank", (r, s)))
            _notify("kill_rank", rank)
            return True
    return False


def process_kill_due(rank, step_index):
    """One-shot query: True when an armed ``kill_process_ranks`` plan
    wants transport peer ``rank``'s REAL process SIGKILLed at/after
    wall step ``step_index``.  Consumes the pair — the kill itself is
    permanent (a killed process stays dead without chaos re-firing),
    so unlike ``rank_dead`` this is not re-queried every tick."""
    if _plan is None or not _plan.kill_process_ranks:
        return False
    with _plan._lock:
        for i, (r, s) in enumerate(_plan.kill_process_ranks):
            if r == rank and step_index >= s:
                del _plan.kill_process_ranks[i]
                _plan.fired.append(("kill_process", (r, s)))
                break
        else:
            return False
    _notify("kill_process", rank)
    return True


def heartbeat_silenced(rank, step_index):
    """True while an armed ``silence_heartbeat=(rank, start, window)``
    plan keeps simulated host ``rank`` mute (alive but unreachable)."""
    if _plan is None or _plan.silence_heartbeat is None:
        return False
    r, start, window = _plan.silence_heartbeat
    if rank != r or not (start <= step_index < start + window):
        return False
    with _plan._lock:
        _plan.fired.append(("silence_heartbeat", (rank, step_index)))
    return True


def consume_transient_fault(step_index):
    """One transient supervised-step fault; True while the armed budget
    lasts at/after the armed wall step.  Each True consumes one unit of
    ``fail_step_transient_count``, so retries genuinely re-attempt: a
    count of 1 fails once and the in-place retry succeeds, a count
    above the supervisor's retry ladder escalates to rollback."""
    if _plan is None or not _plan.fail_step_transient:
        return False
    if step_index < _plan.fail_step_transient \
            or _plan.fail_step_transient_count <= 0:
        return False
    with _plan._lock:
        _plan.fail_step_transient_count -= 1
        _plan.fired.append(("fail_step_transient", step_index))
    _notify("fail_step_transient", step_index)
    return True


def serving_cancel_request(step_index):
    """True when an armed plan wants the serving scheduler to cancel a
    running request at this (1-based) scheduler step — the request-churn
    analog of nan_grad_steps, driven through the user-facing cancel path
    (deepspeed_tpu/serving/scheduler.py::Scheduler.chaos_cancel).  Pure
    query: the scheduler records via record_serving_cancel only when a
    victim actually exists, so ``fired`` audits real cancellations."""
    if _plan is None or not _plan.cancel_request_every:
        return False
    return step_index % _plan.cancel_request_every == 0


def record_serving_cancel(rid):
    """Audit one ACTUAL chaos-driven request cancellation."""
    _notify("cancel_request", rid)
    if _plan is not None:
        with _plan._lock:
            _plan.fired.append(("cancel_request", rid))


def serving_kill_step(step_index):
    """Kill-mid-decode: raises ChaosInterrupt the first time the serving
    engine reaches an armed step — called AFTER the decode dispatch and
    BEFORE host bookkeeping, so the step's tokens are lost exactly like
    a real host crash (the journal holds state as of the last commit)."""
    if _plan is None or not _plan.kill_serving_after_steps:
        return
    if step_index < _plan.kill_serving_after_steps:
        return
    with _plan._lock:
        if any(kind == "kill_serving" for kind, _ in _plan.fired):
            return
        _plan.fired.append(("kill_serving", step_index))
    raise ChaosInterrupt(
        f"chaos: killed serving host mid-decode at step {step_index}")


def serving_slow_step_s(step_index):
    """Seconds to stall this serving step (0.0 = no fault armed)."""
    if _plan is None or not _plan.slow_serving_step_every:
        return 0.0
    if step_index % _plan.slow_serving_step_every:
        return 0.0
    with _plan._lock:
        _plan.fired.append(("slow_serving_step", step_index))
    return _plan.slow_serving_step_s


def serving_poison_step(step_index):
    """True when an armed plan wants NaN injected into one decode lane
    at this serving step (the engine picks the youngest running request
    as the deterministic victim and must quarantine it)."""
    if _plan is None or not _plan.poison_logits_at_step:
        return False
    return step_index == _plan.poison_logits_at_step


def record_serving_poison(rid):
    """Audit one ACTUAL poison injection (a victim lane existed)."""
    _notify("poison_logits", rid)
    if _plan is not None:
        with _plan._lock:
            _plan.fired.append(("poison_logits", rid))


def serving_burst(step_index):
    """Extra request arrivals to release at this serving step — traffic
    drivers (tools/serve_bench.py, tests) query it so thundering-herd
    bursts run through the same arming/audit machinery as every other
    fault."""
    if _plan is None or not _plan.burst_arrival_every:
        return 0
    if step_index % _plan.burst_arrival_every:
        return 0
    with _plan._lock:
        _plan.fired.append(("burst_arrival", step_index))
    return _plan.burst_arrival_count


def fleet_kill_replica_step(replica_index, step_index):
    """Hard-down replica simulation: raises ChaosInterrupt MID-DECODE
    (after the dispatch, before any host bookkeeping — the same crash
    point as ``serving_kill_step``) on EVERY step >= N of the armed
    replica.  Unlike the single-engine kill's one-shot latch, a downed
    host keeps failing, so the fleet router's bounded retry/backoff
    exhausts its circuit breaker and marks the replica dead.  No-op for
    other replicas and for engines that are not fleet-tagged
    (``replica_index is None``)."""
    if _plan is None or not _plan.kill_replica_after_steps \
            or replica_index is None:
        return
    if replica_index != _plan.kill_replica \
            or step_index < _plan.kill_replica_after_steps:
        return
    with _plan._lock:
        _plan.fired.append(("kill_replica", (replica_index, step_index)))
    _notify("kill_replica", replica_index)
    raise ChaosInterrupt(
        f"chaos: fleet replica {replica_index} killed mid-decode at "
        f"step {step_index}")


def fleet_slow_replica_s(replica_index, step_index):
    """Seconds to stall this step of ONE fleet replica (0.0 = not this
    replica / nothing armed) — the per-replica analog of
    ``serving_slow_step_s`` that lets a fleet test wedge a single host
    while its peers keep serving."""
    if _plan is None or not _plan.slow_replica_step_every \
            or replica_index is None:
        return 0.0
    if replica_index != _plan.slow_replica:
        return 0.0
    if step_index % _plan.slow_replica_step_every:
        return 0.0
    with _plan._lock:
        _plan.fired.append(("slow_replica", (replica_index, step_index)))
    _notify("slow_replica", replica_index)
    return _plan.slow_replica_step_s


def consume_preempt_step():
    """One optimizer step toward an armed graceful preemption; True on
    the step the budget exhausts — the engine must then run its preempt
    checkpoint and raise GracefulPreemption.  Fires once; the engine
    latches its own request flag (a real SIGTERM does not un-deliver
    itself), so repeated polls need no chaos state."""
    if _plan is None or _plan.preempt_after_steps <= 0:
        return False
    with _plan._lock:
        _plan.preempt_after_steps -= 1
        if _plan.preempt_after_steps > 0:
            return False
        _plan.preempt_after_steps = 0
        if not any(kind == "preempt" for kind, _ in _plan.fired):
            _plan.fired.append(("preempt", None))
    return True


def preempt_then_resume(run_fn, resume_fn, preempt_after_steps,
                        kill_at_point=None, **extra_arm):
    """Scenario driver: graceful-preempt a training run, then restart it
    (typically on a SMALLER mesh) — the elastic analog of PR 1's
    kill-mid-write chaos tests.

    ``run_fn()`` drives training until the armed preemption interrupts
    it (GracefulPreemption after the forced save; ChaosInterrupt when
    ``kill_at_point`` models a hard kill landing mid-save).  Chaos is
    disarmed, then ``resume_fn()`` builds the restart-world engine and
    resumes.  Returns ``(resume_result, interrupt)`` so the test can
    assert both the landing checkpoint and the interrupt kind.
    """
    from deepspeed_tpu.runtime.resilience.watchdog import GracefulPreemption

    arm(preempt_after_steps=preempt_after_steps,
        kill_at_point=kill_at_point, **extra_arm)
    interrupt = None
    try:
        run_fn()
        raise AssertionError(
            "chaos preempt scenario: run_fn returned without the armed "
            "preemption firing — not enough steps?")
    except (GracefulPreemption, ChaosInterrupt) as e:
        interrupt = e
    finally:
        disarm()
    return resume_fn(), interrupt


def consume_nan_grad_step():
    """One poisoned optimizer step; returns True while the budget lasts."""
    if _plan is None or _plan.nan_grad_steps <= 0:
        return False
    _plan.nan_grad_steps -= 1
    _plan.fired.append(("nan_grads", _plan.nan_grad_steps))
    return True


def flip_bit(rank, step, leaf=0, element=0, bit=30, target="params"):
    """Arm a SINGLE-BIT flip in dp rank ``rank``'s replica of one state
    leaf, applied at the step-``step`` boundary (after that step's
    optimizer update commits) — the silent-data-corruption injector of
    ISSUE 13.  The flipped replica stays finite, so nothing in the
    NaN/overflow machinery fires: only the integrity sentinels (z-score
    on loss/grad-norm/update-ratio) and the cross-replica checksum vote
    can see it.  ``leaf`` indexes ``state.params`` (or ``state.
    opt_state`` with ``target="opt"``) in flatten order; ``element`` is
    the flat element, ``bit`` the fp32 word bit (default 30, the top
    exponent bit — clear on any weight with |w| < 1, so the flip
    inflates it by ~2^124: loud but finite).  Composes with an already-armed plan, or
    arms a fresh one."""
    plan = _plan if _plan is not None else arm()
    with plan._lock:
        plan.flip_bits.append((str(target), int(rank), int(step),
                               int(leaf), int(element), int(bit)))
    return plan


def corrupt_opt_state(rank, step, leaf=0, element=0, bit=30):
    """Arm a single-bit flip in one OPTIMIZER-STATE leaf on dp rank
    ``rank`` (applied at the step-``step`` boundary).  Physics note:
    under ZeRO sharding the optimizer shard has no replica — the
    corruption propagates symmetrically through the parameter exchange,
    so it is caught by the sentinels (and rolled back), not attributed
    to a rank by the vote.  That asymmetry is exactly what the e2e
    tests pin."""
    return flip_bit(rank, step, leaf=leaf, element=element, bit=bit,
                    target="opt")


def spike_loss(step, magnitude=64.0):
    """Arm a one-shot PaLM-style loss spike: the batch that feeds
    optimizer step ``step`` has its float features scaled by
    ``magnitude`` (anomalous DATA, not a rank fault) — losses and
    gradients spike finite-but-wrong on EVERY rank, the cross-replica
    vote stays unanimous, and the correct response is rollback plus
    skipping the offending data window."""
    plan = _plan if _plan is not None else arm()
    plan.spike_loss_at_step = int(step)
    plan.spike_loss_magnitude = float(magnitude)
    return plan


def consume_bit_flips(step_index):
    """Pending bit flips due at/before this completed optimizer step, as
    ``(target, rank, leaf, element, bit)`` tuples; each fires once."""
    if _plan is None or not _plan.flip_bits:
        return []
    due = []
    with _plan._lock:
        rest = []
        for target, rank, step, leaf, element, bit in _plan.flip_bits:
            if step_index >= step:
                due.append((target, rank, leaf, element, bit))
                _plan.fired.append(("flip_bit",
                                    (target, rank, step, leaf, element,
                                     bit)))
            else:
                rest.append((target, rank, step, leaf, element, bit))
        _plan.flip_bits = rest
    for f in due:
        _notify("flip_bit", f)
    return due


def maybe_spike_batch(batch, next_step):
    """Scale the batch feeding optimizer step ``next_step`` when a
    ``spike_loss`` plan is armed for it (one-shot).  Host-side, float
    arrays only — integer ids/labels pass through untouched."""
    if _plan is None or not _plan.spike_loss_at_step \
            or next_step != _plan.spike_loss_at_step:
        return batch
    with _plan._lock:
        if any(kind == "spike_loss" for kind, _ in _plan.fired):
            return batch
        _plan.fired.append(("spike_loss", next_step))
    _notify("spike_loss", next_step)
    import numpy as np

    mag = _plan.spike_loss_magnitude

    def scale(x):
        a = np.asarray(x)
        if np.issubdtype(a.dtype, np.floating):
            return a * a.dtype.type(mag)
        return x

    logger.warning(f"chaos: spiked the batch feeding step {next_step} "
                   f"by x{mag:g} (finite anomalous data)")
    if isinstance(batch, dict):
        return {k: scale(v) for k, v in batch.items()}
    return scale(batch)


def corrupt_file(path, offset=0, nbytes=4):
    """Flip ``nbytes`` bytes of ``path`` in place (silent bit rot)."""
    # intentional corruption — the write the manifest checksums must catch
    with open(path, "r+b") as f:  # graftlint: disable=raw-ckpt-write
        f.seek(offset)
        chunk = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))
    logger.warning(f"chaos: corrupted {nbytes} bytes of {path} at {offset}")


def truncate_file(path, keep_bytes=0):
    """Truncate ``path`` to ``keep_bytes`` (partial write / torn page)."""
    # intentional torn-page injection; size check must catch it
    with open(path, "r+b") as f:  # graftlint: disable=raw-ckpt-write
        f.truncate(keep_bytes)
    logger.warning(f"chaos: truncated {path} to {keep_bytes} bytes")
