"""Topology-elastic checkpoint resharding: save once, resume on any mesh.

The npz checkpoint families already store topology-independent payloads
(full unsharded leaves for the base engine, layer-keyed files for the
pipeline engine), so in principle any mesh can load them.  This module
makes that guarantee EXPLICIT and verified instead of accidental:

- every checkpoint carries a **topology manifest** (mesh axis sizes,
  zero/dp/pipe/virtual-stage degrees, per-leaf partition specs, schedule
  + stash config, global-batch shape) and a **data position** (exact
  global sample offset), both in the human-readable tag manifest
  (``manifest.json``) and in the pickled load metadata;
- ``load_checkpoint(..., elastic=True)`` builds a :class:`ReshardPlan`
  from the saved manifest against the LIVE engine: which axes reshard
  (optimizer leaves re-partitioned along the new zero axis, pipeline
  chunks remapped through ``PipelineParallelGrid.chunk_owner_stage``),
  and which schedule features are DROPPED by the new topology (zb-stash
  -> 1f1b, interleaved -> classic) — dropped features warn with the
  repo's DISARMED discipline, naming exactly what was lost;
- the data position lets a resumed run continue at the exact sample
  offset (:func:`micro_batches_to_skip` / :func:`fast_forward`), so a
  preempted run neither replays nor skips samples.

Elastic config selection on resume reuses ``compute_elastic_config``
(deepspeed_tpu/elasticity): a run restarted on a shrunken world keeps
the SAME global batch with a re-derived micro-batch/gas pair, so the
loss trajectory is unchanged — a placement-spec change in the sense of
PAPERS.md 2601.02311, not a new training run.
"""
import logging

from deepspeed_tpu.utils.logging import log_dist, logger

# manifest.json / metadata.pkl keys (shared with atomic.read_topology)
TOPOLOGY_KEY = "topology"
DATA_POSITION_KEY = "data_position"

# schedule features lost when a checkpoint written under a richer
# schedule resumes under a plainer one (the downgrade axis of an elastic
# load); keys match runtime/pipe/schedule.py's schedule names
_SCHEDULE_FEATURES = {
    "1f1b": (),
    "interleaved": ("virtual-stage interleaving",),
    "zb-h1": ("zero-bubble wgrad deferral",),
}


class ElasticReshardError(RuntimeError):
    """An elastic load that cannot be satisfied on the current mesh."""


# ---------------------------------------------------------------------------
# manifest construction (save side)
# ---------------------------------------------------------------------------

def partition_specs(engine):
    """Per-leaf partition specs of the engine's live sharding tree, as
    ``{tree_path: spec_string}`` — the zero-axis layout the writing mesh
    used.  None before the state is built (saves always build first)."""
    import jax

    sh = getattr(engine, "_shardings", None)
    if sh is None:
        return None
    out = {}
    for p, s in jax.tree_util.tree_flatten_with_path(sh)[0]:
        spec = getattr(s, "spec", s)
        out[jax.tree_util.keystr(p)] = str(spec)
    return out


def topology_manifest(engine):
    """The writing mesh's identity card, stored with every checkpoint.

    Everything an elastic load needs to know what it is resharding FROM:
    mesh axis sizes, parallel degrees, the zero partition layout, the
    pipeline chunk grid + schedule/stash config, and the global-batch
    shape that must be preserved across a resize."""
    topo = {
        "engine": type(engine).__name__,
        "mesh": {str(a): int(s) for a, s in dict(engine.mesh.shape).items()},
        "dp": int(engine.dp_world_size),
        "mp": int(engine.mp_world_size),
        "sp": int(engine.sp_world_size),
        "zero_stage": int(engine.zero_optimization_stage()),
        "global_batch": {
            "train_batch_size": int(engine.train_batch_size()),
            "micro_batch_per_gpu": int(engine.train_micro_batch_size_per_gpu()),
            "gradient_accumulation_steps":
                int(engine.gradient_accumulation_steps()),
        },
    }
    if hasattr(engine, "num_stages"):  # PipelineEngine
        from deepspeed_tpu.runtime.constants import (PIPELINE_STASH_BUDGET)

        pipe = {
            "num_stages": int(engine.num_stages),
            "virtual_stages": int(engine.virtual_stages),
            "num_chunks": int(engine.num_chunks),
            "schedule": engine.pipe_schedule,
            "requested_schedule": engine.requested_schedule,
            "stash_armed": bool(engine._stash_armed),
            "stash_budget": int(engine._config.pipeline[PIPELINE_STASH_BUDGET]),
            "partition": [int(b) for b in
                          engine.module.partition_layers(engine.num_chunks)],
        }
        pipe["chunk_owner_stage"] = [
            int(engine.grid.chunk_owner_stage(q))
            for q in range(engine.num_chunks)]
        topo["pipe"] = pipe
    else:
        specs = partition_specs(engine)
        if specs is not None:
            topo["partition_specs"] = specs
    opt = getattr(engine, "optimizer", None)
    if getattr(opt, "axis_name", None) is not None:
        # 1-bit/0-1 wire state: error-feedback residuals and the local
        # accumulator carry a leading per-device (axis_size,) dim — a
        # dp-change load cannot remap old per-device error memories, so
        # the manifest records what was written and the load side resets
        # them (DISARM-warning) when the axis changed.  The freeze /
        # local-step phase needs no flag here: it re-derives purely from
        # the restored step counters (zeroone_cadence, _onebit_frozen).
        comp = {"optimizer": getattr(opt, "name", type(opt).__name__),
                "axis_name": str(opt.axis_name),
                "axis_size": int(getattr(opt, "axis_size", 0) or 0)}
        for k in ("freeze_step", "var_freeze_step", "local_steps",
                  "local_step_scaler", "local_step_clipper", "bits"):
            if hasattr(opt, k):
                comp[k] = int(getattr(opt, k))
        topo["compression"] = comp
    return topo


def data_position(engine):
    """Exact position in the global sample stream: enough to fast-forward
    ANY loader shape (the offset is in samples, not batches, so a resumed
    run with a different micro-batch/dp split lands on the same sample).

    ``samples_skipped`` (ISSUE 13) biases the stream position past data
    windows the integrity ladder deliberately skipped (PaLM-style
    rollback-and-skip): the stream stands ``micro_steps`` worth of
    TRAINED samples plus every skipped sample past its start, and both
    numbers persist with the checkpoint so later rollbacks/resumes land
    on the true stream offset, not the trained-sample count."""
    mb = int(engine.train_micro_batch_size_per_gpu())
    dp = int(engine.dp_world_size)
    micro_steps = int(engine.micro_steps)
    skipped = int(getattr(engine, "samples_skipped", 0))
    return {
        "global_steps": int(engine.global_steps),
        "micro_steps": micro_steps,
        "micro_batch_per_gpu": mb,
        "dp_world_size": dp,
        "samples_skipped": skipped,
        "samples_consumed": micro_steps * mb * dp + skipped,
    }


# ---------------------------------------------------------------------------
# data-order resume (load side)
# ---------------------------------------------------------------------------

def micro_batches_to_skip(position, engine):
    """How many micro-batches of the CURRENT engine's shape cover the
    saved sample offset.  Raises when the offset does not land on a
    current micro-batch boundary — silently rounding would replay or
    skip samples, the exact bug this exists to prevent.  With the global
    batch preserved across the resize (compute_elastic_config), offsets
    are always whole optimizer steps and therefore always divide."""
    if position is None:
        return 0
    consumed = int(position.get("samples_consumed", 0))
    per_batch = int(engine.train_micro_batch_size_per_gpu()) \
        * int(engine.dp_world_size)
    if consumed % per_batch:
        raise ElasticReshardError(
            f"checkpoint consumed {consumed} samples, which is not a "
            f"multiple of the current micro_batch*dp = {per_batch} — the "
            f"data stream cannot resume on a batch boundary. Use an "
            f"elastic config (compute_elastic_config) so the global batch "
            f"divides evenly at every world size")
    return consumed // per_batch


def fast_forward(data_iter, position, engine):
    """Advance ``data_iter`` past the samples the checkpoint already
    consumed; returns the iterator (same object) positioned at the next
    unseen sample.  ``data_iter`` yields micro-batches of the CURRENT
    shape (micro_batch*dp rows, the train_batch contract)."""
    n = micro_batches_to_skip(position, engine)
    for i in range(n):
        try:
            next(data_iter)
        except StopIteration:
            # raise-don't-misalign: a bare StopIteration would be eaten
            # (or PEP 479-mangled) by generator-based training loops
            raise ElasticReshardError(
                f"data stream exhausted after {i} of {n} skip "
                f"micro-batches — the loader is shorter than the "
                f"checkpoint's {position['samples_consumed']}-sample "
                f"offset; resume with the run's full (repeating) data "
                f"stream") from None
    if n:
        log_dist(f"elastic resume: fast-forwarded data stream by {n} "
                 f"micro-batches ({position['samples_consumed']} samples)",
                 ranks=[0])
    return data_iter


# ---------------------------------------------------------------------------
# pipeline chunk remapping
# ---------------------------------------------------------------------------

def chunk_layer_ranges(partition):
    """[(lo, hi)) model-layer range per chunk from a partition boundary
    list (module.partition_layers output, length num_chunks+1)."""
    return [(int(partition[i]), int(partition[i + 1]))
            for i in range(len(partition) - 1)]


def chunk_remap(saved_pipe, grid, current_partition):
    """Per-layer remap from the saved chunk grid onto the current one.

    ``saved_pipe`` is the manifest's ``pipe`` section (num_stages,
    virtual_stages, partition); ``grid`` the live PipelineParallelGrid;
    ``current_partition`` the live module's chunk boundaries.  Returns a
    list of ``{layer, saved_chunk, saved_stage, chunk, stage}`` — the
    explicit statement that layer L, written by saved chunk q_s on saved
    stage ``q_s % S_old``, is now owned by current chunk q on stage
    ``grid.chunk_owner_stage(q)``.  Raises when the two grids do not
    cover the same model."""
    saved_ranges = chunk_layer_ranges(saved_pipe["partition"])
    cur_ranges = chunk_layer_ranges(current_partition)
    n_saved = saved_ranges[-1][1] if saved_ranges else 0
    n_cur = cur_ranges[-1][1] if cur_ranges else 0
    if n_saved != n_cur:
        raise ElasticReshardError(
            f"checkpoint partitions {n_saved} model layers but the current "
            f"module has {n_cur} — elastic resharding remaps the same "
            f"model across meshes, it cannot change the model")
    saved_stages = int(saved_pipe["num_stages"])

    def owner(ranges, layer):
        for q, (lo, hi) in enumerate(ranges):
            if lo <= layer < hi:
                return q
        raise ElasticReshardError(
            f"layer {layer} not covered by chunk partition {ranges}")

    remap = []
    for layer in range(n_cur):
        q_saved = owner(saved_ranges, layer)
        q_cur = owner(cur_ranges, layer)
        remap.append({
            "layer": layer,
            "saved_chunk": q_saved,
            "saved_stage": q_saved % saved_stages,
            "chunk": q_cur,
            "stage": int(grid.chunk_owner_stage(q_cur)),
        })
    return remap


# ---------------------------------------------------------------------------
# elastic plan + reporting
# ---------------------------------------------------------------------------

def schedule_features(schedule, stash_armed=False):
    """Human-readable feature set a (schedule, stash) pair provides."""
    feats = list(_SCHEDULE_FEATURES.get(schedule, ()))
    if stash_armed:
        feats.append("bounded activation stashing")
    return feats


def plan_elastic_load(saved_topo, engine):
    """Diff the saved topology manifest against the live engine.

    Returns a plain dict (JSON-able, lands in the returned client_state):

    - ``changed``: {axis: (saved, current)} for every differing degree;
    - ``resharded``: human-readable actions the load performs (zero-axis
      repartition, chunk remap, ...);
    - ``dropped`` / ``gained``: schedule features lost/won by the move
      (dropped features DISARM-warn in :func:`log_plan`);
    - ``layers_moved``: pipeline layers whose owning stage changed;
    - ``notes``: everything else worth surfacing.
    """
    plan = {"changed": {}, "resharded": [], "dropped": [], "gained": [],
            "layers_moved": 0, "notes": []}
    if saved_topo is None:
        plan["notes"].append(
            "checkpoint carries no topology manifest (pre-elastic "
            "layout); resharding based on the live engine only")
        return plan

    for axis in ("dp", "mp", "sp", "zero_stage"):
        saved = saved_topo.get(axis)
        cur = {"dp": engine.dp_world_size, "mp": engine.mp_world_size,
               "sp": engine.sp_world_size,
               "zero_stage": engine.zero_optimization_stage()}[axis]
        if saved is not None and int(saved) != int(cur):
            plan["changed"][axis] = (int(saved), int(cur))
    if "dp" in plan["changed"] or "zero_stage" in plan["changed"]:
        s_dp = saved_topo.get("dp")
        if int(saved_topo.get("zero_stage") or 0) > 0 \
                or engine.zero_optimization_stage() > 0:
            plan["resharded"].append(
                f"optimizer-state leaves re-partitioned along the zero "
                f"axis (dp {s_dp} -> {engine.dp_world_size}, zero stage "
                f"{saved_topo.get('zero_stage')} -> "
                f"{engine.zero_optimization_stage()})")
        else:
            plan["resharded"].append(
                f"data-parallel degree changed (dp {s_dp} -> "
                f"{engine.dp_world_size}); replicated state re-placed on "
                f"the new mesh")

    saved_comp = saved_topo.get("compression")
    if saved_comp is not None and "dp" in plan["changed"]:
        plan["resharded"].append(
            f"per-device {saved_comp.get('optimizer')} compression state "
            f"(error-feedback residuals, local accumulator) written at "
            f"axis_size={saved_comp.get('axis_size')} reset to zero on "
            f"the new data axis; freeze/local-step phase re-derived from "
            f"the restored step counters")

    saved_pipe = saved_topo.get("pipe")
    if saved_pipe is not None and hasattr(engine, "num_stages"):
        cur_grid = (engine.num_stages, engine.virtual_stages)
        saved_grid = (int(saved_pipe["num_stages"]),
                      int(saved_pipe["virtual_stages"]))
        if saved_grid[0] != cur_grid[0]:
            plan["changed"]["pipe"] = (saved_grid[0], cur_grid[0])
        if saved_grid[1] != cur_grid[1]:
            plan["changed"]["virtual_stages"] = (saved_grid[1],
                                                 cur_grid[1])
        remap = chunk_remap(
            saved_pipe, engine.grid,
            engine.module.partition_layers(engine.num_chunks))
        moved = sum(1 for r in remap if r["saved_stage"] != r["stage"])
        plan["layers_moved"] = moved
        if moved:
            plan["resharded"].append(
                f"{moved}/{len(remap)} pipeline layers remapped to new "
                f"owner stages through chunk_owner_stage "
                f"({saved_grid[0]}x{saved_grid[1]} -> "
                f"{cur_grid[0]}x{cur_grid[1]} chunk grid)")
        saved_feats = set(schedule_features(
            saved_pipe.get("schedule"), saved_pipe.get("stash_armed")))
        cur_feats = set(schedule_features(
            engine.pipe_schedule, engine._stash_armed))
        plan["dropped"] = sorted(saved_feats - cur_feats)
        plan["gained"] = sorted(cur_feats - saved_feats)
        if plan["dropped"] or plan["gained"]:
            plan["notes"].append(
                f"schedule {saved_pipe.get('schedule')}"
                f"{' + stash' if saved_pipe.get('stash_armed') else ''}"
                f" -> {engine.pipe_schedule}"
                f"{' + stash' if engine._stash_armed else ''}")
    elif saved_pipe is not None:
        plan["notes"].append(
            "checkpoint was written by a PipelineEngine; loading on the "
            "base engine ignores its chunk grid (layer files are "
            "stage-independent)")

    saved_gb = (saved_topo.get("global_batch") or {}).get("train_batch_size")
    if saved_gb is not None:
        if int(saved_gb) == int(engine.train_batch_size()):
            if plan["changed"]:
                plan["notes"].append(
                    f"global batch preserved at {saved_gb} "
                    f"(micro/gas re-derived for the new world)")
        else:
            plan["notes"].append(
                f"GLOBAL BATCH CHANGED: {saved_gb} -> "
                f"{engine.train_batch_size()} — the loss trajectory will "
                f"diverge from the original run; use an elasticity config "
                f"so compute_elastic_config preserves it across resizes")
    return plan


def log_plan(plan):
    """Surface a reshard plan: resharding actions as info, dropped
    schedule features as a DISARMED warning naming exactly what was
    lost (the repo's armed-or-warns discipline)."""
    for line in plan["resharded"]:
        log_dist(f"elastic resume: {line}", ranks=[0])
    if plan["dropped"]:
        log_dist(
            f"elastic resume: schedule features DISARMED by the new "
            f"topology — dropped: {', '.join(plan['dropped'])}"
            + (f" ({'; '.join(plan['notes'])})" if plan["notes"] else ""),
            ranks=[0], level=logging.WARNING)
    if plan["gained"]:
        log_dist(f"elastic resume: schedule features gained: "
                 f"{', '.join(plan['gained'])}", ranks=[0])
    for note in plan["notes"]:
        if "GLOBAL BATCH CHANGED" in note:
            log_dist(f"elastic resume: {note}", ranks=[0],
                     level=logging.WARNING)
        elif not plan["dropped"]:
            log_dist(f"elastic resume: {note}", ranks=[0])


def elastic_batch_check(engine):
    """Consult compute_elastic_config for the CURRENT world and confirm
    the config's batch shape matches (the config computed it at init when
    elasticity is enabled).  Returns ``(final_batch, micro, gas)`` or
    None when no elasticity config is present."""
    pd = engine._config._param_dict
    from deepspeed_tpu.elasticity import (compute_elastic_config,
                                          elasticity_enabled)

    if not elasticity_enabled(pd):
        return None
    from deepspeed_tpu.version import __version__

    final, _valid, micro = compute_elastic_config(
        pd, __version__, world_size=int(engine.dp_world_size))
    gas = final // (micro * int(engine.dp_world_size))
    if (final, micro, gas) != (engine.train_batch_size(),
                               engine.train_micro_batch_size_per_gpu(),
                               engine.gradient_accumulation_steps()):
        raise ElasticReshardError(
            f"elastic config resolves to (batch={final}, micro={micro}, "
            f"gas={gas}) at world size {engine.dp_world_size} but the "
            f"engine is configured with "
            f"(batch={engine.train_batch_size()}, "
            f"micro={engine.train_micro_batch_size_per_gpu()}, "
            f"gas={engine.gradient_accumulation_steps()}) — the elastic "
            f"config is immutable once scheduled "
            f"(ensure_immutable_elastic_config)")
    return final, micro, gas


def elastic_load_report(meta, engine):
    """The load-side entry point both engines call under
    ``load_checkpoint(..., elastic=True)``: plan the reshard from the
    checkpoint metadata, log it (DISARMED warnings included), verify the
    elastic batch config, and return the JSON-able report that joins the
    returned client_state."""
    plan = plan_elastic_load(meta.get(TOPOLOGY_KEY), engine)
    log_plan(plan)
    resolved = elastic_batch_check(engine)
    if resolved is not None:
        plan["elastic_config"] = {
            "train_batch_size": int(resolved[0]),
            "micro_batch_per_gpu": int(resolved[1]),
            "gradient_accumulation_steps": int(resolved[2]),
        }
    position = meta.get(DATA_POSITION_KEY)
    if position is not None:
        plan[DATA_POSITION_KEY] = dict(position)
        try:
            plan["micro_batches_to_skip"] = micro_batches_to_skip(position,
                                                                  engine)
        except ElasticReshardError as e:
            # the STATE restore is still valid; only the exact-sample
            # data resume is not — surface it without failing the load
            # (auto-resume falling back to an older tag would not help:
            # the misalignment is a property of the new batch shape)
            plan["data_position_error"] = str(e)
            logger.warning(f"elastic resume: {e}")
    return plan
